"""Multi-chip serving smoke (ISSUE 14): the zero-to-aha proof that
TP-sharded serving survives chip loss, on 8 virtual CPU devices.

What it proves, end to end, in one run:

1. an mp=2-sharded fleet serves a ragged storm with byte-identical
   greedy output to the single-chip engine (sharding is a layout
   problem);
2. O(1) recompiles: the sharded storm misses each engine's compile
   cache at most twice (compile + optional remat);
3. kill one chip of one replica mid-decode: its flights fail over
   byte-identically, the replica re-shards onto the surviving mesh and
   rejoins the router — the storm completes byte-identical to the
   fault-free run and the rebuilt replica serves again.

Run: python scripts/multichip_serve_smoke.py   (wired into
scripts/verify.sh as its own stage). Exit 0 = all assertions green.
"""

import json
import os
import sys
import time

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from paddle_tpu.inference.decoding import (  # noqa: E402
    ContinuousBatchingEngine, GenerationConfig)
from paddle_tpu.models import llama as L  # noqa: E402
from paddle_tpu.observability.runtime import recompiles  # noqa: E402
from paddle_tpu.parallel.mesh import serving_mesh  # noqa: E402
from paddle_tpu.resilience import Fault, FaultInjector  # noqa: E402
from paddle_tpu.serving import (  # noqa: E402
    ElasticServingController, FleetRouter, HealthConfig, ReplicaHandle,
    RouterConfig, SchedulerConfig)

CFG = L.llama_tiny(num_hidden_layers=2)
MAX_NEW = 8


def _factories():
    def engine_factory(mesh):
        return ContinuousBatchingEngine(
            CFG, GenerationConfig(max_new_tokens=MAX_NEW, seed=0),
            num_slots=2, page_size=4, max_seq_len=64, chunk=2,
            prefix_cache=True, mesh=mesh)

    def handle_factory(rid, eng):
        return ReplicaHandle(
            rid, eng,
            config=SchedulerConfig(max_step_retries=1,
                                   retry_backoff_s=0.005),
            health_config=HealthConfig(suspect_after=1, eject_after=2,
                                       probe_cooldown_s=60.0))

    return engine_factory, handle_factory


def _fleet(injector=None):
    engine_factory, handle_factory = _factories()
    devs = jax.devices()
    handles = [handle_factory(i, engine_factory(
        serving_mesh(2, devs[2 * i:2 * i + 2]))) for i in range(2)]
    router = FleetRouter(handles,
                         config=RouterConfig(failover_backoff_s=0.005),
                         fault_injector=injector)
    ctl = ElasticServingController(router, engine_factory, handle_factory,
                                   fault_injector=injector)
    return router, ctl


def _storm(router, ctl, prompts, max_steps=20000):
    handles = [router.submit(p) for p in prompts]
    steps = 0
    while router.pending or ctl.resizing:
        ctl.step(PARAMS)
        steps += 1
        assert steps < max_steps, "storm did not converge"
    return handles


def main() -> int:
    global PARAMS
    PARAMS = L.init_stacked_params(CFG, seed=0)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, CFG.vocab_size,
                           (int(rng.randint(3, 12)),)).astype(np.int32)
               for _ in range(10)]

    # 1. single-chip reference (one plain engine, same seed)
    single = ContinuousBatchingEngine(
        CFG, GenerationConfig(max_new_tokens=MAX_NEW, seed=0),
        num_slots=2, page_size=4, max_seq_len=64, chunk=2,
        prefix_cache=True)
    ref = single.serve(PARAMS, prompts)

    # 2. mp=2 fleet, fault-free — byte-identical + O(1) recompiles
    u0 = recompiles.count("cbe.unified_step")
    router0, ctl0 = _fleet()
    h0 = _storm(router0, ctl0, prompts)
    fleet_out = {int(h.rid): h.stream.tokens for h in h0}
    assert [fleet_out[i] for i in range(len(prompts))] == ref, \
        "sharded fleet output diverged from the single-chip engine"
    misses = recompiles.count("cbe.unified_step") - u0
    assert misses <= 4, f"{misses} compile misses across 2 fresh engines"

    # 3. chip-kill storm: die mid-decode -> re-shard -> rejoin
    t0 = time.perf_counter()
    inj = FaultInjector(schedule=[Fault("chip_die", 4, replica=0, chip=1)])
    router, ctl = _fleet(injector=inj)
    h1 = _storm(router, ctl, prompts)
    wall = time.perf_counter() - t0
    got = {int(h.rid): h.stream.tokens for h in h1}
    assert [got[i] for i in range(len(prompts))] == ref, \
        "chip-kill storm output diverged from the fault-free run"
    assert not inj.schedule, "the scheduled chip_die never fired"
    assert len(ctl.resizes) == 1 and ctl.resizes[0].done
    rec = ctl.resizes[0]
    assert (rec.from_chips, rec.to_chips) == (2, 1)
    assert router.replicas[0].engine.num_chips == 1
    assert router.replicas[0].health.accepting, "replica did not rejoin"
    # the rebuilt replica actually serves again
    h2 = router.submit(prompts[0])
    while router.pending:
        ctl.step(PARAMS)
    assert h2.stream.tokens == ref[0]

    print(json.dumps({
        "smoke": "multichip_serve",
        "requests": len(prompts),
        "byte_identical": True,
        "compile_misses": misses,
        "resize": {"from_chips": rec.from_chips,
                   "to_chips": rec.to_chips,
                   "kind": rec.kind,
                   "flights_checkpointed": len(rec.flights),
                   "phases": [p for p, _ in rec.phases]},
        "failovers": sum(h.failovers for h in h1),
        "wall_s": round(wall, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
