"""Disaggregated serving + autoscaling smoke (ISSUE 19): the
zero-to-aha proof for the prefill/decode fleet, on CPU, in one run.

What it proves, end to end:

1. a 3-replica role fleet (2 PREFILL + 1 DECODE) serves a prompt storm;
   every finished prefill's KV pages hand off to the decode replica
   (wire round-trip, conservation audited after every import) and every
   stream is byte-identical to an all-HYBRID fleet given the same
   submissions;
2. an :class:`AutoscaleController` over the same fleet rides out a 10x
   prompt burst: overload evidence accumulates on the SignalBus, the
   fleet scales up through the engine/handle factories, and every
   decision lands as a versioned ScaleRecord with its input snapshot;
3. nothing leaks: zero live pages on every engine (including the
   scaled-up one) and the page books balance everywhere.

Run: python scripts/disagg_serve_smoke.py   (wired into
scripts/verify.sh as its own stage). Exit 0 = all assertions green.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from paddle_tpu.models import llama as L  # noqa: E402
from paddle_tpu.inference.decoding import (  # noqa: E402
    ContinuousBatchingEngine, GenerationConfig)
from paddle_tpu.serving import (  # noqa: E402
    AutoscaleConfig, AutoscaleController, DisaggRouter, HealthConfig,
    ReplicaHandle, ReplicaRole, RouterConfig, SchedulerConfig)

MAX_NEW = 6
CFG = L.llama_tiny(num_hidden_layers=2)


class Clock:
    """Deterministic fleet clock; sleep() advances it."""

    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt

    def advance(self, dt):
        self.t += dt


def _fleet(n, roles=None):
    clock = Clock()
    engines = []

    def make_engine():
        eng = ContinuousBatchingEngine(
            CFG, GenerationConfig(max_new_tokens=MAX_NEW),
            num_slots=2, page_size=4, max_seq_len=32, chunk=2,
            prefix_cache=True)
        engines.append(eng)
        return eng

    def make_handle(rid, eng):
        return ReplicaHandle(
            rid, eng, config=SchedulerConfig(max_step_retries=1,
                                             retry_backoff_s=0.01),
            health_config=HealthConfig(),
            clock=clock, sleep=clock.sleep)

    replicas = [make_handle(i, make_engine()) for i in range(n)]
    router = DisaggRouter(replicas, roles=roles, config=RouterConfig(),
                          clock=clock, sleep=clock.sleep)
    return router, clock, engines, make_engine, make_handle


def _drive(router, clock, params, step=None, max_steps=2000):
    steps = 0
    while router.pending:
        (step or router.step)(params)
        clock.advance(0.05)
        steps += 1
        assert steps < max_steps, "storm did not converge"
    return steps


def main() -> int:
    t_start = time.perf_counter()
    params = L.init_stacked_params(CFG, seed=3)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, CFG.vocab_size,
                           (int(rng.randint(9, 13)),)).astype(np.int32)
               for _ in range(6)]

    # 1. role fleet vs all-hybrid reference: handoff is byte-exact
    disagg, clock, engines, _, _ = _fleet(
        3, roles={0: ReplicaRole.PREFILL, 1: ReplicaRole.PREFILL,
                  2: ReplicaRole.DECODE})
    hs = [disagg.submit(p) for p in prompts]
    _drive(disagg, clock, params)
    moved = [list(h.stream.tokens) for h in hs]
    assert all(h.state == "done" for h in hs)
    assert disagg.handoffs_ok >= len(prompts), disagg.statusz()["handoffs"]
    assert disagg.handoffs_failed == 0
    assert all(h.replica_id == 2 for h in hs), \
        "streams should finish on the decode replica"

    hybrid, clock2, engines2, _, _ = _fleet(3)
    href = [hybrid.submit(p) for p in prompts]
    _drive(hybrid, clock2, params)
    ref = [list(h.stream.tokens) for h in href]
    assert hybrid.handoffs_ok == 0
    assert moved == ref, "handoff diverged from the hybrid fleet"

    # 2. autoscaler vs a 10x burst: evidence -> scale_up through the
    # factories, every decision a versioned record
    (router, clock3, engines3, make_engine, make_handle) = _fleet(
        2, roles={0: ReplicaRole.PREFILL, 1: ReplicaRole.DECODE})
    ctl = AutoscaleController(
        router, make_engine, make_handle,
        config=AutoscaleConfig(min_replicas=2, max_replicas=4,
                               up_queue_depth=1.0, up_trend=-1e9,
                               evidence_rounds=2, cooldown_s=0.4),
        interval_s=0.1)
    burst = [rng.randint(1, CFG.vocab_size,
                         (int(rng.randint(9, 13)),)).astype(np.int32)
             for _ in range(12)]
    bh = [router.submit(p) for p in burst]
    _drive(router, clock3, params, step=ctl.step)
    assert all(h.state == "done" for h in bh)
    ups = [r for r in ctl.records
           if r.action == "scale_up" and r.state == "done"]
    assert ups, [r.as_dict() for r in ctl.records]
    assert len(router.replicas) > 2
    assert all(r.snapshot.get("schema_version") == 1
               for r in ctl.records)

    # 3. nothing leaks, anywhere
    for eng in engines + engines2 + engines3:
        eng.mgr.check_conservation()
        assert eng.mgr.num_live_pages == 0, "leaked live pages"

    print(json.dumps({
        "smoke": "disagg_serve",
        "requests": len(prompts) + len(burst),
        "byte_identical": True,
        "handoffs": {"ok": disagg.handoffs_ok,
                     "pages": disagg.handoff_pages_total},
        "autoscale": {"scale_ups": len(ups),
                      "replicas": len(router.replicas),
                      "decisions": [r.action for r in ctl.records]},
        "leaked_pages": 0,
        "wall_s": round(time.perf_counter() - t_start, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
