"""Multi-host serving smoke (ISSUE 17): the zero-to-aha proof that
serving survives losing an engine PROCESS, against two REAL processes.

What it proves, end to end, in one run:

1. two engine processes behind a ``HostFleetRouter`` (every frame a
   versioned, checksummed wire message over a real pipe) serve a ragged
   storm; completions are recorded as the fault-free reference;
2. live migration mid-decode: ``migrate_host`` drains a host WITH its
   KV pages — export at src, checksummed transfer, import into the
   sibling's prefix cache — and the continuation finishes
   byte-identically, having prefilled only the un-migrated tail;
3. a seeded ``host_die`` (real SIGKILL) mid-decode: heartbeats stop,
   the health tracker walks SUSPECT -> EJECTED, every interrupted
   flight fails over and the storm completes byte-identical to the
   fault-free run with the fleet SLO un-breached and zero live pages
   left on the survivor.

Run: python scripts/multihost_serve_smoke.py   (wired into
scripts/verify.sh as its own stage). Exit 0 = all assertions green.
"""

import dataclasses
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from paddle_tpu.observability.format import validate_exposition_text  # noqa: E402
from paddle_tpu.observability.timeline import span_collector, timeline_armed  # noqa: E402
from paddle_tpu.resilience import FaultInjector  # noqa: E402
from paddle_tpu.serving import (  # noqa: E402
    HealthConfig, HostEndpoint, HostFleetRouter, HostHandle, PipeTransport,
    RouterConfig)

MAX_NEW = 10
VOCAB = 256          # prompt token range; well inside llama_tiny's vocab


def _spawn_host(i):
    tr = PipeTransport(factory_kwargs={"max_new_tokens": MAX_NEW,
                                       "max_seq_len": 48, "num_slots": 2},
                       host_id=i)
    ep = HostEndpoint(tr, timeout_s=300.0)
    return HostHandle(i, ep,
                      health_config=HealthConfig(suspect_after=1,
                                                 eject_after=2,
                                                 probe_cooldown_s=600.0))


def _drive(router, max_steps=5000, on_step=None):
    steps = 0
    while router.pending:
        router.step(None)
        steps += 1
        if on_step is not None:
            on_step(steps)
        assert steps < max_steps, "storm did not converge"
    return steps


def main() -> int:
    t_start = time.perf_counter()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, VOCAB,
                           (int(rng.randint(5, 11)),)).astype(np.int32)
               for _ in range(6)]

    hosts = [_spawn_host(i) for i in range(2)]
    router = HostFleetRouter(hosts, config=RouterConfig())
    monitor = router.make_slo_monitor(completion_target=0.99)
    try:
        # 1. fault-free reference storm over both processes
        refs = [router.submit(p) for p in prompts]
        _drive(router)
        ref = [list(h.stream.tokens) for h in refs]
        assert all(len(t) == MAX_NEW for t in ref)

        # 2. live migration mid-decode, pages included — under an ARMED
        # observability federation: every heartbeat ships a telemetry
        # frame back over the pipe, remote spans are skew-corrected into
        # the parent collector, and the migration must land in ONE
        # merged trace tree
        timeline_armed[0] = True
        router.federation.arm()
        router.step(None)         # prime: deliver arm=True to the hosts
        h = router.submit(prompts[0])
        for _ in range(4):
            router.step(None)
        src = h.replica_id
        mig = router.migrate_host(src)
        _drive(router)
        router.step(None)         # flush the final telemetry frames
        assert list(h.stream.tokens) == ref[0], \
            "migrated continuation diverged from the fault-free run"
        assert mig["requests"] == 1 and mig["failed"] == 0
        assert mig["pages"] >= 1 and mig["bytes"] > 0, mig
        router.undrain(src)

        # federated /metrics: ONE validator-clean exposition document
        # covering the parent and both engine processes
        fed_text = router.federation.federated_metrics_text()
        validate_exposition_text(fed_text)
        for lbl in ('host="parent"', 'host="h0"', 'host="h1"'):
            assert lbl in fed_text, f"federated doc is missing {lbl}"

        # merged cross-host trace: both hosts' spans in one tree, with
        # migration / dcn_transfer segments tiling the root envelope
        spans = span_collector.spans(h.trace_id)
        span_hosts = {s.args["host"] for s in spans
                      if s.args and "host" in s.args}
        assert span_hosts == {0, 1}, span_hosts
        tree = span_collector.tree(h.trace_id)
        assert len(tree) == 1, "expected ONE merged trace tree"
        att = span_collector.attribute(h.trace_id)
        segs = att["segments"]
        assert segs.get("migration", 0) > 0, segs
        assert segs.get("dcn_transfer", 0) > 0, segs
        tiling_err = abs(sum(segs.values()) - att["e2e_ms"])
        assert tiling_err <= 0.01 * att["e2e_ms"], (segs, att["e2e_ms"])
        mirrors = {hid: router.federation.mirror(hid) for hid in (0, 1)}
        assert all(m.frames > 0 and m.spans_merged > 0
                   for m in mirrors.values()), {
            hid: (m.frames, m.spans_merged) for hid, m in mirrors.items()}
        reconcile_ms = router.federation.reconcile_error_s() * 1e3

        # 3. seeded host death mid-decode (a real SIGKILL). seeded_hosts
        # schedules 1-based steps; rebase onto the router's live counter
        # so the kill lands a few steps into THIS storm.
        inj = FaultInjector.seeded_hosts(
            seed=23, num_steps=4, num_hosts=2, events=("host_die",))
        base = router._steps
        inj.schedule = [dataclasses.replace(f, step=f.step + base)
                        for f in inj.schedule]
        router.injector = inj
        storm = [router.submit(p) for p in prompts]
        _drive(router)
        assert inj.fired and inj.fired[0][0] == "host_die", inj.fired
        dead = inj.fired[0][2]
        got = [list(h.stream.tokens) for h in storm]
        assert got == ref, "host-kill storm diverged from fault-free run"
        assert not hosts[dead].endpoint.alive()
        assert hosts[1 - dead].endpoint.alive()
        failovers = sum(h.failovers for h in storm)

        # no SLO breach, nothing leaked, nothing unresolved
        assert monitor.health() == "ok", monitor.health()
        assert router.failed_total == 0 and router.shed_total == 0
        assert router.pending == 0 and router.parked == 0
        surv = hosts[1 - dead].statusz()["host"]
        assert surv["pages"]["live"] == 0, surv["pages"]
        assert surv["inflight"] == 0 and surv["queued"] == 0
        snap = router.multihost_snapshot()
        assert snap["migrations"], "migration timeline is empty"

        print(json.dumps({
            "smoke": "multihost_serve",
            "requests": len(prompts),
            "byte_identical": True,
            "migration": {"pages": mig["pages"], "bytes": mig["bytes"],
                          "skipped_pages": mig["skipped_pages"],
                          "ms": round(mig["seconds"] * 1e3, 3)},
            "federation": {
                "trace_hosts": sorted(span_hosts),
                "migration_segment_ms": round(segs["migration"], 3),
                "dcn_transfer_segment_ms": round(segs["dcn_transfer"], 3),
                "tiling_err_ms": round(tiling_err, 6),
                "reconcile_error_ms": round(reconcile_ms, 3),
                "frames": {f"h{hid}": m.frames
                           for hid, m in mirrors.items()}},
            "seeded_kill": {"host": dead, "step": inj.fired[0][1] - base},
            "failovers": failovers,
            "slo": monitor.health(),
            "survivor_live_pages": surv["pages"]["live"],
            "wall_s": round(time.perf_counter() - t_start, 3),
        }))
        return 0
    finally:
        router.close()


if __name__ == "__main__":
    sys.exit(main())
