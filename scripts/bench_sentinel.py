#!/usr/bin/env python
"""Perf-regression sentinel over the checked-in bench trajectory.

The repo keeps one ``BENCH_r<NN>.json`` per growth round (the driver's
record of ``bench.py``'s one-line JSON). This script is the noise-aware
gate ROADMAP item 1 requires before any fusion (or any other "perf"
change) is kept: it compares a FRESH bench line against the trajectory
with **MAD-banded thresholds** — the same robust statistics the anomaly
plane uses (``paddle_tpu.observability.anomaly``) — instead of a naive
"within X% of last round" rule that either pages on benchmark noise or
waves real regressions through, and **exits nonzero on a regression**.

Comparison model:

* trajectory entries group by ``(metric, unit)`` — rounds that measured
  a different workload (the r01 CPU smoke vs the later v5e MFU rounds)
  never band each other;
* for each watched numeric field shared by the fresh line and at least
  ``--min-history`` trajectory points, the band is
  ``median ± max(k · 1.4826 · MAD, rel_floor · |median|)`` — the MAD
  term adapts to each series' measured noise, the relative floor stops
  a freakishly quiet series from flagging micro-jitter;
* direction comes from the field: throughput-like fields regress LOW
  (``tokens_per_sec``, MFU ``value``), latency-like fields regress HIGH
  (``*_ms``, a ``ms``/``latency`` unit). A 2x ITL regression is a
  halved ``tokens_per_sec`` — exactly what the band catches.

Modes::

    # gate a fresh line (a bench's stdout JSON, or a BENCH_r*.json)
    python scripts/bench_sentinel.py --fresh /tmp/bench_line.json

    # CI self-check: every trajectory entry re-judged against the rest
    # (proves the checked-in history is self-consistent — verify.sh's
    # --sentinel stage)
    python scripts/bench_sentinel.py --replay

Output is ONE JSON line (``{"sentinel": ..., "pass": bool, ...}``);
exit 0 on pass, 1 on regression, 2 on usage/IO errors, 3 when a
``--fresh`` line had NO judgeable trajectory peers (renamed metric /
new platform — a vacuous pass would hide a regression; override with
``--allow-new-metric`` for a workload's first round). Entries stamped
with ``schema_version`` (``benchmarks/_telemetry.run_header``) are
trusted verbatim; unstamped legacy lines are compared best-effort and
noted in the report.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from paddle_tpu.observability.anomaly import MAD_SCALE, mad, median  # noqa: E402

#: watched fields -> direction ("high" = regresses when it drops,
#: "low" = regresses when it rises, "unit" = decide from the unit string)
FIELDS: Dict[str, str] = {
    "tokens_per_sec": "high",
    "tokens_per_s": "high",
    "value": "unit",
    "acceptance_rate": "high",
    "overhead_pct": "low",
    "ttft_p50_ms": "low",
    "itl_p50_ms": "low",
}

#: unit substrings that mark "value" as lower-is-better
_LOW_UNITS = ("ms", "latency", "seconds", "s/step", "pct", "%")


def field_direction(field: str, unit: str) -> str:
    d = FIELDS[field]
    if d != "unit":
        return d
    u = unit.lower()
    return "low" if any(t in u for t in _LOW_UNITS) else "high"


def load_entry(path: str) -> Dict[str, Any]:
    """One bench line: either the raw JSON object a benchmark printed,
    or a driver-shaped ``BENCH_r*.json`` whose ``parsed`` field holds
    it."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object")
    return doc


def group_key(entry: Dict[str, Any]) -> Tuple[str, str]:
    return (str(entry.get("metric", entry.get("bench", "?"))),
            str(entry.get("unit", "")))


def judge(fresh: Dict[str, Any], trajectory: List[Dict[str, Any]],
          band_k: float, rel_floor: float, min_history: int
          ) -> Dict[str, Any]:
    """Compare one fresh entry against its same-(metric, unit) peers.
    Returns the verdict document (``pass`` True when no watched field
    regressed; fields without enough history are reported, not judged)."""
    key = group_key(fresh)
    peers = [e for e in trajectory if group_key(e) == key]
    unit = str(fresh.get("unit", ""))
    checked: List[Dict[str, Any]] = []
    regressions: List[Dict[str, Any]] = []
    for field in sorted(FIELDS):
        if not isinstance(fresh.get(field), (int, float)):
            continue
        series = [float(e[field]) for e in peers
                  if isinstance(e.get(field), (int, float))]
        value = float(fresh[field])
        if len(series) < min_history:
            checked.append({"field": field, "value": value,
                            "history": len(series),
                            "verdict": "insufficient_history"})
            continue
        med = median(series)
        band = max(band_k * MAD_SCALE * mad(series, center=med),
                   rel_floor * abs(med))
        direction = field_direction(field, unit)
        if direction == "high":
            bad = value < med - band
            bound = med - band
        else:
            bad = value > med + band
            bound = med + band
        row = {"field": field, "value": value, "median": round(med, 4),
               "band": round(band, 4), "bound": round(bound, 4),
               "direction": direction, "history": len(series),
               "verdict": "regression" if bad else "ok"}
        checked.append(row)
        if bad:
            regressions.append(row)
    judged = sum(1 for row in checked
                 if row["verdict"] in ("ok", "regression"))
    return {"metric": key[0], "unit": key[1], "peers": len(peers),
            "schema_version": fresh.get("schema_version"),
            "judged": judged, "checked": checked,
            "regressions": regressions, "pass": not regressions}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python scripts/bench_sentinel.py",
        description="noise-aware perf-regression gate over the "
                    "BENCH_* trajectory")
    ap.add_argument("--fresh", help="path to the fresh bench JSON line "
                                    "('-' reads stdin)")
    ap.add_argument("--replay", action="store_true",
                    help="re-judge every trajectory entry against the "
                         "others (self-consistency gate)")
    ap.add_argument("--trajectory",
                    default=os.path.join(REPO_ROOT, "BENCH_r*.json"),
                    help="trajectory glob (default: repo BENCH_r*.json)")
    ap.add_argument("--band-k", type=float, default=4.0,
                    help="MAD band width in robust sigmas (default 4)")
    ap.add_argument("--rel-floor", type=float, default=0.05,
                    help="minimum band as a fraction of |median| "
                         "(default 0.05)")
    ap.add_argument("--min-history", type=int, default=2,
                    help="trajectory points needed before a field is "
                         "judged (default 2)")
    ap.add_argument("--allow-new-metric", action="store_true",
                    help="exit 0 even when the fresh line's (metric, "
                         "unit) has no judgeable trajectory peers "
                         "(first round of a renamed workload)")
    args = ap.parse_args(argv)
    if not args.replay and not args.fresh:
        ap.error("one of --fresh or --replay is required")

    paths = sorted(glob.glob(args.trajectory))
    trajectory: List[Dict[str, Any]] = []
    for p in paths:
        try:
            e = load_entry(p)
        except Exception as exc:
            print(json.dumps({"sentinel": "error", "path": p,
                              "error": repr(exc)}))
            return 2
        e["_path"] = p
        trajectory.append(e)

    if args.replay:
        results = []
        ok = True
        for e in trajectory:
            others = [o for o in trajectory if o is not e]
            v = judge(e, others, args.band_k, args.rel_floor,
                      args.min_history)
            v["entry"] = os.path.basename(e["_path"])
            results.append(v)
            ok = ok and v["pass"]
        print(json.dumps({"sentinel": "replay", "entries": len(results),
                          "results": results, "pass": ok}))
        return 0 if ok else 1

    try:
        if args.fresh == "-":
            fresh = json.loads(sys.stdin.read())
            if isinstance(fresh.get("parsed"), dict):
                fresh = fresh["parsed"]
        else:
            fresh = load_entry(args.fresh)
    except Exception as exc:
        print(json.dumps({"sentinel": "error", "path": args.fresh,
                          "error": repr(exc)}))
        return 2
    verdict = judge(fresh, trajectory, args.band_k, args.rel_floor,
                    args.min_history)
    verdict["sentinel"] = "fresh"
    if verdict["judged"] == 0 and not args.allow_new_metric:
        # a renamed metric / new platform suffix has no peers: passing
        # silently would make a regression indistinguishable from a
        # clean run — fail loudly (exit 3) unless explicitly allowed
        verdict["pass"] = False
        verdict["verdict"] = "no_comparable_history"
        print(json.dumps(verdict))
        return 3
    print(json.dumps(verdict))
    return 0 if verdict["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
