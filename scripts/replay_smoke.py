"""Black-box journal + postmortem replay smoke (ISSUE 20): every debug
bundle becomes a runnable incident, proven end to end on CPU.

What it proves, in one run:

1. a 4-replica chaos fleet (seeded replica death mid-decode + a stall)
   runs with the incident journal armed and the flight recorder set to
   auto-dump; the ejection produces a mid-incident bundle and the final
   manual dump captures the whole window — both embed ``journal.jsonl``
   and pass the bundle schema validator;
2. ``replay_bundle`` on the FINAL bundle rebuilds the fleet from the
   head frame, re-drives every journaled step/arrival/fault on a pinned
   clock and reproduces every stream byte-identically — zero leaked
   pages, page books balanced, no divergence;
3. the MID-INCIDENT (ejection) bundle replays as a clean prefix: replay
   completes the step that was in flight, observed frames extending
   past the journal are not a divergence;
4. a planted divergence — one flipped token in an ``outcome`` frame,
   re-signed so every line checksum stays valid — is localized to the
   exact (step, replica, component), not reported as a wall of diffs.

Run: python scripts/replay_smoke.py   (wired into scripts/verify.sh as
its own stage). Exit 0 = all assertions green.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from paddle_tpu.models import llama as L  # noqa: E402
from paddle_tpu.inference.decoding import (  # noqa: E402
    ContinuousBatchingEngine, GenerationConfig)
from paddle_tpu.observability.flight import (  # noqa: E402
    flight_recorder, validate_bundle)
from paddle_tpu.observability.journal import (  # noqa: E402
    decode_journal, encode_frames, journal, model_spec)
from paddle_tpu.observability.replay import replay_bundle  # noqa: E402
from paddle_tpu.resilience import Fault, FaultInjector  # noqa: E402
from paddle_tpu.serving import (  # noqa: E402
    FleetRouter, HealthConfig, ReplicaHandle, RouterConfig,
    SchedulerConfig)

MAX_NEW = 8
SEED = 3
CFG = L.llama_tiny(num_hidden_layers=2)


class Clock:
    """Deterministic fleet clock; sleep() advances it."""

    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt

    def advance(self, dt):
        self.t += dt


def _fleet(injector):
    params = L.init_stacked_params(CFG, seed=SEED)
    clock = Clock()
    replicas = []
    for i in range(4):
        eng = ContinuousBatchingEngine(
            CFG, GenerationConfig(max_new_tokens=MAX_NEW, seed=SEED),
            num_slots=2, page_size=4, max_seq_len=32, chunk=2)
        replicas.append(ReplicaHandle(
            i, eng,
            config=SchedulerConfig(max_step_retries=1,
                                   retry_backoff_s=0.01),
            health_config=HealthConfig(suspect_after=1, eject_after=2,
                                       probe_cooldown_s=0.4),
            clock=clock, sleep=clock.sleep))
    router = FleetRouter(
        replicas,
        config=RouterConfig(failover_backoff_s=0.05, stall_s=0.5),
        clock=clock, sleep=clock.sleep, fault_injector=injector)
    return params, router, clock


def run_incident(dump_dir):
    """The journaled chaos run; returns (streams, ejection bundle path,
    final bundle path)."""
    injector = FaultInjector(schedule=[
        Fault("replica_die", 3, replica=1),
        Fault("replica_stall", 5, replica=2),
    ])
    params, router, clock = _fleet(injector)
    rng = np.random.RandomState(31)
    prompts = [rng.randint(1, CFG.vocab_size,
                           (int(rng.randint(4, 9)),)).astype(np.int32)
               for _ in range(12)]
    submissions = {0: prompts[:8], 6: prompts[8:10], 16: prompts[10:]}

    flight_recorder.arm(dump_dir=dump_dir)
    journal.arm(capacity=8192)
    journal.record_head(model=model_spec(CFG, SEED),
                        fleet=router.journal_topology())
    try:
        handles, step = [], 0
        while step < 300:
            for p in submissions.pop(step, []):
                handles.append(router.submit(p))
            if not submissions and not router.pending:
                break
            router.step(params)
            clock.advance(0.05)
            step += 1
        assert step < 300, router.statusz()
        final = flight_recorder.dump_debug_bundle(reason="smoke_final")
    finally:
        journal.disarm()
        flight_recorder.disarm()
    streams = [list(h.stream.result()) for h in handles]
    assert all(len(s) == MAX_NEW for s in streams)
    ejection = os.path.join(
        dump_dir,
        [f for f in os.listdir(dump_dir) if "replica_ejected" in f][0])
    return streams, ejection, final


def main():
    with tempfile.TemporaryDirectory() as tmp:
        streams, ejection, final = run_incident(tmp)
        print(f"incident: 12 requests, {len(streams)} streams, "
              f"bundles: {os.path.basename(ejection)}, "
              f"{os.path.basename(final)}")

        # 1. both bundles pass the schema validator and carry a journal
        for path in (ejection, final):
            doc = validate_bundle(path)
            assert doc["journal"] is not None, path
            assert doc["manifest"]["schema_versions"], path
        print("bundle schema validation: OK")

        # 2. the final bundle replays byte-identically and leaks nothing
        rep = replay_bundle(final)
        assert rep.refused is None, rep.refused
        assert rep.divergence is None, rep.divergence
        assert rep.pending == 0 and rep.leaked_pages == 0, rep.as_dict()
        assert rep.conservation == "ok"
        assert rep.arrivals == 12 and rep.outcomes == 12
        print(f"final bundle replay: OK — {rep.steps} steps, "
              f"{rep.arrivals} arrivals re-driven, 0 leaked pages")

        # 3. the mid-incident ejection bundle replays as a clean prefix
        rep = replay_bundle(ejection)
        assert rep.refused is None, rep.refused
        assert rep.divergence is None, rep.divergence
        assert rep.conservation == "ok"
        print(f"ejection bundle replay: OK — prefix of {rep.steps} "
              f"steps, {rep.pending} requests still pending at journal "
              "end")

        # 4. a planted flipped token is localized, not silently passed
        decoded = validate_bundle(final)["journal"]
        frames = [dict(f) for f in decoded.frames]
        target = next(f for f in frames if f["t"] == "outcome")
        target["tokens"] = list(target["tokens"])
        target["tokens"][0] ^= 1
        doctored = os.path.join(tmp, "doctored.tar.gz")
        import tarfile
        with tarfile.open(final, "r:gz") as src, \
                tarfile.open(doctored, "w:gz") as dst:
            for m in src.getmembers():
                data = src.extractfile(m).read()
                if os.path.basename(m.name) == "journal.jsonl":
                    data = encode_frames(decoded.head, frames)
                    m.size = len(data)
                import io
                dst.addfile(m, io.BytesIO(data))
        rep = replay_bundle(doctored)
        assert rep.divergence is not None, "flipped token not caught"
        d = rep.divergence
        assert d.component == "outcome"
        assert d.step == target["step"] and d.replica == target["replica"]
        print(f"planted divergence: localized to step {d.step}, "
              f"replica {d.replica}, component {d.component}")

    print("replay smoke: ALL GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
