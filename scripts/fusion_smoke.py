#!/usr/bin/env python
"""Fusion-pass smoke for scripts/verify.sh (ISSUE 13 satellite).

One self-contained CPU check of the artifact→pass→install loop:

1. arm the chain profiler over a real (tiny) serving storm + an eager
   optimizer run, export the ``paddle_tpu.hot_chains`` artifact;
2. feed it to :class:`paddle_tpu.jit.fusion.FusionPass` and assert BOTH
   shipped regions fuse (decode_tail + optimizer_chain), install them,
   and spot-check byte-identity of a fused serve;
3. degrade-gracefully paths: a synthetically stale artifact (ops whose
   claimed symbols no longer resolve) produces structured
   ``symbol-missing`` skips, a schema-mismatched artifact produces a
   ``schema-mismatch`` skip — and neither ever raises.

Exit 0 and ONE JSON line on success; nonzero + a message otherwise.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.core.tensor import Parameter
    from paddle_tpu.inference.decoding import (ContinuousBatchingEngine,
                                               GenerationConfig)
    from paddle_tpu.jit.fusion import FusionPass
    from paddle_tpu.models import llama as L
    from paddle_tpu.observability.profiling import chain_profiler
    from paddle_tpu.observability.runtime import telemetry
    from paddle_tpu.optimizer.clip import ClipGradByGlobalNorm
    from paddle_tpu.optimizer.optimizer import AdamW

    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_stacked_params(cfg, seed=3)
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 9, 13, 7, 17, 3)]

    def engine(fused=False):
        return ContinuousBatchingEngine(
            cfg, GenerationConfig(max_new_tokens=8), num_slots=2,
            page_size=4, max_seq_len=64, chunk=3, unified=True,
            fused_tail=fused)

    # 1. profile the CPU smoke ------------------------------------------------
    telemetry.enable()
    chain_profiler.reset()
    chain_profiler.arm()
    try:
        want = engine().serve(params, prompts)
        ps = [Parameter(jnp.asarray(rng.randn(16, 8).astype(np.float32)))
              for _ in range(4)]
        opt = AdamW(0.01, parameters=ps,
                    grad_clip=ClipGradByGlobalNorm(1.0))
        for _ in range(3):
            for p in ps:
                p._grad_value = jnp.asarray(
                    rng.randn(16, 8).astype(np.float32))
            opt.step()
    finally:
        chain_profiler.disarm()
    path = os.path.join(tempfile.mkdtemp(prefix="fusion_smoke_"),
                        "hot_chains.json")
    chain_profiler.export(path=path, top_n=8, workload="verify_smoke")

    # 2. the pass fuses both regions -----------------------------------------
    artifact = FusionPass.load(path)
    plan = FusionPass().plan(artifact)
    fused_regions = {c.region.name for c in plan.candidates}
    assert "decode_tail" in fused_regions, (fused_regions, plan.skipped)
    assert "optimizer_chain" in fused_regions, (fused_regions,
                                                plan.skipped)
    eng2 = engine()
    installed = plan.apply(engine=eng2, optimizer=opt)
    assert set(installed) == {"decode_tail", "optimizer_chain"}
    assert eng2.serve(params, prompts) == want, \
        "fused serve diverged from unfused"

    # 3. degraded inputs become structured skips, never exceptions -----------
    stale = json.loads(json.dumps(artifact))
    stale["chains"] = [{"ops": [op + "_renamed" for op in ch["ops"]],
                        "count": ch["count"], "est_us": ch["est_us"]}
                       for ch in stale["chains"]]
    stale["symbols"] = {op + "_renamed": "paddle_tpu.gone.symbol"
                        for ch in artifact["chains"]
                        for op in ch["ops"]}
    stale_plan = FusionPass().plan(stale)
    assert not stale_plan.candidates
    assert stale_plan.skipped and all(
        s["reason"] == "symbol-missing" for s in stale_plan.skipped), \
        stale_plan.skipped

    mismatched = dict(artifact)
    mismatched["schema_version"] = mismatched["version"] = 999
    bad_plan = FusionPass().plan(mismatched)
    assert not bad_plan.candidates
    assert bad_plan.skipped[0]["reason"] == "schema-mismatch"

    print(json.dumps({
        "fusion_smoke": "ok",
        "artifact": path,
        "chains": len(artifact["chains"]),
        "fused_regions": sorted(fused_regions),
        "stale_skips": len(stale_plan.skipped),
        "schema_skips": len(bad_plan.skipped),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
