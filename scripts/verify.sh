#!/usr/bin/env bash
# One-stop PR gate: tier-1 tests + tpu-lint + the armed-observability
# overhead guard + the fusion-pass smoke/A-B gate + the bench-trajectory
# sentinel. Run from the repo root:
#
#   bash scripts/verify.sh             # everything (tier-1 is the slow part)
#   bash scripts/verify.sh --fast      # lint + overhead only (skips the
#                                      # fusion stage, sentinel and tier-1)
#   bash scripts/verify.sh --sentinel  # ONLY the perf-regression sentinel
#
# The fusion stage (ROADMAP item 1) proves the profile→pass loop end to
# end: scripts/fusion_smoke.py runs the profiler on the CPU smoke, feeds
# the artifact to jit/fusion.py's FusionPass, asserts BOTH shipped
# regions fuse and that a synthetically stale artifact degrades to
# structured skips; benchmarks/bench_fusion.py then re-runs the ABBA
# admission gates (byte-identity, recompile-neutrality, measured win)
# and its one-line JSON is judged against the BENCH_r*.json trajectory
# (wide 30% relative floor until the fusion series accumulates history).
#
# The sentinel stage replays the checked-in BENCH_r*.json trajectory
# through scripts/bench_sentinel.py (noise-aware MAD bands) — the gate
# ROADMAP item 1 requires before any fusion/perf change is kept. Gate a
# fresh line directly with:
#
#   python scripts/bench_sentinel.py --fresh /tmp/bench_line.json
#
# Exit codes: 0 all green; first failing stage's code otherwise.
set -u -o pipefail
cd "$(dirname "$0")/.."

fast=0
only_sentinel=0
[ "${1:-}" = "--fast" ] && fast=1
[ "${1:-}" = "--sentinel" ] && only_sentinel=1

if [ "$only_sentinel" = "1" ]; then
    echo "== bench_sentinel (trajectory replay) =="
    python scripts/bench_sentinel.py --replay
    exit $?
fi

echo "== [1/11] tpu-lint (python -m paddle_tpu.analysis; incl. dataflow: page-leak/dtype-flow/cache-key) =="
s0=$SECONDS
python -m paddle_tpu.analysis || exit $?
echo "tpu-lint stage wall: $((SECONDS - s0))s (in-process budget 5s — regressions show here)"

echo "== [2/11] bench_obs_overhead (armed sensor+timeline plane, 3% budget) =="
JAX_PLATFORMS=cpu python benchmarks/bench_obs_overhead.py || exit $?

if [ "$fast" = "1" ]; then
    echo "== [3-11/11] fusion + multichip + multihost + disagg + replay + sentinel + tier-1 skipped (--fast) =="
    exit 0
fi

echo "== [3/11] fusion pass smoke (profile -> pass -> install, stale skips) =="
JAX_PLATFORMS=cpu python scripts/fusion_smoke.py || exit $?

echo "== [4/11] bench_fusion ABBA gates + sentinel fresh-line judgement =="
JAX_PLATFORMS=cpu python benchmarks/bench_fusion.py > /tmp/_fusion_line.json \
    || exit $?
tail -n 1 /tmp/_fusion_line.json | python scripts/bench_sentinel.py \
    --fresh - --min-history 1 --rel-floor 0.3 || exit $?

echo "== [5/11] multichip serve smoke (mp=2 storm, chip kill, byte-identical rejoin) =="
JAX_PLATFORMS=cpu python scripts/multichip_serve_smoke.py || exit $?

echo "== [6/11] multihost serve smoke (2 processes, page migration, seeded host kill) =="
JAX_PLATFORMS=cpu python scripts/multihost_serve_smoke.py || exit $?

echo "== [7/11] disagg serve smoke (prefill/decode handoff byte-identity, autoscaler vs 10x burst) =="
JAX_PLATFORMS=cpu python scripts/disagg_serve_smoke.py || exit $?

echo "== [8/11] replay smoke (journal -> bundle -> byte-identical replay, planted divergence) =="
JAX_PLATFORMS=cpu python scripts/replay_smoke.py || exit $?

echo "== [9/11] bench_router resize recovery + sentinel fresh-line judgement =="
JAX_PLATFORMS=cpu python benchmarks/bench_router.py > /tmp/_router_line.json \
    || exit $?
tail -n 1 /tmp/_router_line.json | python scripts/bench_sentinel.py \
    --fresh - --min-history 1 --rel-floor 0.4 || exit $?

echo "== [10/11] bench_sentinel (trajectory replay) =="
python scripts/bench_sentinel.py --replay || exit $?

echo "== [11/11] tier-1 test suite =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
exit $rc
