#!/usr/bin/env bash
# One-stop PR gate: tier-1 tests + tpu-lint + the armed-observability
# overhead guard + the bench-trajectory sentinel. Run from the repo root:
#
#   bash scripts/verify.sh             # everything (tier-1 is the slow part)
#   bash scripts/verify.sh --fast      # lint + overhead only (skips the
#                                      # sentinel and tier-1)
#   bash scripts/verify.sh --sentinel  # ONLY the perf-regression sentinel
#
# The sentinel stage replays the checked-in BENCH_r*.json trajectory
# through scripts/bench_sentinel.py (noise-aware MAD bands) — the gate
# ROADMAP item 1 requires before any fusion/perf change is kept. Gate a
# fresh line directly with:
#
#   python scripts/bench_sentinel.py --fresh /tmp/bench_line.json
#
# Exit codes: 0 all green; first failing stage's code otherwise.
set -u -o pipefail
cd "$(dirname "$0")/.."

fast=0
only_sentinel=0
[ "${1:-}" = "--fast" ] && fast=1
[ "${1:-}" = "--sentinel" ] && only_sentinel=1

if [ "$only_sentinel" = "1" ]; then
    echo "== bench_sentinel (trajectory replay) =="
    python scripts/bench_sentinel.py --replay
    exit $?
fi

echo "== [1/4] tpu-lint (python -m paddle_tpu.analysis) =="
python -m paddle_tpu.analysis || exit $?

echo "== [2/4] bench_obs_overhead (armed sensor+timeline plane, 3% budget) =="
JAX_PLATFORMS=cpu python benchmarks/bench_obs_overhead.py || exit $?

if [ "$fast" = "1" ]; then
    echo "== [3/4] sentinel skipped (--fast) =="
    echo "== [4/4] tier-1 skipped (--fast) =="
    exit 0
fi

echo "== [3/4] bench_sentinel (trajectory replay) =="
python scripts/bench_sentinel.py --replay || exit $?

echo "== [4/4] tier-1 test suite =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
exit $rc
