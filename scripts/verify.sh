#!/usr/bin/env bash
# One-stop PR gate: tier-1 tests + tpu-lint + the armed-observability
# overhead guard. Run from the repo root:
#
#   bash scripts/verify.sh          # everything (tier-1 is the slow part)
#   bash scripts/verify.sh --fast   # skip tier-1 (lint + overhead only)
#
# Exit codes: 0 all green; first failing stage's code otherwise.
set -u -o pipefail
cd "$(dirname "$0")/.."

fast=0
[ "${1:-}" = "--fast" ] && fast=1

echo "== [1/3] tpu-lint (python -m paddle_tpu.analysis) =="
python -m paddle_tpu.analysis || exit $?

echo "== [2/3] bench_obs_overhead (armed <1% measured, 3% budget) =="
JAX_PLATFORMS=cpu python benchmarks/bench_obs_overhead.py || exit $?

if [ "$fast" = "1" ]; then
    echo "== [3/3] tier-1 skipped (--fast) =="
    exit 0
fi

echo "== [3/3] tier-1 test suite =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
exit $rc
