"""``paddle.signal`` parity: frame / overlap_add / stft / istft.

Rebuild of python/paddle/signal.py (phi frame/overlap_add kernels +
fft-composed stft/istft — SURVEY.md §2.1 kernel corpus long tail). The
framing is a gather over strided window starts and overlap-add a
scatter-add — both XLA-fusable; the transforms ride paddle_tpu.fft.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from .core.dispatch import apply
from .core.tensor import Tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _frame_gather(v, frame_length: int, hop: int):
    """(..., N) -> (..., num_frames, frame_length) strided window gather —
    the single home of the window-start arithmetic."""
    n = v.shape[-1]
    num = 1 + (n - frame_length) // hop
    idx = (jnp.arange(num) * hop)[:, None] +         jnp.arange(frame_length)[None, :]
    return jnp.take(v, idx, axis=-1), idx


def _ola_scatter(frames, hop: int):
    """(..., num_frames, frame_length) -> (..., N) overlap-add scatter."""
    num, fl = frames.shape[-2], frames.shape[-1]
    n = (num - 1) * hop + fl
    idx = (jnp.arange(num) * hop)[:, None] + jnp.arange(fl)[None, :]
    out = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
    return out.at[..., idx].add(frames), idx


def frame(x, frame_length: int, hop_length: int, axis: int = -1, name=None):
    """Slice overlapping frames (paddle layout, axis must be 0 or -1):
    axis=-1: (..., N) -> (..., frame_length, num_frames);
    axis=0:  (N, ...) -> (num_frames, frame_length, ...)."""
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")
    if axis not in (0, -1):
        raise ValueError("frame: axis must be 0 or -1 (paddle semantics)")

    def fn(v):
        # branch on the USER-CHOSEN layout: for 1-D input axis 0 and -1
        # name the same dimension but paddle's output layouts differ
        sig_ax = 0 if axis == 0 else v.ndim - 1
        n = v.shape[sig_ax]
        if frame_length > n:
            raise ValueError(
                f"frame_length {frame_length} > signal length {n}")
        vm = jnp.moveaxis(v, sig_ax, -1)
        frames, _ = _frame_gather(vm, frame_length, hop_length)
        # frames: (..., num, frame_length)
        if axis == 0:
            return jnp.moveaxis(jnp.moveaxis(frames, -2, 0), -1, 1)
        return jnp.swapaxes(frames, -1, -2)

    return apply(fn, _t(x), op_name="frame")


def overlap_add(x, hop_length: int, axis: int = -1, name=None):
    """Inverse of :func:`frame` (axis 0 or -1): axis=-1 consumes
    (..., frame_length, num_frames), axis=0 consumes
    (num_frames, frame_length, ...); N = (num-1)*hop + frame_length."""
    if hop_length <= 0:
        raise ValueError("hop_length must be positive")
    if axis not in (0, -1):
        raise ValueError("overlap_add: axis must be 0 or -1")

    def fn(v):
        if axis == 0:
            # v: (num, fl, ...) -> (..., num, fl)
            fr = jnp.moveaxis(jnp.moveaxis(v, 1, -1), 0, -2)
        else:
            fr = jnp.swapaxes(v, -1, -2)      # (..., num, fl)
        out, _ = _ola_scatter(fr, hop_length)
        if axis == 0:
            out = jnp.moveaxis(out, -1, 0)
        return out

    return apply(fn, _t(x), op_name="overlap_add")


def _window_array(window, n_fft, dtype):
    if window is None:
        return jnp.ones((n_fft,), dtype)
    w = window._value if isinstance(window, Tensor) else jnp.asarray(window)
    if w.shape[-1] != n_fft:
        raise ValueError(f"window length {w.shape[-1]} != n_fft {n_fft}")
    return w.astype(dtype)


def stft(x, n_fft: int, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window=None, center: bool = True,
         pad_mode: str = "reflect", normalized: bool = False,
         onesided: bool = True, name=None):
    """paddle.signal.stft parity: (B?, N) real/complex -> (B?, F, num_frames)
    complex spectrogram."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    if wl > n_fft:
        raise ValueError("win_length must be <= n_fft")

    def fn(v, *rest):
        is_complex = jnp.iscomplexobj(v)
        if onesided and is_complex:
            raise ValueError("onesided is not supported for complex inputs")
        if rest:
            w = rest[0].astype(jnp.float32)
        else:
            w = jnp.ones((wl,), jnp.float32)
        # center-pad the window to n_fft (paddle semantics)
        lp = (n_fft - wl) // 2
        w = jnp.pad(w, (lp, n_fft - wl - lp))
        if center:
            pad = n_fft // 2
            v = jnp.pad(v, [(0, 0)] * (v.ndim - 1) + [(pad, pad)],
                        mode=pad_mode)
        if v.shape[-1] < n_fft:
            raise ValueError(
                f"stft: signal length {v.shape[-1]} "
                f"{'(after center padding) ' if center else ''}is shorter "
                f"than n_fft {n_fft}")
        frames, _ = _frame_gather(v, n_fft, hop)      # (..., num, n_fft)
        frames = frames * w
        if onesided:
            spec = jnp.fft.rfft(frames, axis=-1)
        else:
            spec = jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        return jnp.swapaxes(spec, -1, -2)             # (..., F, num)

    args = [_t(x)] + ([_t(window)] if window is not None else [])
    return apply(fn, *args, op_name="stft")


def istft(x, n_fft: int, hop_length: Optional[int] = None,
          win_length: Optional[int] = None, window=None, center: bool = True,
          normalized: bool = False, onesided: bool = True,
          length: Optional[int] = None, return_complex: bool = False,
          name=None):
    """paddle.signal.istft parity: inverse with window-envelope
    normalization (COLA division)."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    if return_complex and onesided:
        raise ValueError(
            "istft: return_complex=True requires onesided=False "
            "(a onesided spectrum reconstructs a real signal)")

    def fn(v, *rest):
        if rest:
            w = rest[0].astype(jnp.float32)
        else:
            w = jnp.ones((wl,), jnp.float32)
        lp = (n_fft - wl) // 2
        w = jnp.pad(w, (lp, n_fft - wl - lp))
        spec = jnp.swapaxes(v, -1, -2)                # (..., num, F)
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = jnp.real(frames)
        frames = frames * w
        num = frames.shape[-2]
        out, idx = _ola_scatter(frames, hop)
        n = out.shape[-1]
        env = jnp.zeros((n,), jnp.float32).at[idx.reshape(-1)].add(
            jnp.tile(w * w, (num,)))
        out = out / jnp.maximum(env, 1e-11)
        if center:
            out = out[..., n_fft // 2: n - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    args = [_t(x)] + ([_t(window)] if window is not None else [])
    return apply(fn, *args, op_name="istft")
