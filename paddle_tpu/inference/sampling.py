"""Distribution-faithful decoding: the in-program sampling epilogue.

ISSUE 16's tentpole. The unified ragged step already produces per-row
logits in ONE compiled program; this module is the epilogue that turns
them into tokens for every workload class at once — greedy, sampled
(temperature / top-k / top-p), speculative and grammar-constrained —
without forking the program:

* **Per-request runtime parameters.** :class:`SamplerConfig`
  (temperature, top_k, top_p, per-request seed) rides on each request
  and lands in per-row DEVICE arrays the engine updates at admission
  (the same lazy ``.at[slot].set`` discipline as the token carry), so a
  mixed greedy/sampled/constrained batch is one dispatch and the
  request mix never recompiles anything.
* **Counter-based PRNG.** The key for the token at sequence position
  ``P`` of a request with seed ``s`` is
  ``fold_in(fold_in(PRNGKey(s), P), salt)`` — derived in-program from
  plain int inputs, no key state threads across steps and no global
  stream couples rows. Streams are therefore seeded-replayable
  (same seed => same tokens) regardless of batch composition, chunk
  size, fused/unfused tail, TP degree, or a mid-stream failover resume
  (the position IS the counter).
* **Greedy is temperature == 0**, computed as ``argmax`` over the same
  (grammar-masked) logits — for unconstrained rows the mask is a no-op
  and the argmax is bit-identical to the pre-sampling engine.
* **Lossless rejection-sampling speculation**
  (:func:`spec_sample_rows`). The shipped drafters are deterministic,
  so the draft distribution is a point mass and the accept probability
  ``min(1, p/q)`` reduces to ``p_target(draft)``; on rejection the
  residual ``max(p - q, 0)`` renormalized is exactly the target with
  the draft token excluded — one categorical over the processed logits
  with that token masked. The committed-token marginal equals the
  non-speculative sampler's distribution EXACTLY (property-tested in
  ``tests/test_sampling.py``); greedy rows keep the verify-by-argmax
  prefix match and stay byte-identical.
* Salt discipline: ``DRAW`` keys ordinary categorical draws (shared by
  the non-spec epilogue and the spec bonus/undrafted draws — a row
  with an empty draft commits byte-identically to the non-spec
  sampler), ``ACCEPT`` keys the per-candidate accept coin,
  ``RESAMPLE`` keys the residual draw. Keys at positions a rejected
  round discarded are re-derived next round — the accept prefix is a
  function of the coins at earlier positions only, so reuse is
  independence-safe.

Grammar masking/advance live in ``inference/constrain.py``; the
engine applies the mask via the model's ``logits_epilogue`` hook (or
inside the injected fused-tail epilogue) BEFORE this module's
temperature/top-k/top-p processing, so constrained rows renormalize
over legal tokens only.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..observability.registry import get_registry
from . import constrain as _constrain

#: PRNG salts (see module docstring)
SALT_DRAW = 0
SALT_ACCEPT = 1
SALT_RESAMPLE = 2

_reg = get_registry()
_c_requests = _reg.counter(
    "paddle_sampling_requests_total",
    "requests admitted with a non-greedy epilogue, by mode "
    "(sampled | constrained)",
    labels=("mode",))
_c_tokens = _reg.counter(
    "paddle_sampling_tokens_total",
    "tokens committed through the sampling epilogue, by mode",
    labels=("mode",))
_c_violations = _reg.counter(
    "paddle_sampling_violations_total",
    "tokens the host grammar mirror rejected (device/host automaton "
    "disagreement — never expected; each also emits a "
    "constraint_violation event)")
_g_states = _reg.gauge(
    "paddle_sampling_grammar_states",
    "grammar-arena rows in use across registered token DFAs")


def note_request(mode: str) -> None:
    _c_requests.inc(mode=mode)


def note_tokens(mode: str, n: int) -> None:
    if n:
        _c_tokens.inc(n, mode=mode)


def note_violation() -> None:
    _c_violations.inc()


def set_grammar_states(n: int) -> None:
    _g_states.set(float(n))


@dataclass(frozen=True)
class SamplerConfig:
    """Per-request sampling parameters. ``temperature == 0`` is greedy
    (the byte-identical argmax path); ``top_k == 0`` and
    ``top_p == 1.0`` disable their filters. ``seed=None`` asks the
    engine to derive a deterministic per-request seed (config seed +
    rid) — pass an explicit seed for streams that must replay across
    engines (e.g. router failover resume)."""

    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None

    def resolved(self, default_seed: int) -> "SamplerConfig":
        if self.seed is not None:
            return self
        return replace(self, seed=int(default_seed) & 0x7FFFFFFF)


def greedy_config() -> SamplerConfig:
    return SamplerConfig(temperature=0.0, seed=0)


#: the per-row device arrays one engine slot owns, in tuple order:
#: (seeds uint32, temperatures f32, top_k int32, top_p f32)
def init_row_state(num_rows: int) -> Tuple:
    return (jnp.zeros((num_rows,), jnp.uint32),
            jnp.zeros((num_rows,), jnp.float32),
            jnp.zeros((num_rows,), jnp.int32),
            jnp.ones((num_rows,), jnp.float32))


def set_row(samp: Tuple, s: int, cfg: Optional[SamplerConfig]) -> Tuple:
    """Write one slot's sampler parameters at admission (lazy device
    updates, mirroring the engine's token-carry discipline). ``None``
    resets the row to greedy defaults — slot reuse must never inherit a
    previous request's temperature."""
    seeds, temps, top_k, top_p = samp
    if cfg is None:
        cfg = greedy_config()
    return (seeds.at[s].set(jnp.uint32((cfg.seed or 0) & 0xFFFFFFFF)),
            temps.at[s].set(jnp.float32(cfg.temperature)),
            top_k.at[s].set(jnp.int32(cfg.top_k)),
            top_p.at[s].set(jnp.float32(cfg.top_p)))


# ---------------------------------------------------------------------------
# In-program pieces
# ---------------------------------------------------------------------------
def _keys(seeds, pos, salt):
    """(N,) uint32 seeds x (N,) int32 positions -> N independent keys:
    ``fold_in(fold_in(PRNGKey(seed), pos), salt)``. Counter-based — no
    key threads across calls, so the draw at a given (seed, position,
    salt) is one fixed value wherever/whenever it is computed."""
    base = jax.vmap(jax.random.PRNGKey)(seeds)
    keyed = jax.vmap(jax.random.fold_in)(base, pos)
    return jax.vmap(lambda k: jax.random.fold_in(k, salt))(keyed)


def process_logits(logits, temps, top_k, top_p):
    """Temperature scale -> top-k -> top-p, all with PER-ROW runtime
    parameters — the vectorized twin of the legacy ``_sample`` filters
    (same kth-value rule, same keep-ties-at-cutoff top-p rule), with
    ``top_k == 0`` / ``top_p == 1`` rows passing through untouched.
    ``logits`` must already be f32 (and grammar-masked for constrained
    rows)."""
    V = logits.shape[-1]
    x = logits / jnp.maximum(temps, 1e-6)[:, None]
    k_on = (top_k > 0)[:, None]
    sorted_desc = jnp.sort(x, axis=-1)[..., ::-1]
    k_idx = jnp.clip(top_k - 1, 0, V - 1)[:, None]
    kth = jnp.take_along_axis(sorted_desc, k_idx, axis=-1)
    x = jnp.where(k_on & (x < kth), -jnp.inf, x)
    p_on = (top_p < 1.0)[:, None]
    sorted_desc = jnp.sort(x, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum(cum < top_p[:, None], axis=-1, keepdims=True)
    cutoff = jnp.take_along_axis(sorted_desc,
                                 jnp.clip(cutoff_idx, 0, V - 1), axis=-1)
    return jnp.where(p_on & (x < cutoff), -jnp.inf, x)


def sample_rows(logits, pos_next, samp, gstate, gtable):
    """The unified-step epilogue: per-row logits -> (token, grammar
    state). ``logits (rows, V)`` must already carry the grammar mask
    (the model's ``logits_epilogue`` hook / the fused tail applies
    :func:`constrain.mask_logits` first); ``pos_next (rows,)`` is the
    sequence position the sampled token will occupy (= the row's
    attended length this micro-round) — it is the PRNG counter.
    Greedy rows (``temperature <= 0``) take the bit-exact argmax."""
    seeds, temps, top_k, top_p = samp
    lg = logits.astype(jnp.float32)
    greedy_tok = jnp.argmax(lg, axis=-1).astype(jnp.int32)
    proc = process_logits(lg, temps, top_k, top_p)
    keys = _keys(seeds, pos_next, SALT_DRAW)
    drawn = jax.vmap(jax.random.categorical)(keys, proc).astype(jnp.int32)
    tok = jnp.where(temps <= 0.0, greedy_tok, drawn)
    return tok, _constrain.advance_states(gstate, tok, gtable)


def greedy_rows(logits, pos_next, samp, gstate, gtable):
    """Argmax-only twin of :func:`sample_rows` for engines whose
    request mix has never seen a sampler or a grammar: the engine
    compiles this tail until the first ``sampler=``/``grammar=``
    submit flips it to the full epilogue (ONE counted recompile, then
    sticky). Tracing no sort/cumsum/PRNG keeps the greedy program's
    compile cost at the pre-sampling baseline — on single-core CI
    boxes compile time is the tier-1 budget. The f32 cast is
    value-exact for bf16/f16 logits, so the argmax is bit-identical
    both to the legacy tail and to ``sample_rows``'s greedy path."""
    del pos_next, samp, gtable
    tok = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
    return tok, gstate


def spec_greedy_rows(logits, drafts, draft_len, pos_base, samp, gstate,
                     gtable):
    """Greedy-only twin of :func:`spec_sample_rows` (same signature,
    same ``(tokens, accepted, gstate)`` fence): per-candidate argmax +
    drafted-prefix match, no rejection sampling, no grammar advance —
    the pre-sampling speculative verifier. Swapped in by the engine
    while the epilogue is off (see :func:`greedy_rows`)."""
    del pos_base, samp, gtable
    R, k1, V = logits.shape
    k = k1 - 1
    g = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
    if k > 0:
        lane = jnp.arange(k, dtype=jnp.int32)[None, :]
        valid = lane < draft_len[:, None]
        d = jnp.clip(drafts[:, :k], 0, V - 1)
        match = (d == g[:, :k]) & valid
        accepted = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                           axis=1).astype(jnp.int32)
    else:
        accepted = jnp.zeros((R,), jnp.int32)
    return g, accepted, gstate


def spec_sample_rows(logits, drafts, draft_len, pos_base, samp, gstate,
                     gtable):
    """The speculative-step epilogue: per-candidate logits
    ``(rows, k+1, V)`` -> ``(tokens (rows, k+1), accepted (rows,),
    grammar state)``; the host commits ``draft[:a] + [tokens[a]]``
    where ``a = accepted``.

    Greedy rows: exact argmax + drafted-prefix match (byte-identical to
    the pre-sampling verifier). Sampled rows: lossless rejection
    sampling against the deterministic (point-mass) draft — candidate
    ``j`` accepts with probability ``p_j(draft_j)`` (the ``min(1, p/q)``
    rule with ``q`` a point mass), and ``tokens[j]`` holds the residual
    resample for drafted lanes / the plain ``DRAW``-salt categorical
    past the draft (so an undrafted row commits byte-identically to the
    non-speculative sampler at the same position). Constrained rows
    never draft (``draft_len == 0``); candidate 0 is grammar-masked and
    the row's DFA state advances on its committed token."""
    seeds, temps, top_k, top_p = samp
    R, k1, V = logits.shape
    k = k1 - 1
    lg = logits.astype(jnp.float32)
    lg0 = _constrain.mask_logits(lg[:, 0], gstate, gtable)
    lg = lg.at[:, 0].set(lg0)
    g = jnp.argmax(lg, axis=-1).astype(jnp.int32)          # (R, k1)
    flat = lg.reshape(R * k1, V)
    rep = lambda a: jnp.repeat(a, k1)  # noqa: E731 - row -> candidates
    proc = process_logits(flat, rep(temps), rep(top_k),
                          rep(top_p)).reshape(R, k1, V)
    pos_gen = (pos_base[:, None] + 1
               + jnp.arange(k1, dtype=jnp.int32)[None, :])  # (R, k1)
    seeds_c = jnp.repeat(seeds, k1)
    plain = jax.vmap(jax.random.categorical)(
        _keys(seeds_c, pos_gen.reshape(-1), SALT_DRAW),
        proc.reshape(R * k1, V)).reshape(R, k1).astype(jnp.int32)
    if k > 0:
        lane = jnp.arange(k, dtype=jnp.int32)[None, :]
        valid = lane < draft_len[:, None]                   # (R, k)
        d = jnp.clip(drafts[:, :k], 0, V - 1)
        match = (d == g[:, :k]) & valid
        acc_greedy = jnp.sum(jnp.cumprod(match.astype(jnp.int32),
                                         axis=1), axis=1)
        probs = jax.nn.softmax(proc[:, :k, :], axis=-1)
        p_d = jnp.take_along_axis(probs, d[..., None], axis=-1)[..., 0]
        u = jax.vmap(jax.random.uniform)(
            _keys(seeds_c.reshape(R, k1)[:, :k].reshape(-1),
                  pos_gen[:, :k].reshape(-1),
                  SALT_ACCEPT)).reshape(R, k)
        accept = (u < p_d) & valid
        acc_sampled = jnp.sum(jnp.cumprod(accept.astype(jnp.int32),
                                          axis=1), axis=1)
        resid = jnp.where(jax.nn.one_hot(d, V, dtype=bool),
                          -jnp.inf, proc[:, :k, :])
        r = jax.vmap(jax.random.categorical)(
            _keys(seeds_c.reshape(R, k1)[:, :k].reshape(-1),
                  pos_gen[:, :k].reshape(-1), SALT_RESAMPLE),
            resid.reshape(R * k, V)).reshape(R, k).astype(jnp.int32)
        toks_s = jnp.concatenate(
            [jnp.where(valid, r, plain[:, :k]), plain[:, k:]], axis=1)
    else:
        acc_greedy = jnp.zeros((R,), jnp.int32)
        acc_sampled = jnp.zeros((R,), jnp.int32)
        toks_s = plain
    greedy = temps <= 0.0
    toks = jnp.where(greedy[:, None], g, toks_s)
    accepted = jnp.where(greedy, acc_greedy, acc_sampled).astype(jnp.int32)
    gst = _constrain.advance_states(gstate, toks[:, 0], gtable)
    return toks, accepted, gst
