"""Grammar-constrained decoding: host-compiled token-level DFAs.

The constrained-decoding half of the sampling subsystem (ISSUE 16): a
regex (or the bounded-depth JSON grammar below) is compiled HOST-SIDE —
Thompson NFA, subset-construction DFA over characters, then lifted to a
**token-level** DFA by running every vocabulary token's string through
the character DFA from every state. What ships to the device is only the
resulting transition table: ``trans[state, token] = next_state`` with
``-1`` marking illegal tokens, so the in-program allowed-token mask is a
single gather + compare (:func:`mask_logits`) applied to the row's
logits *before* the sampling epilogue (``inference/sampling.py``), and
the per-row DFA state advances in-program with a second gather
(:func:`advance_states`). Every emitted token is grammar-legal by
construction — the host mirrors the automaton per delivered token and
emits a ``constraint_violation`` event if the device ever disagrees
(it never should; the mirror is the audit, not the mechanism).

Shape discipline (the O(1)-recompile invariant): all registered
grammars live in ONE fixed-capacity device arena
(:class:`GrammarArena`, ``(capacity_states, vocab)`` int32). Registering
a grammar rewrites table DATA, never shapes — the compiled unified/spec
programs take the arena as a plain input array and are never retraced.
A grammar that would overflow the arena raises ``ValueError`` at
``submit`` time (enlarge ``grammar_states`` at engine construction),
it never silently truncates.

EOS is part of the automaton, not a special case: the eos column of
``trans`` is legal exactly in accepting states (self-loop), so "the
grammar is complete" and "the row may stop" are the same table lookup
on host and device.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

#: transition-table sentinel: token illegal in this state
ILLEGAL = -1


# ---------------------------------------------------------------------------
# Regex -> character NFA (Thompson construction)
# ---------------------------------------------------------------------------
class _Regex:
    """Recursive-descent parser for the supported regex subset:
    literals, ``\\``-escapes, ``.``, char classes ``[a-z0-9]`` /
    ``[^...]``, grouping ``()``, alternation ``|``, and the
    quantifiers ``*``, ``+``, ``?``, ``{m}``, ``{m,n}``. Anchored on
    both ends (the whole generated text must match)."""

    def __init__(self, pattern: str):
        self.pat = pattern
        self.i = 0
        # NFA as epsilon/char transition lists; state 0 is start
        self.eps: List[List[int]] = []
        self.chars: List[List[Tuple[FrozenSet[str], int]]] = []

    # -- NFA building blocks ------------------------------------------------
    def _state(self) -> int:
        self.eps.append([])
        self.chars.append([])
        return len(self.eps) - 1

    def _frag_char(self, chars: FrozenSet[str]) -> Tuple[int, int]:
        a, b = self._state(), self._state()
        self.chars[a].append((chars, b))
        return a, b

    # -- parsing ------------------------------------------------------------
    def _peek(self) -> Optional[str]:
        return self.pat[self.i] if self.i < len(self.pat) else None

    def _take(self) -> str:
        c = self.pat[self.i]
        self.i += 1
        return c

    def parse(self) -> Tuple[int, int]:
        frag = self._alt()
        if self.i != len(self.pat):
            raise ValueError(
                f"regex parse error at {self.i}: unexpected "
                f"{self.pat[self.i]!r} in {self.pat!r}")
        return frag

    def _alt(self) -> Tuple[int, int]:
        frags = [self._concat()]
        while self._peek() == "|":
            self._take()
            frags.append(self._concat())
        if len(frags) == 1:
            return frags[0]
        a, b = self._state(), self._state()
        for s, e in frags:
            self.eps[a].append(s)
            self.eps[e].append(b)
        return a, b

    def _concat(self) -> Tuple[int, int]:
        frags = []
        while self._peek() is not None and self._peek() not in ")|":
            frags.append(self._quant())
        if not frags:
            s = self._state()
            return s, s
        s, e = frags[0]
        for ns, ne in frags[1:]:
            self.eps[e].append(ns)
            e = ne
        return s, e

    def _quant(self) -> Tuple[int, int]:
        frag = self._atom()
        while self._peek() in ("*", "+", "?", "{"):
            c = self._peek()
            if c == "{":
                frag = self._repeat(frag)
                continue
            self._take()
            s, e = self._state(), self._state()
            fs, fe = frag
            self.eps[s].append(fs)
            self.eps[fe].append(e)
            if c in "*?":
                self.eps[s].append(e)
            if c in "*+":
                self.eps[fe].append(fs)
            frag = (s, e)
        return frag

    def _repeat(self, frag: Tuple[int, int]) -> Tuple[int, int]:
        # {m} / {m,n}: expand by copying the sub-NFA (bounded, so the
        # DFA stays finite); the sub-pattern is re-parsed from its text
        start = self.i
        self._take()                            # '{'
        spec = ""
        while self._peek() not in (None, "}"):
            spec += self._take()
        if self._peek() is None:
            raise ValueError(f"unterminated {{...}} at {start}")
        self._take()                            # '}'
        parts = spec.split(",")
        try:
            lo = int(parts[0])
            hi = int(parts[1]) if len(parts) > 1 else lo
        except (ValueError, IndexError):
            raise ValueError(f"bad repeat spec {{{spec}}}")
        if hi < lo or lo < 0:
            raise ValueError(f"bad repeat bounds {{{spec}}}")
        # copy helper: clone the fragment's reachable sub-NFA
        def clone(f: Tuple[int, int]) -> Tuple[int, int]:
            mapping: Dict[int, int] = {}
            stack = [f[0], f[1]]
            while stack:
                s = stack.pop()
                if s in mapping:
                    continue
                mapping[s] = self._state()
                stack.extend(self.eps[s])
                stack.extend(t for _, t in self.chars[s])
            for old, new in list(mapping.items()):
                for t in self.eps[old]:
                    self.eps[new].append(mapping[t])
                for cs, t in self.chars[old]:
                    self.chars[new].append((cs, mapping[t]))
            return mapping[f[0]], mapping[f[1]]

        s, e = self._state(), self._state()
        cur = s
        for _ in range(lo):
            fs, fe = clone(frag)
            self.eps[cur].append(fs)
            cur = fe
        for _ in range(hi - lo):
            fs, fe = clone(frag)
            self.eps[cur].append(fs)
            self.eps[cur].append(e)            # optional tail
            cur = fe
        self.eps[cur].append(e)
        return s, e

    _CLASSES = {"d": "0123456789",
                "w": ("abcdefghijklmnopqrstuvwxyz"
                      "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"),
                "s": " \t\n\r"}

    def _escape(self) -> FrozenSet[str]:
        c = self._take()
        if c in self._CLASSES:
            return frozenset(self._CLASSES[c])
        if c == "n":
            return frozenset("\n")
        if c == "t":
            return frozenset("\t")
        return frozenset(c)

    def _atom(self) -> Tuple[int, int]:
        c = self._take()
        if c == "(":
            frag = self._alt()
            if self._peek() != ")":
                raise ValueError(f"unbalanced '(' in {self.pat!r}")
            self._take()
            return frag
        if c == "[":
            return self._frag_char(self._char_class())
        if c == ".":
            return self._frag_char(DOT)
        if c == "\\":
            return self._frag_char(self._escape())
        if c in "*+?{":
            raise ValueError(f"dangling quantifier {c!r} in {self.pat!r}")
        return self._frag_char(frozenset(c))

    def _char_class(self) -> FrozenSet[str]:
        negate = False
        if self._peek() == "^":
            self._take()
            negate = True
        chars: set = set()
        while self._peek() not in (None, "]"):
            c = self._take()
            if c == "\\":
                chars |= self._escape()
                continue
            if self._peek() == "-" and self.i + 1 < len(self.pat) \
                    and self.pat[self.i + 1] != "]":
                self._take()
                hi = self._take()
                chars |= {chr(x) for x in range(ord(c), ord(hi) + 1)}
            else:
                chars.add(c)
        if self._peek() is None:
            raise ValueError(f"unbalanced '[' in {self.pat!r}")
        self._take()
        if negate:
            return frozenset({"<NEG>"} | chars)
        return frozenset(chars)


#: sentinel charsets: "." (any char) and the negation marker
DOT: FrozenSet[str] = frozenset({"<DOT>"})


def _charset_match(cs: FrozenSet[str], ch: str) -> bool:
    if "<DOT>" in cs:
        return ch != "\n"
    if "<NEG>" in cs:
        return ch not in cs
    return ch in cs


class _CharDFA:
    """Subset-construction DFA over characters: ``step(state, ch)``
    returns the next state or ``ILLEGAL``. States are dense ints; the
    alphabet is whatever characters the vocabulary's token strings use
    (transitions are computed lazily per character and cached)."""

    def __init__(self, pattern: str):
        rx = _Regex(pattern)
        start, accept = rx.parse()
        self._eps = rx.eps
        self._chars = rx.chars
        self._accept_nfa = accept
        s0 = self._closure({start})
        self._ids: Dict[FrozenSet[int], int] = {s0: 0}
        self._sets: List[FrozenSet[int]] = [s0]
        self._trans: List[Dict[str, int]] = [{}]
        self.start = 0

    def _closure(self, states) -> FrozenSet[int]:
        seen = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in self._eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    def step(self, state: int, ch: str) -> int:
        if state == ILLEGAL:
            return ILLEGAL
        cache = self._trans[state]
        if ch in cache:
            return cache[ch]
        nxt: set = set()
        for s in self._sets[state]:
            for cs, t in self._chars[s]:
                if _charset_match(cs, ch):
                    nxt.add(t)
        if not nxt:
            cache[ch] = ILLEGAL
            return ILLEGAL
        closed = self._closure(nxt)
        if closed not in self._ids:
            self._ids[closed] = len(self._sets)
            self._sets.append(closed)
            self._trans.append({})
        cache[ch] = self._ids[closed]
        return cache[ch]

    def accepting(self, state: int) -> bool:
        return state != ILLEGAL and self._accept_nfa in self._sets[state]


# ---------------------------------------------------------------------------
# Token-level DFA (what the engine and the device consume)
# ---------------------------------------------------------------------------
@dataclass
class TokenDFA:
    """A grammar lifted to token granularity. ``trans`` is
    ``(n_states, vocab) int32`` over LOCAL state ids (``ILLEGAL`` marks
    forbidden tokens; the eos column self-loops in accepting states).
    ``accepting`` marks states where the text so far is a complete
    match. ``fingerprint`` dedupes arena registrations."""

    trans: np.ndarray
    accepting: np.ndarray
    start: int
    eos_token_id: int
    pattern: str = ""
    fingerprint: str = field(default="")

    def __post_init__(self):
        if not self.fingerprint:
            h = hashlib.sha256()
            h.update(self.trans.tobytes())
            h.update(bytes([self.start & 0xFF]))
            self.fingerprint = h.hexdigest()

    @property
    def n_states(self) -> int:
        return int(self.trans.shape[0])

    @property
    def vocab_size(self) -> int:
        return int(self.trans.shape[1])

    # -- host mirror (the per-token audit in the engine's unpack) -----------
    def legal(self, state: int, token: int) -> bool:
        return (0 <= state < self.n_states
                and int(self.trans[state, token]) != ILLEGAL)

    def advance(self, state: int, token: int) -> int:
        if not self.legal(state, token):
            return ILLEGAL
        return int(self.trans[state, token])

    def allowed_tokens(self, state: int) -> np.ndarray:
        """Token ids legal in ``state`` (host-side; tests + debugging)."""
        return np.nonzero(self.trans[state] != ILLEGAL)[0]


def compile_regex(pattern: str, vocab: Sequence[str],
                  eos_token_id: int) -> TokenDFA:
    """Compile ``pattern`` against a concrete vocabulary (token id ->
    token STRING) into a :class:`TokenDFA`. Raises ``ValueError`` for a
    grammar with a reachable stuck state (some prefix the automaton
    allows would leave the model with no legal token and no legal EOS —
    the epilogue's categorical would have nothing to renormalize)."""
    cdfa = _CharDFA(pattern)
    V = len(vocab)
    if not (0 <= eos_token_id < V):
        raise ValueError(f"eos_token_id {eos_token_id} outside vocab "
                         f"of {V} tokens")
    rows: List[np.ndarray] = []
    ids: Dict[int, int] = {cdfa.start: 0}
    order: List[int] = [cdfa.start]
    qi = 0
    while qi < len(order):
        cstate = order[qi]
        qi += 1
        row = np.full((V,), ILLEGAL, np.int32)
        for tid, text in enumerate(vocab):
            if tid == eos_token_id:
                continue
            s = cstate
            ok = bool(text)
            for ch in text:
                s = cdfa.step(s, ch)
                if s == ILLEGAL:
                    ok = False
                    break
            if not ok:
                continue
            if s not in ids:
                ids[s] = len(order)
                order.append(s)
            row[tid] = ids[s]
        rows.append(row)
    trans = np.stack(rows)
    accepting = np.asarray([cdfa.accepting(s) for s in order], bool)
    for local, cstate in enumerate(ids):
        if accepting[local]:
            trans[local, eos_token_id] = local      # complete: EOS legal
    stuck = [local for local in range(len(order))
             if not (trans[local] != ILLEGAL).any()]
    if stuck:
        raise ValueError(
            f"grammar {pattern!r} has reachable stuck state(s) {stuck} "
            "under this vocabulary: some legal prefix leaves no legal "
            "next token and no legal EOS — extend the vocabulary or "
            "tighten the grammar")
    return TokenDFA(trans=trans, accepting=accepting, start=0,
                    eos_token_id=eos_token_id, pattern=pattern)


def json_regex(max_depth: int = 2, ws: bool = True) -> str:
    """A bounded-depth JSON value grammar as a regex (objects/arrays
    nest at most ``max_depth`` levels — regular languages cannot count,
    so the depth bound is what makes JSON compilable to a DFA).
    ``ws`` allows a single optional space after ``,`` and ``:``."""
    sp = " ?" if ws else ""
    string = r'"([^"\\]|\\.)*"'
    number = r"-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?"
    scalar = f"({string}|{number}|true|false|null)"
    value = scalar
    for _ in range(max_depth):
        obj = (f"\\{{({sp}|{sp}{string}:{sp}{value}"
               f"(,{sp}{string}:{sp}{value})*{sp})\\}}")
        arr = f"\\[({sp}|{sp}{value}(,{sp}{value})*{sp})\\]"
        value = f"({scalar}|{obj}|{arr})"
    return value


def json_grammar(vocab: Sequence[str], eos_token_id: int,
                 max_depth: int = 2) -> TokenDFA:
    """The JSON grammar compiled against a concrete vocabulary — the
    ready-made constraint for "emit valid JSON" serving traffic."""
    return compile_regex(json_regex(max_depth), vocab, eos_token_id)


# ---------------------------------------------------------------------------
# Device arena: every registered grammar in ONE fixed-shape table
# ---------------------------------------------------------------------------
class GrammarArena:
    """Fixed-capacity ``(capacity_states, vocab) int32`` transition
    arena shared by every grammar an engine serves. Registration copies
    a grammar's table in with its state ids rebased to GLOBAL arena
    rows; the compiled programs take the arena as a plain device input,
    so new grammars change data, never shapes (no recompiles). Rows a
    request is not constrained by are never read (state ``-1`` opts a
    row out of masking entirely)."""

    def __init__(self, vocab_size: int, capacity_states: int = 64):
        self.vocab_size = int(vocab_size)
        self.capacity = max(int(capacity_states), 1)
        self._table = np.full((self.capacity, self.vocab_size), ILLEGAL,
                              np.int32)
        self._offsets: Dict[str, int] = {}
        self._grammars: Dict[str, TokenDFA] = {}
        self.used = 0
        self._device = None            # lazily refreshed jnp mirror

    def register(self, tdfa: TokenDFA) -> int:
        """Install (or find) a grammar; returns its GLOBAL start state.
        Raises ``ValueError`` when the arena is out of rows."""
        if tdfa.vocab_size != self.vocab_size:
            raise ValueError(
                f"grammar compiled for vocab {tdfa.vocab_size} does not "
                f"match the engine's vocab {self.vocab_size} — compile "
                "it against the serving tokenizer's vocabulary")
        off = self._offsets.get(tdfa.fingerprint)
        if off is not None:
            return off + tdfa.start
        n = tdfa.n_states
        if self.used + n > self.capacity:
            raise ValueError(
                f"grammar needs {n} states but the arena holds "
                f"{self.capacity - self.used} of {self.capacity} — "
                "construct the engine with a larger grammar_states")
        off = self.used
        block = tdfa.trans.copy()
        block[block != ILLEGAL] += off
        self._table[off:off + n] = block
        self.used += n
        self._offsets[tdfa.fingerprint] = off
        self._grammars[tdfa.fingerprint] = tdfa
        self._device = None
        return off + tdfa.start

    def device_table(self):
        """The arena as a device array (cached until a registration)."""
        if self._device is None:
            import jax.numpy as jnp
            self._device = jnp.asarray(self._table)
        return self._device


# ---------------------------------------------------------------------------
# In-program helpers (called from the compiled unified/spec epilogues)
# ---------------------------------------------------------------------------
def mask_logits(logits, gstate, gtable):
    """Grammar mask gathered in-program: rows with ``gstate >= 0`` get
    ``-inf`` on every token whose arena transition is ``ILLEGAL``;
    rows with ``gstate == -1`` (unconstrained) pass through UNTOUCHED —
    the greedy byte-identity guarantee rides on that no-op."""
    import jax.numpy as jnp
    cstr = gstate >= 0
    st = jnp.clip(gstate, 0, gtable.shape[0] - 1)
    allowed = gtable[st] != ILLEGAL                  # (rows, V)
    return jnp.where(cstr[:, None] & ~allowed, -jnp.inf, logits)


def advance_states(gstate, tokens, gtable):
    """Per-row DFA advance (in-program twin of the host mirror):
    constrained rows step ``trans[state, token]``, unconstrained rows
    keep ``-1``."""
    import jax.numpy as jnp
    st = jnp.clip(gstate, 0, gtable.shape[0] - 1)
    tok = jnp.clip(tokens, 0, gtable.shape[1] - 1)
    nxt = gtable[st, tok]
    return jnp.where(gstate >= 0, nxt, gstate)
