"""``paddle_tpu.inference`` — deployment API.

Rebuild of the reference's inference stack (paddle/fluid/inference/api/
analysis_predictor.cc, python/paddle/inference/ — SURVEY.md §2.5 inference
row, §3.5 call stack): ``Config`` + ``create_predictor`` + named IO handles.

TPU-first: the AnalysisPredictor's IR-fusion passes and the TensorRT
subgraph engine are XLA's job — the loaded artifact is already a compiled
StableHLO program (jit.save), so ``create_predictor`` is a thin wrapper:
load → bind IO handles → ``run()`` executes the XLA executable. The serving
decode loop with KV cache lives in paddle_tpu.inference.decoding.
"""

from .config import Config  # noqa: F401
from .predictor import Predictor, create_predictor  # noqa: F401
from . import decoding  # noqa: F401
from .decoding import (  # noqa: F401
    ContinuousBatchingEngine, GenerationConfig, GenerationEngine,
    PagedGenerationEngine, KVCache,
)
from .speculative import (  # noqa: F401
    Drafter, DraftModel, NgramDrafter, SpeculationTelemetry,
)
from . import sampling  # noqa: F401
from . import constrain  # noqa: F401
from .sampling import SamplerConfig  # noqa: F401
from .constrain import (  # noqa: F401
    GrammarArena, TokenDFA, compile_regex, json_grammar, json_regex,
)
