"""Serving decode loop: compiled prefill + KV-cache token generation.

This is the TPU replacement for the reference's inference hot path
(AnalysisPredictor decode loop over fused_multi_transformer with its CUDA
KV cache — SURVEY.md §2.2/§3.5): one jitted prefill over the padded prompt
bucket, then a jitted ``lax.scan`` over decode steps, KV cache donated
between steps so generation runs without host round-trips.

Prompt lengths are padded to buckets (powers of two by default) — the
dynamic-shape story on XLA (SURVEY §2.5 CINN row: bucketing/padding
replaces symbolic shapes).
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..flags import flag_value
from ..observability.events import emit_event
from ..observability.journal import token_checksum
from ..observability.memory import memory_armed, memory_ledger
from ..observability.profiling import chain_armed as _chain_armed
from ..observability.profiling import note_chain as _note_chain
from ..observability.runtime import recompiles
from ..profiler.record import emit_span, emit_spans, make_span, spans_armed
from . import constrain as _constrain
from . import sampling as _sampling
from .sampling import SamplerConfig


def _prefill_flags() -> Tuple:
    """Mutable host state the prefill/unified programs bake in at trace
    time (``llama._mm_prefill`` reads FLAGS_serving_a8w8_prefill to pick
    the int8 prefill matmul; the kernel-backend selectors in
    ``ops/_common.use_pallas`` and ``ops/rms_norm._use_pallas_rms`` read
    their flags the same way). Every compile-cache key that guards such
    a program includes this tuple, so a ``set_flags`` flip RETRACES — a
    counted ``paddle_runtime_recompiles_total`` miss — instead of
    silently keeping the stale program. The backend flags were the
    cache-key rule's first triage catch (tpu-lint: trace-host-state +
    cache-key): before PR 15 a ``use_pallas_*`` flip kept serving the
    old backend's program forever."""
    return (bool(flag_value("serving_a8w8_prefill")),
            bool(flag_value("use_pallas_kernels")),
            bool(flag_value("use_pallas_rms_norm")))


@dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    temperature: float = 1.0
    top_k: int = 0            # 0 = off
    top_p: float = 1.0        # 1.0 = off
    do_sample: bool = False   # False = greedy
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    seed: int = 0


class KVCache:
    """Thin named wrapper over the model's cache pytree (parity surface for
    the reference's CacheKV tensors)."""

    def __init__(self, tree: Any):
        self.tree = tree

    @property
    def seq_capacity(self) -> int:
        leaves = jax.tree_util.tree_leaves(self.tree)
        return leaves[0].shape[2] if leaves else 0


def _bucket(n: int, minimum: int = 16) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _sample(logits, key, cfg: GenerationConfig):
    logits = logits.astype(jnp.float32)
    if not cfg.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(cfg.temperature, 1e-6)
    if cfg.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -cfg.top_k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if cfg.top_p < 1.0:
        sorted_l = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest set with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < cfg.top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_l, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


class GenerationEngine:
    """Compiled generation over a model's (prefill, decode_step, init_cache)
    triple.

    ``prefill(params, ids, cache) -> (logits, cache)``
    ``decode_step(params, tok, pos, cache) -> (logits, cache)``
    ``init_cache(batch, max_len) -> cache pytree``
    """

    def __init__(self, prefill: Callable, decode_step: Callable,
                 init_cache: Callable, config: GenerationConfig = None):
        self._prefill = prefill
        self._decode = decode_step
        self._init_cache = init_cache
        self.config = config or GenerationConfig()
        self._compiled: Dict[Tuple, Callable] = {}

    # -- compiled program per (bucket, max_new) shape ------------------------

    def _build(self, prompt_bucket: int, max_new: int):
        cfg = self.config
        prefill = self._prefill
        decode = self._decode

        def run(params, ids, prompt_len, cache, key):
            # ids: (B, prompt_bucket) right-padded; prompt_len: (B,) uniform
            # (ragged serving batches belong to the paged-attention path,
            # ops/paged_attention.py)
            logits, cache = prefill(params, ids, cache)       # (B, T, V)
            last = jax.lax.dynamic_index_in_dim(
                logits, prompt_len[0] - 1, axis=1, keepdims=False)
            key, sub = jax.random.split(key)
            tok = _sample(last, sub, cfg)

            def step(carry, i):
                tok, cache, key = carry
                pos = prompt_len[0] + i  # uniform-length batch
                lg, cache = decode(params, tok, pos, cache)
                key, sub = jax.random.split(key)
                nxt = _sample(lg, sub, cfg)
                return (nxt, cache, key), tok

            (last, cache, _), toks = jax.lax.scan(
                step, (tok, cache, key), jnp.arange(max_new - 1))
            toks = jnp.concatenate([toks, last[None]], axis=0)  # (max_new, B)
            # Return the final cache so the donated input cache buffers are
            # actually aliasable (donating without returning produced
            # "donated buffers were not usable" warnings and saved nothing).
            return jnp.swapaxes(toks, 0, 1), cache              # (B, max_new)

        return jax.jit(run, donate_argnums=(3,))

    def generate(self, params, input_ids,
                 generation_config: Optional[GenerationConfig] = None):
        """input_ids: (B, T) numpy/jax int array → (B, max_new_tokens)."""
        if generation_config is not None:
            self.config = generation_config
            self._compiled.clear()
        cfg = self.config
        ids = np.asarray(input_ids)
        b, t = ids.shape
        bucket = _bucket(t)
        padded = np.full((b, bucket), cfg.pad_token_id, ids.dtype)
        padded[:, :t] = ids
        # right-padding is safe: pad rows in the cache sit beyond kv_len
        # until decode overwrites each position before first attending to it
        key = (bucket, cfg.max_new_tokens, b) + _prefill_flags()
        if key not in self._compiled:
            recompiles.record_miss("generation_engine.run", key)
            self._compiled[key] = self._build(bucket, cfg.max_new_tokens)
        cache = self._init_cache(b, bucket + cfg.max_new_tokens)
        if isinstance(cache, KVCache):
            cache = cache.tree
        prompt_len = jnp.full((b,), t, jnp.int32)
        rng = jax.random.key(cfg.seed)
        out, _ = self._compiled[key](params, jnp.asarray(padded), prompt_len,
                                     cache, rng)
        return np.asarray(out)


def llama_engine(config, generation_config: Optional[GenerationConfig] = None
                 ) -> GenerationEngine:
    """GenerationEngine wired to the stacked-param Llama family."""
    from ..models import llama as L

    return GenerationEngine(
        prefill=functools.partial(_llama_prefill, config=config),
        decode_step=functools.partial(_llama_decode, config=config),
        init_cache=lambda b, s: L.init_kv_cache(config, b, s),
        config=generation_config,
    )


def _llama_prefill(params, ids, cache, config):
    from ..models import llama as L
    return L.prefill_stacked(params, ids, cache, config)


def _llama_decode(params, tok, pos, cache, config):
    from ..models import llama as L
    return L.decode_step_stacked(params, tok, pos, cache, config)


# ---------------------------------------------------------------------------
# Ragged (paged) serving engine
# ---------------------------------------------------------------------------
class PagedGenerationEngine:
    """Ragged-batch generation over the paged KV cache.

    Unlike GenerationEngine (uniform prompt lengths, contiguous cache),
    prompts may have different lengths: each sequence owns pages via a
    block table (ops/paged_attention.py), decode positions advance per row,
    and sampling starts from each row's own last prompt token.
    """

    def __init__(self, model_config, generation_config: Optional[GenerationConfig] = None,
                 page_size: int = 16, num_pages: Optional[int] = None):
        from ..models import llama as L
        self._L = L
        self.model_config = model_config
        self.config = generation_config or GenerationConfig()
        self.page_size = page_size
        self._num_pages = num_pages
        self._compiled: Dict[Tuple, Callable] = {}

    def _build(self, max_new: int):
        L = self._L
        cfg = self.config
        mcfg = self.model_config

        def run(params, ids, seq_lens, k_pages, v_pages, block_tables, key):
            logits, k_pages, v_pages = L.prefill_paged(
                params, ids, seq_lens, k_pages, v_pages, block_tables, mcfg)
            last = jnp.take_along_axis(
                logits, (seq_lens - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]                       # (B, V) per-row last token
            key, sub = jax.random.split(key)
            tok = _sample(last, sub, cfg)

            def step(carry, i):
                tok, kp, vp, key = carry
                positions = seq_lens + i            # (B,) per-row position
                lg, kp, vp = L.decode_step_paged(
                    params, tok, positions, kp, vp, block_tables, mcfg)
                key, sub = jax.random.split(key)
                nxt = _sample(lg, sub, cfg)
                return (nxt, kp, vp, key), tok

            (last_tok, k_pages, v_pages, _), toks = jax.lax.scan(
                step, (tok, k_pages, v_pages, key), jnp.arange(max_new - 1))
            toks = jnp.concatenate([toks, last_tok[None]], axis=0)
            return jnp.swapaxes(toks, 0, 1), k_pages, v_pages

        return jax.jit(run, donate_argnums=(3, 4))

    def generate(self, params, prompts):
        """prompts: list of 1-D int arrays (ragged) → (B, max_new_tokens)."""
        from ..ops.paged_attention import PagedKVCacheManager
        cfg = self.config
        mcfg = self.model_config
        lens = [len(p) for p in prompts]
        b = len(prompts)
        t_bucket = _bucket(max(lens))
        ids = np.full((b, t_bucket), cfg.pad_token_id, np.int32)
        for i, p in enumerate(prompts):
            ids[i, :len(p)] = np.asarray(p, np.int32)

        total = [l + cfg.max_new_tokens for l in lens]
        pages_per_seq = [PagedKVCacheManager.pages_needed(n, self.page_size)
                         for n in total]
        num_pages = self._num_pages or (sum(pages_per_seq) + 1)
        mgr = PagedKVCacheManager(
            mcfg.num_hidden_layers, num_pages, self.page_size,
            mcfg.num_key_value_heads, mcfg.head_dim, dtype=mcfg.dtype)
        for i in range(b):
            mgr.allocate(i, total[i])
            mgr._lens[i] = lens[i]  # prompt length is the live length
        bt, seq_lens = mgr.block_tables(list(range(b)))

        key = (t_bucket, cfg.max_new_tokens, b,
               bt.shape[1]) + _prefill_flags()
        if key not in self._compiled:
            recompiles.record_miss("paged_engine.run", key)
            self._compiled[key] = self._build(cfg.max_new_tokens)
        rng = jax.random.key(cfg.seed)
        toks, _, _ = self._compiled[key](
            params, jnp.asarray(ids), jnp.asarray(seq_lens, jnp.int32),
            mgr.k_pages, mgr.v_pages, jnp.asarray(bt), rng)
        return np.asarray(toks)


# ---------------------------------------------------------------------------
# Continuous batching (round 4): a fixed-slot serving loop
# ---------------------------------------------------------------------------
@dataclass
class _Request:
    rid: int
    prompt: np.ndarray
    tokens: list = field(default_factory=list)
    done: bool = False
    max_new_tokens: Optional[int] = None  # None -> engine config default
    trace_id: str = ""                    # serving-layer trace correlation
    sampler: Optional[SamplerConfig] = None   # None -> greedy row
    grammar: Any = None                   # TokenDFA; None -> unconstrained
    gstart: int = -1                      # arena GLOBAL start state
    gstate_host: int = -1                 # host DFA mirror (LOCAL ids)


class ContinuousBatchingEngine:
    """Fixed-slot continuous batching over the paged KV cache — the
    *service* engine the reference exposes through AnalysisPredictor's
    serving surface (paddle/fluid/inference/api/analysis_predictor.cc:§0;
    vLLM-style continuous batching over the paged pool, PAPERS.md ragged
    paged attention).

    ``num_slots`` sequences decode together in one compiled step; when a
    sequence hits EOS (or its token budget) its pages return to the pool
    and a queued request is prefilled INTO the freed slot while the other
    slots keep decoding. Admission control is host metadata only — device
    shapes (slots, page pool, block-table width) never change, so nothing
    recompiles at runtime.

    Unified ragged step (default, ``unified=True``): the WHOLE round —
    prefill chunks of newly admitted prompts, warm-prefix/COW suffixes
    and every decoding row — is ONE dispatch of one compiled program
    (``models.llama.ragged_step`` over
    ``ops.paged_attention.ragged_paged_attention``). Rows are metadata
    arrays padded to the fixed slot count, so the program's shape is
    invariant to the request mix: exactly one compile-cache entry ever
    (O(1) recompiles across a length-diverse storm), and a prompt
    submitted mid-decode joins the current step's batch immediately.
    ``unified=False`` keeps the legacy pipeline — bucketed prefill waves
    (``_build_prefill``), the warm-suffix variant
    (``_build_prefill_suffix``) and the per-shape decode chunk
    (``_build_decode_chunk``) — for A/B benches; both paths emit
    byte-identical greedy tokens.

    Speculative decoding (``speculative=True``, default off): each
    decode row's round becomes ``[carry] + up to spec_k drafted
    tokens`` (inference/speculative.py — prompt-lookup self-drafting by
    default, ``DraftModel`` hook for a small draft model), verified by
    the SAME single-dispatch ragged program: the per-row last-token
    logits generalize to per-candidate logits, accept/reject is a
    host-side argmax comparison, and rejection rolls the paged pool
    back per row (``mgr.truncate_pages``). Greedy output stays
    byte-identical to non-speculative by construction
    (verify-then-commit); ``check_conservation`` runs after every
    speculative step.

    Host-fence discipline (the axon tunnel makes every device->host value
    dependency a full round trip): the ONLY transfer per round is the
    decode chunk's emitted tokens. Slot tokens live on device (admission
    writes the prefill's sampled token with a lazy ``.at[s].set``), the
    decode scan emits each step's INPUT token — so chunk outputs chain
    across chunks without overlap and the prefill token arrives with the
    slot's first chunk — and positions are mirrored host-side
    analytically instead of being read back.

    Service API:
      ``submit(prompt) -> rid``; ``step(params)`` runs one admit+decode
      chunk; ``collect()`` drains finished requests; ``serve(params,
      prompts)`` streams a whole list through the engine.
    """

    def __init__(self, model_config,
                 generation_config: Optional[GenerationConfig] = None,
                 num_slots: int = 8, page_size: int = 16,
                 max_seq_len: int = 2048, num_pages: Optional[int] = None,
                 chunk: int = 16, prefix_cache: bool = False,
                 check_invariants: bool = True, unified: bool = True,
                 step_tokens: Optional[int] = None,
                 speculative: bool = False, spec_k: int = 4,
                 drafter=None, fused_tail: bool = False,
                 mesh=None, mp_axis: str = "mp",
                 grammar_states: int = 0):
        from ..models import llama as L
        from ..ops.paged_attention import PagedKVCacheManager
        self._L = L
        self.model_config = model_config
        self.config = generation_config or GenerationConfig()
        self.num_slots = num_slots
        self.page_size = page_size
        self.chunk = chunk
        self.max_seq_len = max_seq_len
        self._table_width = PagedKVCacheManager.pages_needed(max_seq_len,
                                                             page_size)
        # pool sized for every slot at max length unless told otherwise
        pool = num_pages or (num_slots * self._table_width + 1)
        mcfg = model_config
        if prefix_cache:
            # shared-ownership pool + radix prefix index: retired prompts
            # stay resident and later requests prefill only their suffix
            from ..kvcache import PrefixCache, RefcountedKVCacheManager
            self.mgr = RefcountedKVCacheManager(
                mcfg.num_hidden_layers, pool, page_size,
                mcfg.num_key_value_heads, mcfg.head_dim, dtype=mcfg.dtype)
            self.cache: Optional["PrefixCache"] = PrefixCache(self.mgr)
        else:
            self.mgr = PagedKVCacheManager(
                mcfg.num_hidden_layers, pool, page_size,
                mcfg.num_key_value_heads, mcfg.head_dim, dtype=mcfg.dtype)
            self.cache = None
        # multi-chip TP serving (ROADMAP item 3): the weights are
        # Megatron-sharded and the paged pool head-sharded over the
        # mesh's mp axis — GQA groups mapped to chips. The unified
        # step's row metadata is shape-stable, so sharding is a LAYOUT
        # property of the arrays (device_put placements), not a new
        # program: the same single compiled step serves any degree and
        # O(1)-recompile behavior is untouched.
        self._mp_axis = mp_axis
        if mesh is not None and mp_axis not in mesh.shape:
            raise ValueError(
                f"serving mesh has no {mp_axis!r} axis (axes: "
                f"{tuple(mesh.shape)}) — build it with "
                "parallel.mesh.serving_mesh(...) or pass mp_axis naming "
                "the TP axis")
        chips = int(mesh.shape[mp_axis]) if mesh is not None else 1
        # a DEGREE-1 mesh is kept too: it carries no sharding but pins
        # the replica's device affinity — a replica resized down to one
        # chip must live on ITS surviving chip, not the process default
        # device another replica's mesh occupies
        self._mesh = mesh
        if self._mesh is not None:
            if chips > 1 and not unified:
                raise ValueError(
                    "multi-chip serving shards the unified ragged step; "
                    "construct with unified=True")
            if (mcfg.num_key_value_heads % chips
                    or mcfg.num_attention_heads % chips):
                raise ValueError(
                    f"TP degree {chips} must divide num_attention_heads="
                    f"{mcfg.num_attention_heads} and num_key_value_heads="
                    f"{mcfg.num_key_value_heads} (whole GQA groups per "
                    "chip — pick a degree via mesh.surviving_mp_degree)")
            self.mgr.shard_heads(self._mesh, mp_axis)
        # one-slot param-placement cache: the caller keeps passing the
        # SAME host/replicated params object to step(); the engine
        # shards it onto ITS mesh once (each replica owns its own mesh
        # after an elastic resize, so placement must be per-engine). The
        # original params are held strongly so a recycled id() can never
        # alias a dead pytree.
        self._placed_params: Tuple = (None, None)
        # the conservation audit is O(pool) host work per step; on by
        # default (it anchors the shared-ownership model, and speculative
        # draft growth/rollback is the first path that returns pages
        # mid-sequence) but opt-out for latency-critical deployments
        # with very large pools
        self._check_invariants = check_invariants and (prefix_cache
                                                       or speculative)
        # host slot state
        self._slot_rid = [None] * num_slots       # rid occupying each slot
        self._queue: list = []                    # pending _Request
        self._live: Dict[int, _Request] = {}      # rid -> request (slotted)
        self._finished: Dict[int, list] = {}
        self._finished_crc: Dict[int, int] = {}  # rid -> crc32 of the
        # retired output, stamped in _retire — the engine-side checksum
        # the postmortem journal pairs against the router's stream crc
        self._next_rid = 0
        # slot tokens stay ON DEVICE (no per-admit readback); positions
        # are host-mirrored analytically
        self._tok_dev = jnp.zeros((num_slots,), jnp.int32)
        self._pos = np.zeros((num_slots,), np.int32)
        self._bt = np.zeros((num_slots, self._table_width), np.int32)
        self._rng = jax.random.key(self.config.seed)
        # per-row sampling epilogue state (inference/sampling.py): the
        # (seeds, temps, top_k, top_p) device arrays admission writes
        # lazily, like the token carry. Defaults are greedy — a slot
        # never inherits a retired request's temperature.
        self._samp_dev = _sampling.init_row_state(num_slots)
        # per-row grammar DFA state; -1 = unconstrained (mask is a no-op)
        self._gstate_dev = jnp.full((num_slots,), -1, jnp.int32)
        # the grammar arena is ALLOCATED AT CONSTRUCTION with a fixed
        # shape — it is a program input, so sizing it lazily would
        # change the compiled signature and recompile. grammar_states=0
        # keeps a 1-row placeholder (constrained submit then raises with
        # the sizing hint); size it for the grammars you will serve
        # (json_grammar(max_depth=2) on a byte-ish vocab needs ~650).
        self._arena = _constrain.GrammarArena(
            mcfg.vocab_size, capacity_states=max(1, int(grammar_states)))
        # sampling epilogue compiled LAZILY: until the first
        # ``sampler=``/``grammar=`` submit the step programs trace the
        # argmax-only twins (sampling.greedy_rows/spec_greedy_rows) —
        # byte-identical greedy output at the pre-sampling compile
        # cost. The first such submit flips this STICKY flag and drops
        # the compiled programs: ONE counted recompile (the flag is in
        # the recompile key), after which mixed greedy/sampled/
        # constrained storms still run O(1) programs.
        self._epilogue_on = False
        # legacy (unified=False) per-shape compile caches; the unified
        # path needs exactly ONE compiled step function
        self._compiled_prefill: Dict[Tuple, Callable] = {}
        self._decode_chunk = None
        # unified ragged step: one program serving mixed prefill+decode
        # rows; its shape depends only on (slots, chunk, step_tokens,
        # table width) fixed at construction — O(1) recompiles by design
        self._unified = unified
        self._step_tokens = max(step_tokens or
                                max(num_slots, chunk, page_size), num_slots)
        self._unified_step = None
        self._unified_flags = None      # host state baked into the program
        # profile-guided fusion (jit/fusion.py decode_tail region,
        # default OFF): the step program is built by the fused builders
        # — identical compute graph fed from a PACKED two-upload plan,
        # the spec verify epilogue moves in-program, and steady-state
        # all-decode rounds plan through a vectorized fast path. Tokens
        # are byte-identical fused on/off; the admission gate lives in
        # benchmarks/bench_fusion.py.
        if fused_tail and not unified:
            raise ValueError(
                "fused_tail megakernel-izes the unified ragged step; "
                "construct with unified=True")
        self._fused_tail = bool(fused_tail)
        self._pend = [None] * num_slots   # per-slot unfed prompt suffix
        # coalesced per-slot span windows ([kind, t0_ns, t1_ns, units]):
        # armed steps MERGE each slot's prefill/decode activity into one
        # growing window instead of emitting a span per step, flushed on
        # phase change and at retire/cancel — per-step armed cost is a
        # few list ops, inside bench_obs_overhead's budget. The emitted
        # decode span therefore covers the request's whole decode wall
        # time (host gaps between dispatches included), which is exactly
        # the "decode" segment the timeline attributes.
        self._win = [None] * num_slots
        # speculative decoding (inference/speculative.py): each decode
        # row's round becomes [carry + up to spec_k drafted tokens] — a
        # short prefill the same ragged program verifies in ONE dispatch
        # whose per-candidate argmax IS the accept/reject oracle.
        # Default OFF: the non-speculative paths above are byte-for-byte
        # untouched.
        self._speculative = bool(speculative)
        self.spec_k = int(spec_k)
        self.spec = None                # SpeculationTelemetry when enabled
        self.drafter = drafter
        self._spec_step = None
        self._spec_flags = None
        if speculative:
            if not unified:
                raise ValueError(
                    "speculative decoding rides the unified ragged step; "
                    "construct with unified=True")
            # sampling composes with speculation since the rejection-
            # sampling verifier (sampling.spec_sample_rows) landed:
            # greedy rows keep verify-by-argmax byte-identity, sampled
            # rows accept draft j with prob p_target(d_j) and resample
            # the residual — distribution-identical to the non-spec
            # sampler (tests/test_sampling.py property test)
            from .speculative import NgramDrafter, SpeculationTelemetry
            self.drafter = drafter or NgramDrafter()
            self.spec = SpeculationTelemetry()
            # packed axis: every slot may speculate (1 carry + spec_k
            # drafts) in the same round; prefill shares what's left
            self._spec_tokens = max(self._step_tokens,
                                    num_slots * (self.spec_k + 1))
            # admission's page reservation per slot: rollback never
            # truncates below it (it is the row's guarantee that
            # committed decode can't OOM mid-flight)
            self._reserved = np.zeros((num_slots,), np.int64)
        #: prompt tokens actually run through prefill (cache hits skip
        #: their cached prefix; benchmarks diff this against submitted
        #: prompt lengths for the skip ratio)
        self._prefill_tokens = 0
        # HBM memory ledger (observability/memory.py): when armed, every
        # step feeds the pool's byte split + per-request holdings and
        # runs the byte conservation audit alongside check_conservation.
        self._mem_tick = 0
        # serving-layer hooks (paddle_tpu.serving): both default to None so
        # the plain submit/step/collect/serve surface is byte-identical.
        # token_callback(rid, token) fires for every KEPT token as step()
        # unpacks a chunk; finish_callback(rid, tokens) fires at _retire.
        self.token_callback: Optional[Callable[[int, int], None]] = None
        self.finish_callback: Optional[Callable[[int, list], None]] = None

    # -- compiled programs --------------------------------------------------

    def _build_prefill(self, bucket: int):
        L = self._L
        mcfg = self.model_config
        cfg = self.config

        def run(params, ids, seq_len, k_pages, v_pages, bt, key):
            logits, k_pages, v_pages = L.prefill_paged(
                params, ids, seq_len, k_pages, v_pages, bt, mcfg)
            last = jnp.take_along_axis(
                logits, (seq_len - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
            tok = _sample(last, key, cfg)
            return tok, k_pages, v_pages

        return jax.jit(run, donate_argnums=(3, 4))

    def _build_prefill_suffix(self, bucket: int):
        """Prefill of the UNCACHED SUFFIX only (prefix-cache hits): the
        rows' leading ``start`` tokens are already resident in shared
        pages, so the model runs over the suffix at offset positions and
        attends through the page gather (models.llama.prefill_paged_suffix).
        Cold rows (start 0) riding in the same batch are exact full
        prefills."""
        L = self._L
        mcfg = self.model_config
        cfg = self.config

        def run(params, ids, seq_len, start, k_pages, v_pages, bt, key):
            logits, k_pages, v_pages = L.prefill_paged_suffix(
                params, ids, seq_len, start, k_pages, v_pages, bt, mcfg)
            last = jnp.take_along_axis(
                logits, (seq_len - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
            tok = _sample(last, key, cfg)
            return tok, k_pages, v_pages

        return jax.jit(run, donate_argnums=(4, 5))

    def _build_decode_chunk(self):
        L = self._L
        mcfg = self.model_config
        cfg = self.config
        K = self.chunk

        def run(params, tok, pos, k_pages, v_pages, bt, key):
            def step(carry, _):
                tok, pos, kp, vp, key = carry
                lg, kp, vp = L.decode_step_paged(params, tok, pos, kp, vp,
                                                 bt, mcfg)
                key, sub = jax.random.split(key)
                nxt = _sample(lg, sub, cfg)
                # emit the INPUT token: chunk outputs then chain across
                # chunks (and deliver each admission's prefill token)
                return (nxt, pos + 1, kp, vp, key), tok

            (tok, pos, k_pages, v_pages, _), toks = jax.lax.scan(
                step, (tok, pos, k_pages, v_pages, key), None, length=K)
            return (jnp.swapaxes(toks, 0, 1),       # (S, K)
                    tok, k_pages, v_pages)

        return jax.jit(run, donate_argnums=(3, 4))

    # -- service API --------------------------------------------------------

    def _budget(self, req: "_Request") -> int:
        """Per-request new-token budget (submit() override or config)."""
        return (req.max_new_tokens if req.max_new_tokens is not None
                else self.config.max_new_tokens)

    @property
    def num_chips(self) -> int:
        """TP chips this engine is sharded over (1 = single-chip)."""
        return self.mgr.mesh_chips

    @property
    def mesh(self):
        """The serving TP mesh (None when single-chip)."""
        return self._mesh

    def _place_params(self, params):
        """Shard the caller's params onto this engine's mesh (cached by
        object identity — the serving loop passes one params object
        forever; a fresh object, e.g. after a weight swap, re-places)."""
        if self._placed_params[0] is params:
            return self._placed_params[1]
        placed = self._L.shard_params_tp(params, self._mesh,
                                         self.model_config)
        self._placed_params = (params, placed)
        return placed

    @property
    def num_free_slots(self) -> int:
        """Slots not occupied by a live sequence (pending queue not counted)."""
        return self._slot_rid.count(None)

    @property
    def num_queued(self) -> int:
        """Submitted requests waiting in the engine's internal FIFO (not
        yet holding a slot). The scheduler's admission headroom math uses
        this instead of reaching into ``._queue`` (tpu-lint
        private-engine)."""
        return len(self._queue)

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               trace_id: str = "", sampler: Optional[SamplerConfig] = None,
               grammar=None, grammar_prefix=None) -> int:
        """Queue a request. ``sampler`` carries the per-request
        temperature/top-k/top-p/seed (None follows the engine's
        ``GenerationConfig``: a per-request sampler derived from it when
        ``do_sample``, plain greedy otherwise); ``grammar`` is a
        ``constrain.TokenDFA`` constraining every generated token;
        ``grammar_prefix`` pre-advances the DFA through tokens this
        request already generated elsewhere (the router's failover
        resume, whose continuation prompt contains them). Both ride the
        unified step's in-program epilogue, so a mixed
        greedy/sampled/constrained batch stays ONE dispatch of ONE
        compiled program."""
        budget = (max_new_tokens if max_new_tokens is not None
                  else self.config.max_new_tokens)
        prompt = np.asarray(prompt, np.int32)
        if len(prompt) + budget > self.max_seq_len:
            raise ValueError(
                f"prompt of {len(prompt)} tokens + max_new_tokens="
                f"{budget} exceeds the engine's "
                f"max_seq_len={self.max_seq_len}; raise max_seq_len or "
                "truncate the prompt (silent page clamping would corrupt "
                "the sequence's KV)")
        if (sampler is not None or grammar is not None) \
                and not self._unified:
            # the legacy pipeline's epilogue is the engine-wide
            # GenerationConfig sampler baked into three programs; the
            # per-row runtime-parameter epilogue exists only in the
            # unified step. A genuinely unsupported combo, so it stays
            # a construction-time contract (README "Sampling &
            # constrained decoding").
            raise ValueError(
                "per-request sampling / constrained decoding ride the "
                "unified ragged step's in-program epilogue; construct "
                "the engine with unified=True (legacy unified=False "
                "supports only the engine-wide GenerationConfig sampler)")
        rid = self._next_rid
        self._next_rid += 1
        if sampler is None and self.config.do_sample and self._unified:
            # engine-wide do_sample maps onto the same per-request
            # epilogue: one derived SamplerConfig per request, seeded
            # from (config seed, rid) so streams are replayable
            sampler = SamplerConfig(temperature=self.config.temperature,
                                    top_k=self.config.top_k,
                                    top_p=self.config.top_p)
        if sampler is not None:
            sampler = sampler.resolved(
                self.config.seed * 1000003 + 7919 * rid)
        if (sampler is not None or grammar is not None) \
                and not self._epilogue_on:
            # first sampled/constrained request: swap the argmax-only
            # tail for the full in-program epilogue — ONE counted
            # recompile (the flag is in the recompile key), sticky for
            # the engine's lifetime
            self._epilogue_on = True
            self._unified_step = None
            self._spec_step = None
        gstart, ghost = -1, -1
        if grammar is not None:
            # ValueError on vocab mismatch / arena overflow — at submit,
            # never mid-step
            gstart = self._arena.register(grammar)
            _sampling.set_grammar_states(self._arena.used)
            ghost = grammar.start
            for t in (grammar_prefix if grammar_prefix is not None
                      else ()):
                ghost = grammar.advance(ghost, int(t))
                if ghost == _constrain.ILLEGAL:
                    raise ValueError(
                        f"grammar_prefix token {int(t)} is illegal in "
                        f"grammar {grammar.pattern!r} — the resumed "
                        "stream cannot have produced it")
            _sampling.note_request("constrained")
        elif sampler is not None and sampler.temperature > 0:
            _sampling.note_request("sampled")
        self._queue.append(_Request(rid, prompt,
                                    max_new_tokens=max_new_tokens,
                                    trace_id=trace_id, sampler=sampler,
                                    grammar=grammar, gstart=gstart,
                                    gstate_host=ghost))
        return rid

    def cancel(self, rid: int) -> bool:
        """Abort a request mid-flight. Queued: dropped before admission.
        Live: the slot is retired immediately — pages return to the pool,
        the block-table row points back at the garbage page, and nothing
        lands in the finished map (the caller initiated the abort, so no
        finish_callback fires either). Returns False for unknown/done rids.
        """
        for i, req in enumerate(self._queue):
            if req.rid == rid:
                self._queue.pop(i)
                return True
        if rid in self._live:
            self._retire(self._slot_rid.index(rid), cancelled=True)
            return True
        return False

    def _admit_pick(self):
        """Shared admission bookkeeping (host metadata only): pop queued
        requests into free slots, resolve the prefix cache (shared pages,
        COW copy), allocate pages. Returns the picked
        ``(slot, req, pages_row, prompt_len, n_cached)`` list; the legacy
        path then runs bucketed prefill dispatches over it while the
        unified path just queues the suffix tokens into the next ragged
        step."""
        picked = []                # (slot, req, pages_row, lp, n_cached)
        recorded = []              # deferred stats-only cache accounting
        try:
            picked = self._admit_window(picked, recorded)
            for req, r_lp, r_cached, r_shared, r_cow in recorded:
                # stats-only lookup accounting (counters + cache_hit
                # event), deferred until the WHOLE window lands so a
                # mid-window raise can't count a hit for a request that
                # gets rolled back and re-admitted next step
                try:
                    self.cache.record(req.rid, r_lp, r_cached, r_shared,
                                      cow=r_cow is not None,
                                      trace_id=req.trace_id)
                except Exception:
                    # a broken stats sink must not tear down an admitted
                    # window (events.emit discipline): rolling back here
                    # would re-admit and DOUBLE-count the hits already
                    # recorded — undercounting once is the safe failure
                    pass
        except BaseException:
            # admission is atomic across the whole window: requests are
            # admitted only once every picked entry lands, so anything
            # raising between an allocate and the return must free EVERY
            # picked allocation and requeue the requests at the head in
            # original order — rolling back only the current request
            # would orphan earlier picks: their pages leak (never reach
            # _slot_rid, so cancel/retire can't find them) and the
            # requests silently vanish (tpu-lint page-leak)
            for _, req, _, _, _ in reversed(picked):
                self.mgr.free(req.rid)
                self._queue.insert(0, req)
            raise
        return picked

    def _admit_window(self, picked, recorded):
        for s in range(self.num_slots):
            if self._slot_rid[s] is not None or not self._queue:
                continue
            req = self._queue[0]
            lp = len(req.prompt)
            total = lp + self._budget(req)       # submit() bounds this
            shared: list = []
            n_cached = 0
            cow_src = None
            if self.cache is not None:
                shared, n_cached, cow_src = self.cache.lookup(req.prompt)
            need_fresh = self.mgr.pages_for(total) - len(shared)
            if self.mgr.num_free_pages < need_fresh and self.cache is not None:
                # reclaim cold cached pages before deferring admission;
                # protect the pages THIS lookup is about to share (their
                # refcounts rise only at allocate)
                self.cache.evict(need_fresh - self.mgr.num_free_pages,
                                 protect=shared + [cow_src])
                if (self.mgr.num_free_pages < need_fresh
                        and cow_src is not None):
                    # still short: give up the COW page (one more
                    # evictable) and recompute its block instead
                    cow_src, n_cached = None, len(shared) * self.page_size
                    self.cache.evict(
                        need_fresh - self.mgr.num_free_pages,
                        protect=shared)
            if self.mgr.num_free_pages < need_fresh:
                if not self._live and not picked:
                    # infeasibility is judged against WHOLE-pool capacity:
                    # with nothing live and nothing evictable left, a
                    # request within capacity admits (free == usable -
                    # shared); beyond capacity nothing ever will
                    if self.mgr.pages_for(total) > self.mgr.usable_pages:
                        memory_ledger.note_oom(
                            "infeasible", self.mgr,
                            need_pages=self.mgr.pages_for(total),
                            free_pages=self.mgr.num_free_pages,
                            request_id=req.rid, trace_id=req.trace_id)
                        raise MemoryError(
                            f"request {req.rid} needs "
                            f"{self.mgr.pages_for(total)} pages but the "
                            f"pool only holds {self.mgr.usable_pages}; "
                            "enlarge num_pages")
                break                    # pool full: wait for a completion
            if self.cache is not None:
                pages = self.mgr.allocate(req.rid, total, shared=shared)
            else:
                pages = self.mgr.allocate(req.rid, total)
            # ownership transfers into ``picked`` IMMEDIATELY (the
            # rollback in _admit_pick owns the pages from here); the
            # pop comes after, so an allocate raise leaves the request
            # queued with nothing to undo
            picked.append((s, req, pages, lp, n_cached))
            self._queue.pop(0)
            if self.cache is not None and cow_src is not None:
                # the suffix's first write lands mid-page: append into
                # a private device-side copy, never the shared page
                self.mgr.copy_page(cow_src, pages[len(shared)])
            self.mgr._lens[req.rid] = lp
            if memory_armed[0]:
                # per-request HBM attribution: cached-vs-fresh page
                # split for /memz, memory.json and the request span args
                memory_ledger.note_request(
                    self.mgr, req.rid, prompt_len=lp,
                    cached_pages=len(shared), trace_id=req.trace_id)
            if self.cache is not None:
                recorded.append((req, lp, n_cached, len(shared), cow_src))
        return picked

    def _admit(self, params):
        """Legacy (unified=False) admission: allocate pages, prefill into
        the slots, record the first generated tokens.

        Round-5: admissions are BATCHED — every free slot fillable this
        round goes through ONE prefill call per prompt bucket (B padded to
        the next power of two so the compile cache stays small; pad rows
        write into the reserved garbage page 0 and their sampled tokens
        are discarded). A one-at-a-time B=1 prefill wave was ~1/3 of the
        mixed-workload serve wall time at 16 slots — batch-1 prefills
        leave the MXU almost idle."""
        cfg = self.config
        picked = self._admit_pick()
        if not picked:
            return
        # group by (SUFFIX bucket, warm): cold rows NEVER share a group
        # with warm rows, so they always run the original full-prefill
        # program and cache-enabled cold traffic stays byte-identical
        # with the cache-disabled engine (the suffix program is a
        # numerically different attention — fine for warm rows, whose
        # reuse is cross-program by construction, but not imposed on
        # cold ones). Without the cache every row is cold and grouping /
        # compile keys match the pre-cache engine exactly.
        groups: Dict[Tuple, list] = {}
        for item in picked:
            groups.setdefault((_bucket(item[3] - item[4]), item[4] > 0),
                              []).append(item)
        for (bucket, warm), items in groups.items():
            real = len(items)
            b_pad = 1
            while b_pad < real:
                b_pad *= 2
            # real <= num_slots by construction; clamp keeps b_pad within
            # one slot-wave (for non-power-of-two num_slots the final
            # bucket is num_slots itself)
            b_pad = min(b_pad, self.num_slots)
            ids = np.full((b_pad, bucket), cfg.pad_token_id, np.int32)
            rows = np.zeros((b_pad, self._table_width), np.int32)
            lens = np.ones((b_pad,), np.int32)   # pad rows: 1 garbage tok
            starts = np.zeros((b_pad,), np.int32)
            for i, (s, req, pages, lp, nc) in enumerate(items):
                ids[i, :lp - nc] = req.prompt[nc:]
                rows[i, :len(pages)] = pages
                lens[i] = lp - nc
                starts[i] = nc
            key = (("sfx", bucket, b_pad) if warm
                   else (bucket, b_pad)) + _prefill_flags()
            fresh = key not in self._compiled_prefill
            if fresh:
                recompiles.record_miss("cbe.prefill", key)
                self._compiled_prefill[key] = (
                    self._build_prefill_suffix(bucket) if warm
                    else self._build_prefill(bucket))
            self._rng, sub = jax.random.split(self._rng)
            c0 = time.perf_counter() if fresh else 0.0
            t0_ns = time.perf_counter_ns() if spans_armed() else 0
            if warm:
                tok, self.mgr.k_pages, self.mgr.v_pages = \
                    self._compiled_prefill[key](
                        params, jnp.asarray(ids), jnp.asarray(lens),
                        jnp.asarray(starts), self.mgr.k_pages,
                        self.mgr.v_pages, jnp.asarray(rows), sub)
            else:
                tok, self.mgr.k_pages, self.mgr.v_pages = \
                    self._compiled_prefill[key](
                        params, jnp.asarray(ids), jnp.asarray(lens),
                        self.mgr.k_pages, self.mgr.v_pages,
                        jnp.asarray(rows), sub)
            if fresh:
                # first call of a new shape = trace+compile; surface the
                # warmup cost in paddle_runtime_compile_seconds{fn}
                jax.block_until_ready(tok)
                recompiles.observe_compile("cbe.prefill",
                                           time.perf_counter() - c0)
            self._prefill_tokens += int(sum(it[3] - it[4] for it in items))
            if t0_ns:
                # one batched prefill serves several requests: emit one
                # span per admitted request so each trace-id lane shows
                # its own prefill segment
                t1_ns = time.perf_counter_ns()
                for s, req, pages, lp, nc in items:
                    emit_span("engine.prefill", t0_ns, t1_ns,
                              event_type="Operator", trace_id=req.trace_id,
                              args={"request_id": req.rid, "bucket": bucket,
                                    "prompt_len": lp, "cached_tokens": nc})
            # NO host readback: prefill tokens are written into the slots
            # lazily and reach the host with the next chunk's emissions
            slot_idx = jnp.asarray([s for s, *_ in items], jnp.int32)
            self._tok_dev = self._tok_dev.at[slot_idx].set(tok[:real])
            for i, (s, req, pages, lp, nc) in enumerate(items):
                self._slot_rid[s] = req.rid
                self._live[req.rid] = req
                self._pos[s] = lp
                self._bt[s] = rows[i]

    def _complete(self, req) -> bool:
        cfg = self.config
        if len(req.tokens) >= self._budget(req):
            return True
        return (cfg.eos_token_id is not None
                and req.tokens and req.tokens[-1] == cfg.eos_token_id)

    def _note_win(self, s, kind: str, t0_ns: int, t1_ns: int, units: int,
                  batch: list) -> None:
        """Merge one armed step's activity into the slot's pending span
        window (same kind: extend + accumulate; phase change: flush the
        old window into ``batch`` and start a new one)."""
        w = self._win[s]
        if w is not None:
            if w[0] == kind:
                w[2] = t1_ns
                w[3] += units
                return
            self._flush_win(s, batch)
        self._win[s] = [kind, t0_ns, t1_ns, units]

    def _flush_win(self, s, batch: Optional[list] = None) -> None:
        """Emit the slot's pending coalesced span (no-op when none). A
        cancel flushes too — a mid-decode failover must not lose the
        dead replica's decode segment from the request's trace."""
        w = self._win[s]
        if w is None:
            return
        self._win[s] = None
        rid = self._slot_rid[s]
        req = self._live.get(rid)
        if req is None:
            return
        kind, t0_ns, t1_ns, units = w
        if kind == "prefill":
            sp = make_span("engine.prefill", t0_ns, t1_ns, "Operator",
                           req.trace_id,
                           args={"request_id": rid, "slot": s,
                                 "prefill_tokens": units})
        else:
            sp = make_span("engine.decode_chunk", t0_ns, t1_ns,
                           "Operator", req.trace_id,
                           args={"request_id": rid, "slot": s,
                                 "chunk": units})
        if batch is None:
            emit_spans([sp])
        else:
            batch.append(sp)

    def _retire(self, s, cancelled: bool = False):
        """Free a finished (or cancelled) slot: pages back to the pool,
        output to the finished map, slot table pointed at the reserved
        garbage page. Cancelled slots free resources but produce no
        finished entry and no finish_callback."""
        self._flush_win(s)
        rid = self._slot_rid[s]
        req = self._live.pop(rid)
        req.done = True
        if not cancelled:
            out = req.tokens[:self._budget(req)]
            self._finished[rid] = out
            self._finished_crc[rid] = token_checksum(out)
            if self.cache is not None:
                # index the finished prefix BEFORE release: pages backing
                # its full token blocks stay resident (refcount 0, cached)
                # instead of draining to the free list. Positions past the
                # kept output may hold over-decoded garbage, but those
                # never complete a block (full blocks end <= kept length).
                toks = ([int(t) for t in req.prompt]
                        + [int(t) for t in out])
                if self._speculative and out:
                    # the last delivered token may be the verify bonus —
                    # committed but never fed back, so its K/V slot was
                    # never written. Index one token short so a future
                    # cache hit can never attend a hole.
                    toks = toks[:-1]
                self.cache.insert(toks, self.mgr._tables[rid])
            if self.finish_callback is not None:
                self.finish_callback(rid, out)
        self.mgr.free(rid)
        self._slot_rid[s] = None
        self._bt[s] = 0
        self._pos[s] = 0
        self._pend[s] = None

    def _deliver_tokens(self, s, tokens) -> bool:
        """Unpack one slot's emitted tokens: append to the request, fire
        ``token_callback`` per token (surviving a reentrant in-place
        cancel from inside the callback), retire on completion. Shared
        verbatim by the legacy and unified steps — the reentrancy
        contract must never fork. Returns True while the slot's request
        keeps decoding (caller may advance its position mirror)."""
        rid = self._slot_rid[s]
        req = self._live[rid]
        mode = ("constrained" if req.grammar is not None else
                "sampled" if (req.sampler is not None
                              and req.sampler.temperature > 0) else None)
        for t in tokens:
            t = int(t)
            if req.grammar is not None:
                # host DFA mirror: the audit half of constrained
                # decoding (the mask is the mechanism). Device and host
                # walk the same table, so a disagreement means real
                # corruption — count it, emit the event, keep serving.
                if req.grammar.legal(req.gstate_host, t):
                    req.gstate_host = req.grammar.advance(
                        req.gstate_host, t)
                else:
                    _sampling.note_violation()
                    emit_event("constraint_violation", request_id=rid,
                               trace_id=req.trace_id, token=t,
                               state=int(req.gstate_host),
                               pattern=req.grammar.pattern)
            if mode is not None:
                _sampling.note_tokens(mode, 1)
            req.tokens.append(t)
            if self.token_callback is not None:
                self.token_callback(rid, t)
                if self._slot_rid[s] != rid:
                    return False   # callback cancelled this request
            if self._complete(req):
                break
        if self._slot_rid[s] != rid:
            return False           # already retired by a reentrant cancel
        if self._complete(req):
            self._retire(s)
            return False
        return True

    def step(self, params) -> int:
        """One admit + decode round (ONE device->host transfer: the
        step's emitted tokens). Returns the live count after the round.

        Unified mode (default): admission is host bookkeeping only and
        the round is ONE ragged dispatch — newly admitted prompts join
        the current step's packed batch immediately, alongside every
        decoding row. Legacy mode replays the pre-unified pipeline
        (bucketed prefill waves + per-shape decode chunk). Speculative
        mode folds draft verification into the same single dispatch
        (``_step_spec``)."""
        if self._mesh is not None:
            params = self._place_params(params)
        if self._speculative:
            n = self._step_spec(params)
        elif self._unified:
            n = self._step_unified(params)
        else:
            n = self._step_legacy(params)
        if memory_armed[0]:
            # the memory half of the per-step audit: byte split by class
            # + per-request holdings + byte conservation, run alongside
            # check_conservation (one list index when disarmed)
            self._note_memory(params)
        return n

    def _note_memory(self, params) -> None:
        """Feed the HBM ledger one accounting round (armed only): model
        weights (once per params object), the pool's page split with the
        speculative-tail attribution, and the prefix-cache stats.

        Invariant-checked engines feed EVERY step — the byte
        conservation audit rides alongside ``check_conservation``. An
        engine that opted out of per-step invariant checking (the
        latency-critical large-pool configuration) decimates its feed
        to every 16th step: the common armed-step cost collapses to one
        counter bump, and the books refresh on that cadence instead."""
        if not self._check_invariants:
            self._mem_tick += 1
            if self._mem_tick & 15:
                return
        # cheap on the cached path (identity + fingerprint dict hit);
        # the ledger itself guards against id reuse across dead pytrees
        memory_ledger.note_weights(params)
        reserved = None
        if self._speculative:
            reserved = {self._slot_rid[s]: int(self._reserved[s])
                        for s in range(self.num_slots)
                        if self._slot_rid[s] is not None}
        memory_ledger.observe(
            self.mgr, reserved=reserved,
            cache_stats=self.cache.stats if self.cache is not None
            else None,
            audit=self._check_invariants)

    def _step_legacy(self, params) -> int:
        self._admit(params)
        if not self._live:
            if self._check_invariants:
                self.mgr.check_conservation()
            return 0
        fresh_chunk = self._decode_chunk is None
        if fresh_chunk:
            recompiles.record_miss("cbe.decode_chunk",
                                   (self.num_slots, self.chunk))
            self._decode_chunk = self._build_decode_chunk()
            c0 = time.perf_counter()
        self._rng, sub = jax.random.split(self._rng)
        t0_ns = time.perf_counter_ns() if spans_armed() else 0
        toks, self._tok_dev, self.mgr.k_pages, self.mgr.v_pages = \
            self._decode_chunk(params, self._tok_dev,
                               jnp.asarray(self._pos), self.mgr.k_pages,
                               self.mgr.v_pages, jnp.asarray(self._bt), sub)
        if fresh_chunk:
            jax.block_until_ready(toks)
            recompiles.observe_compile("cbe.decode_chunk",
                                       time.perf_counter() - c0)
        toks = np.asarray(toks)                    # the one fence
        if t0_ns:
            t1_ns = time.perf_counter_ns()
            for s in range(self.num_slots):
                rid = self._slot_rid[s]
                if rid is None:
                    continue
                req = self._live[rid]
                emit_span("engine.decode_chunk", t0_ns, t1_ns,
                          event_type="Operator", trace_id=req.trace_id,
                          args={"request_id": rid, "slot": s,
                                "chunk": self.chunk})
        for s in range(self.num_slots):
            if self._slot_rid[s] is None:
                continue
            if self._deliver_tokens(s, toks[s]):
                self._pos[s] += self.chunk
        # idle slots decode into the garbage page; their host positions
        # stay pinned at 0 so they never run past the rope cache
        if self.cache is not None:
            if self._check_invariants:
                # the ownership-model anchor: every page is free, live
                # (refcounted) or cached — checked after EVERY step
                self.mgr.check_conservation()
            self.cache.update_gauges()
        return len(self._live)

    # -- unified ragged step (the default serving path) ----------------------

    def _set_row_sampler(self, s: int, req: "_Request") -> None:
        """Write one admitted request's sampler/grammar parameters into
        the per-row device arrays (lazy ``.at[s].set``, same discipline
        as the token carry). ALWAYS runs — a greedy request resets the
        slot, so reuse never inherits a retired row's temperature or a
        stale grammar state."""
        self._samp_dev = _sampling.set_row(self._samp_dev, s, req.sampler)
        g = -1
        if req.grammar is not None:
            # arena rows are the grammar block rebased by its offset:
            # global = (gstart - local start) + local host-mirror state
            g = req.gstart - req.grammar.start + req.gstate_host
        self._gstate_dev = self._gstate_dev.at[s].set(jnp.int32(g))

    def _epilogue_active(self) -> bool:
        """Any live request exercising the sampling epilogue (sampled or
        constrained rows) — gates the armed ``cbe.sample_epilogue``
        profiling tap."""
        return any(r.sampler is not None or r.grammar is not None
                   for r in self._live.values())

    def enable_fused_tail(self) -> "ContinuousBatchingEngine":
        """Install the profile-guided decode-tail megaregion (the
        fusion pass's ``decode_tail`` region). Idempotent. Enabled
        before the first step it keeps the engine's ONE compile-cache
        miss; flipping mid-serve drops the compiled program and rebuilds
        on the next step — a counted miss, same contract as a baked-in
        flags flip."""
        if not self._unified:
            raise ValueError(
                "fused_tail megakernel-izes the unified ragged step; "
                "construct with unified=True")
        if not self._fused_tail:
            self._fused_tail = True
            self._unified_step = None
            self._spec_step = None
        return self

    def _build_unified_step(self):
        """ONE compiled program for every step the engine will ever run:
        ``chunk`` micro-rounds of the ragged model step
        (models.llama.ragged_step) under one ``lax.scan``. Per micro-round
        every decoding row advances one token (its sampled carry feeds
        back in-program, so a chunk still costs one host round-trip) and
        prefilling rows consume the next span of their prompt from the
        host-planned packed layout. Shapes depend only on (slots, chunk,
        step_tokens, table width) — the request mix, prompt lengths and
        admission timing never recompile anything."""
        L = self._L
        mcfg = self.model_config
        n_rows = self.num_slots
        mesh, mp_axis = self._mesh, self._mp_axis
        # lazy epilogue: until the first sampler/grammar submit the
        # program traces the argmax-only tail and no grammar mask —
        # the pre-sampling compute graph at the pre-sampling compile
        # cost (greedy output is byte-identical either way)
        epilogue = self._epilogue_on
        tail = _sampling.sample_rows if epilogue else _sampling.greedy_rows
        if self._fused_tail:
            # the fused decode-tail twin: SAME compute graph (the
            # builder receives the model step + sampling epilogue as
            # injected callables) fed from the packed plan —
            # byte-identical emitted tokens, one compile, two plan
            # uploads
            from ..jit import fusion as _fusion

            def model_step(params, ids, token_row, positions, kv_lens,
                           last_idx, k_pages, v_pages, bt, gst, gtable):
                hook = (lambda lg: _constrain.mask_logits(
                    lg.astype(jnp.float32), gst, gtable)) \
                    if epilogue else None
                return L.ragged_step(
                    params, ids, token_row, positions, kv_lens, last_idx,
                    k_pages, v_pages, bt, mcfg, mesh=mesh,
                    mp_axis=mp_axis, logits_epilogue=hook)

            return _fusion.build_fused_unified_step(
                model_step, tail, n_rows)

        def run(params, ids, use_carry, token_row, positions, kv_lens,
                last_idx, sample_mask, tok, gstate, samp, gtable,
                k_pages, v_pages, bt):
            def micro(carry, xs):
                tok, gst, kp, vp = carry
                ids_k, uc_k, tr_k, pos_k, kvl_k, li_k, sm_k = xs
                row_c = jnp.clip(tr_k, 0, n_rows - 1)
                # decode slots take the row's carry token (last sample);
                # prefill slots take the host-fed prompt tokens
                ids_eff = jnp.where(uc_k, jnp.take(tok, row_c), ids_k)
                # the grammar mask rides the model's logits-epilogue
                # hook: applied BEFORE the sampling epilogue so
                # constrained rows renormalize over legal tokens only
                # (an exact no-op for unconstrained rows — greedy
                # byte-identity)
                hook = (lambda lg: _constrain.mask_logits(
                    lg.astype(jnp.float32), gst, gtable)) \
                    if epilogue else None
                logits, kp, vp = L.ragged_step(
                    params, ids_eff, tr_k, pos_k, kvl_k, li_k, kp, vp,
                    bt, mcfg, mesh=mesh, mp_axis=mp_axis,
                    logits_epilogue=hook)
                # the in-program sampling epilogue (sampling.sample_rows):
                # per-row temperature/top-k/top-p + counter-based PRNG
                # keyed on the token's sequence position (= this round's
                # kv_len), greedy rows bit-exact argmax. No key threads
                # through the carry — the position IS the counter.
                nxt, ngst = tail(logits, kvl_k, samp, gst, gtable)
                # emit the INPUT carry: step outputs chain across steps
                # and a finished prefill's first sample arrives with the
                # row's first decode round (same contract as the legacy
                # decode chunk)
                emit = tok
                tok = jnp.where(sm_k, nxt, tok)
                gst = jnp.where(sm_k, ngst, gst)
                return (tok, gst, kp, vp), emit

            (tok, gstate, k_pages, v_pages), toks = jax.lax.scan(
                micro, (tok, gstate, k_pages, v_pages),
                (ids, use_carry, token_row, positions, kv_lens, last_idx,
                 sample_mask))
            return toks, tok, gstate, k_pages, v_pages     # toks (K, R)

        return jax.jit(run, donate_argnums=(12, 13))

    def _plan_step(self):
        """Host-side layout of one unified step: simulate ``chunk``
        micro-rounds over the live slots, packing each round's tokens
        into the fixed ``step_tokens`` axis. Decode rows (no pending
        prompt) always claim one slot each; prefill rows share the
        remaining budget in slot order, transitioning to decode the
        round after their prompt completes. Returns the device metadata
        arrays plus host-only unpack masks; advances the slot mirrors
        (positions, pending suffixes)."""
        K, tb, n_rows = self.chunk, self._step_tokens, self.num_slots
        ids = np.zeros((K, tb), np.int32)
        use_carry = np.zeros((K, tb), bool)
        token_row = np.full((K, tb), -1, np.int32)
        positions = np.zeros((K, tb), np.int32)
        kv_lens = np.zeros((K, n_rows), np.int32)
        last_idx = np.zeros((K, n_rows), np.int32)
        sample_mask = np.zeros((K, n_rows), bool)
        emit = np.zeros((K, n_rows), bool)
        emit_counts = [0] * n_rows            # per-slot decode rounds
        fed = [0] * n_rows                    # prefill tokens consumed
        pos = self._pos.astype(np.int64).copy()
        rem = {s: len(self._pend[s]) for s in range(n_rows)
               if self._slot_rid[s] is not None and self._pend[s] is not None}
        for k in range(K):
            live = [s for s in range(n_rows)
                    if self._slot_rid[s] is not None]
            budget = tb - sum(1 for s in live if rem.get(s, 0) == 0)
            take = {}
            for s in live:
                if rem.get(s, 0) > 0:
                    take[s] = min(rem[s], budget)
                    budget -= take[s]
            cursor = 0
            for s in live:
                if rem.get(s, 0) > 0:          # prefilling
                    n = take[s]
                    if n == 0:
                        continue               # starved this round
                    sl = slice(cursor, cursor + n)
                    ids[k, sl] = self._pend[s][fed[s]:fed[s] + n]
                    token_row[k, sl] = s
                    positions[k, sl] = pos[s] + np.arange(n)
                    pos[s] += n
                    fed[s] += n
                    rem[s] -= n
                    last_idx[k, s] = cursor + n - 1
                    if rem[s] == 0:
                        # prompt complete: this round's last logits are
                        # the row's first sample (kept in the carry)
                        sample_mask[k, s] = True
                    cursor += n
                else:                          # decoding
                    use_carry[k, cursor] = True
                    token_row[k, cursor] = s
                    positions[k, cursor] = pos[s]
                    pos[s] += 1
                    last_idx[k, s] = cursor
                    sample_mask[k, s] = True
                    emit[k, s] = True
                    emit_counts[s] += 1
                    cursor += 1
                kv_lens[k, s] = pos[s]
        self._pos = pos.astype(np.int32)
        for s in list(rem):
            self._pend[s] = (None if rem[s] == 0
                             else self._pend[s][fed[s]:])
        return (ids, use_carry, token_row, positions, kv_lens, last_idx,
                sample_mask), emit, emit_counts, fed

    def _plan_step_packed(self):
        """Fused-tail planning: the same plan arrays as
        :meth:`_plan_step` packed into TWO int32 uploads
        (``jit.fusion.pack_plan``), with a vectorized fast path for the
        steady-state round where every live slot is decoding — the
        K×slots Python simulation collapses to a handful of numpy
        broadcasts (byte-equality with the generic planner is asserted
        in tests/test_fusion.py)."""
        from ..jit.fusion import pack_plan
        K, tb, n_rows = self.chunk, self._step_tokens, self.num_slots
        live = [s for s in range(n_rows)
                if self._slot_rid[s] is not None]
        if live and all(self._pend[s] is None for s in live):
            nl = len(live)
            lv = np.asarray(live, np.int64)
            ids = np.zeros((K, tb), np.int32)
            use_carry = np.zeros((K, tb), bool)
            use_carry[:, :nl] = True
            token_row = np.full((K, tb), -1, np.int32)
            token_row[:, :nl] = lv
            positions = np.zeros((K, tb), np.int32)
            base = self._pos[lv].astype(np.int64)
            k_col = np.arange(K, dtype=np.int64)[:, None]
            positions[:, :nl] = base[None, :] + k_col
            kv_lens = np.zeros((K, n_rows), np.int32)
            kv_lens[:, lv] = base[None, :] + k_col + 1
            last_idx = np.zeros((K, n_rows), np.int32)
            last_idx[:, lv] = np.arange(nl, dtype=np.int64)[None, :]
            sample_mask = np.zeros((K, n_rows), bool)
            sample_mask[:, lv] = True
            emit = np.zeros((K, n_rows), bool)
            emit[:, lv] = True
            self._pos[lv] = (base + K).astype(np.int32)
            emit_counts = [0] * n_rows
            for s in live:
                emit_counts[s] = K
            fed = [0] * n_rows
            plan = (ids, use_carry, token_row, positions, kv_lens,
                    last_idx, sample_mask)
        else:
            plan, emit, emit_counts, fed = self._plan_step()
        plan_tt, plan_tr = pack_plan(*plan)
        return plan_tt, plan_tr, emit, emit_counts, fed

    def _step_unified(self, params) -> int:
        """One ragged round: host-only admission, ONE dispatch serving
        the mixed prefill+decode batch, unpack. The single device→host
        transfer is the step's emitted tokens — identical host-fence
        discipline to the legacy path, minus its prefill dispatches."""
        picked = self._admit_pick()
        for s, req, pages, lp, nc in picked:
            self._slot_rid[s] = req.rid
            self._live[req.rid] = req
            self._pos[s] = nc                 # next position to write
            self._bt[s] = 0
            self._bt[s, :len(pages)] = pages
            # a warm/COW suffix row IS "a row whose first position > 0";
            # cold rows just start at 0 — one code path for all three
            # legacy programs
            self._pend[s] = np.asarray(req.prompt[nc:], np.int32)
            self._set_row_sampler(s, req)
        if not self._live:
            if self._check_invariants:
                self.mgr.check_conservation()
            return 0
        fresh = (self._unified_step is None
                 or self._unified_flags != _prefill_flags())
        if fresh:
            # the engine's ONE compile-cache miss (plus at most one
            # device remat): every later step reuses this program. A
            # set_flags flip of host state the program bakes in (see
            # _prefill_flags) is the ONE sanctioned extra miss — counted
            # here instead of silently serving the stale program.
            self._unified_flags = _prefill_flags()
            recompiles.record_miss(
                "cbe.unified_step",
                (self.num_slots, self.chunk, self._step_tokens,
                 self._table_width, self._fused_tail, self.num_chips,
                 self._epilogue_on)
                + self._unified_flags)
            self._unified_step = self._build_unified_step()
        # armed-only continuous-profiling taps: the plan -> dispatch ->
        # unpack phases are the fusion pass's decode_tail signature
        # (jit/fusion.py); disarmed cost is one list index per step
        armed_chain = _chain_armed[0]
        tc0 = time.perf_counter_ns() if armed_chain else 0
        if self._fused_tail:
            plan_tt, plan_tr, emit, emit_counts, fed = \
                self._plan_step_packed()
        else:
            plan, emit, emit_counts, fed = self._plan_step()
        if armed_chain:
            tc1 = time.perf_counter_ns()
            _note_chain(op_name="cbe.plan_step", dur_ns=tc1 - tc0)
            tc0 = tc1
        # tokens that actually run through prefill THIS step (cancelled
        # mid-prefill requests never inflate the skip-ratio math)
        self._prefill_tokens += sum(fed)
        if fresh:
            c0 = time.perf_counter()   # dispatch-only window, like legacy
        t0_ns = time.perf_counter_ns() if spans_armed() else 0
        if self._fused_tail:
            (toks, self._tok_dev, self._gstate_dev, self.mgr.k_pages,
             self.mgr.v_pages) = self._unified_step(
                params, jnp.asarray(plan_tt), jnp.asarray(plan_tr),
                self._tok_dev, self._gstate_dev, self._samp_dev,
                self._arena.device_table(), self.mgr.k_pages,
                self.mgr.v_pages, jnp.asarray(self._bt))
        else:
            (toks, self._tok_dev, self._gstate_dev, self.mgr.k_pages,
             self.mgr.v_pages) = self._unified_step(
                params, *(jnp.asarray(a) for a in plan),
                self._tok_dev, self._gstate_dev, self._samp_dev,
                self._arena.device_table(), self.mgr.k_pages,
                self.mgr.v_pages, jnp.asarray(self._bt))
        if fresh:
            jax.block_until_ready(toks)
            recompiles.observe_compile("cbe.unified_step",
                                       time.perf_counter() - c0)
        toks = np.asarray(toks)                    # the one fence
        if armed_chain:
            tc1 = time.perf_counter_ns()
            if self._fused_tail:
                _note_chain(op_name="cbe.fused_unified_step",
                            dur_ns=tc1 - tc0)
            else:
                _note_chain(op_name="cbe.unified_step", dur_ns=tc1 - tc0)
            if self._epilogue_active():
                # the sampling epilogue runs inside the dispatch above;
                # this zero-duration tap makes it visible to the fusion
                # pass's chain mining (REGIONS["sampling_epilogue"])
                _note_chain(op_name="cbe.sample_epilogue", dur_ns=0)
            tc0 = tc1
        if t0_ns:
            # per-request phase bookkeeping over the dispatch window:
            # the trace keeps its prefill/decode lanes even though both
            # ride one program. Runs EVERY armed step, so it only
            # updates the per-slot coalesced windows (a few list ops) —
            # spans materialise at phase change / retire, keeping the
            # armed loop inside bench_obs_overhead's budget
            t1_ns = time.perf_counter_ns()
            batch: list = []
            win = self._win
            for s in range(self.num_slots):
                if self._slot_rid[s] is None:
                    continue
                c = emit_counts[s]
                f = fed[s]
                if (c == 0) != (f == 0):
                    # steady-state single-phase round: extend the
                    # window inline (no function call — this branch is
                    # the armed hot path every decode step takes)
                    w = win[s]
                    kind = "decode" if c else "prefill"
                    if w is not None and w[0] == kind:
                        w[2] = t1_ns
                        w[3] += c or f
                        continue
                if f > 0:
                    self._note_win(s, "prefill", t0_ns, t1_ns, f, batch)
                if c:
                    self._note_win(s, "decode", t0_ns, t1_ns, c, batch)
            if batch:
                emit_spans(batch)
        for s in range(self.num_slots):
            if self._slot_rid[s] is None:
                continue
            if self._fused_tail and emit_counts[s] == self.chunk:
                # fused-tail fast unpack: the slot emitted every round,
                # so its column IS the emission (no K-wide mask filter)
                self._deliver_tokens(s, toks[:, s])
            else:
                self._deliver_tokens(
                    s, (toks[k, s] for k in range(self.chunk)
                        if emit[k, s]))
        if armed_chain:
            _note_chain(op_name="cbe.decode_tail",
                        dur_ns=time.perf_counter_ns() - tc0)
        if self.cache is not None:
            if self._check_invariants:
                # the ownership-model anchor: every page is free, live
                # (refcounted) or cached — checked after EVERY ragged
                # step, COW suffix rows included
                self.mgr.check_conservation()
            self.cache.update_gauges()
        return len(self._live)

    # -- speculative decoding (draft + verify in ONE ragged dispatch) --------

    def _build_spec_step(self):
        """ONE compiled program for every speculative round the engine
        will ever run: a single ragged model step whose logits are taken
        at EVERY packed candidate index (``cand_idx`` — the generalized
        ``last_idx`` of ``models.llama.ragged_step``) and argmax'd
        in-program. A speculating row's span ``[carry, d1..dk]`` is just
        a short prefill at consecutive positions under the kernel's one
        ``key_pos <= position`` mask rule, so the per-candidate greedy
        tokens that come back ARE the verifier: ``g[j]`` is the model's
        next token after the row's history + ``span[0..j]``, valid
        exactly while the drafted prefix matches — the host accepts the
        longest matching prefix plus the bonus token. Shapes depend only
        on (spec_tokens, slots*(k+1), table width) fixed at construction
        — the request mix, draft lengths and acceptance history never
        recompile anything."""
        L = self._L
        mcfg = self.model_config
        mesh, mp_axis = self._mesh, self._mp_axis
        n_rows, k1 = self.num_slots, self.spec_k + 1
        # lazy epilogue, spec flavour: argmax + prefix-match verify
        # until the first sampler/grammar submit (see _build_unified_step)
        tail = (_sampling.spec_sample_rows if self._epilogue_on
                else _sampling.spec_greedy_rows)
        if self._fused_tail:
            # fused decode tail, spec flavour: the same single ragged
            # dispatch plus the verify epilogue IN-PROGRAM — greedy rows
            # the vectorized accepted-prefix count, sampled rows the
            # rejection-sampling verifier (jit/fusion.py)
            from ..jit import fusion as _fusion

            def model_step(params, ids, token_row, positions, kv_lens,
                           cand_idx, k_pages, v_pages, bt):
                return L.ragged_step(params, ids, token_row, positions,
                                     kv_lens, cand_idx, k_pages, v_pages,
                                     bt, mcfg, mesh=mesh, mp_axis=mp_axis)

            return _fusion.build_fused_spec_step(
                model_step, tail, self.spec_k, n_rows)

        def run(params, ids, token_row, positions, kv_lens, cand_idx,
                drafts, draft_len, sampled, gstate, samp, gtable,
                k_pages, v_pages, bt):
            logits, kp, vp = L.ragged_step(
                params, ids, token_row, positions, kv_lens, cand_idx,
                k_pages, v_pages, bt, mcfg, mesh=mesh, mp_axis=mp_axis)
            # the speculative sampling epilogue (spec_sample_rows):
            # greedy rows keep the per-candidate argmax + prefix-match
            # verify (byte-identical to the pre-sampling program),
            # sampled rows run lossless rejection sampling — the fence
            # stays (slots, k+1) int32 + (slots,) accepted instead of
            # shipping full (C, V) logits to the host
            lg = logits.reshape(n_rows, k1, -1)
            pos_base = jnp.take(positions,
                                cand_idx.reshape(n_rows, k1)[:, 0])
            toks, accepted, ngst = tail(
                lg, drafts, draft_len, pos_base, samp, gstate, gtable)
            # only rows that really committed a token advance their
            # grammar state (a mid-prefill constrained row's candidate
            # slot holds garbage)
            gstate = jnp.where(sampled, ngst, gstate)
            return toks, accepted, gstate, kp, vp

        return jax.jit(run, donate_argnums=(12, 13))

    def _plan_spec(self):
        """Host layout of one speculative round. Every decode row claims
        a span of ``[carry] + up to spec_k drafted tokens`` — its page
        table grows to cover the speculative tail (``mgr.grow_to``);
        pool pressure or the block-table span shrink the draft, never
        fail the round. Prefill rows share the remaining packed budget
        exactly like ``_plan_step``'s single micro-round. Returns the
        device metadata arrays, the per-slot verify plan and the
        per-slot prefill-token counts."""
        T, n_rows = self._spec_tokens, self.num_slots
        k1 = self.spec_k + 1
        cap_tokens = self._table_width * self.page_size
        ids = np.zeros((T,), np.int32)
        token_row = np.full((T,), -1, np.int32)
        positions = np.zeros((T,), np.int32)
        # per-row padded drafts for the in-program verify epilogue
        # (both tails consume them since the rejection-sampling
        # verifier moved the accept/reject in-program)
        drafts = np.zeros((n_rows, max(self.spec_k, 1)), np.int32)
        draft_len = np.zeros((n_rows,), np.int32)
        # rows committing a token this round (spec spans + completed
        # prefills): gates the in-program grammar-state advance
        sampled = np.zeros((n_rows,), bool)
        kv_lens = np.zeros((n_rows,), np.int32)
        cand_idx = np.zeros((n_rows * k1,), np.int32)
        info: Dict[int, tuple] = {}
        fed = [0] * n_rows
        live = [s for s in range(n_rows) if self._slot_rid[s] is not None]
        spans: Dict[int, tuple] = {}
        armed = spans_armed()
        draft_spans: list = []
        for s in live:
            if self._pend[s] is not None:
                continue                      # prefilling: planned below
            rid = self._slot_rid[s]
            req = self._live[rid]
            d0_ns = time.perf_counter_ns() if armed else 0
            # committed history (prompt + delivered tokens; the last
            # delivered token IS the carry whose K/V this round writes)
            history = [int(t) for t in req.prompt] + req.tokens
            if req.grammar is not None:
                # constrained rows NEVER draft: candidates past the
                # carry would be verified against un-advanced grammar
                # states (the mask covers candidate 0 only), so an
                # accepted draft could smuggle an illegal token. One
                # candidate per round keeps every emitted token legal.
                draft = []
            else:
                draft = [int(t) for t in
                         self.drafter.draft(history, self.spec_k)]
            pos0 = int(self._pos[s])
            # clamp the draft to (a) the remaining token budget: a
            # round commits at most accepted+1 <= len(draft)+1 tokens
            # and _deliver_tokens trims at the budget, so positions
            # past rem-1 could never commit — verifying them would be
            # pure waste and the page they'd grow would be freed right
            # back; (b) the row's block-table span (the model clips
            # positions past it into the last slot, which would corrupt
            # real pages)
            rem = self._budget(req) - len(req.tokens)
            draft = draft[:max(0, min(self.spec_k, rem - 1,
                                      cap_tokens - 1 - pos0))]
            # ensure the page table covers the span. With the budget
            # clamp above the span sits inside the admission
            # reservation and this is a no-op; it is the engine's
            # safety net (and the hook a lazy-allocation admission mode
            # would grow through — mgr.grow_to/truncate_pages are
            # exercised as the speculative substrate by the kvcache
            # interleaving property test). Under pool pressure the
            # draft shrinks; the carry's own slot always fits.
            while True:
                try:
                    self.mgr.grow_to(rid, pos0 + len(draft) + 1)
                    break
                except MemoryError:
                    draft.pop()
            tbl = self.mgr._tables[rid]
            self._bt[s] = 0
            self._bt[s, :len(tbl)] = tbl
            if d0_ns:
                # host-side drafting (n-gram lookup / draft model +
                # speculative page growth) is its own timeline segment,
                # split from the verify dispatch (engine.spec_round)
                draft_spans.append(make_span(
                    "engine.spec_draft", d0_ns, time.perf_counter_ns(),
                    "Operator", req.trace_id,
                    args={"request_id": rid, "slot": s,
                          "drafted": len(draft)}))
            spans[s] = (pos0, [history[-1]] + draft, draft)
            if draft:
                drafts[s, :len(draft)] = draft
            draft_len[s] = len(draft)
        emit_spans(draft_spans)
        budget = T - sum(1 + len(d) for _, _, d in spans.values())
        cursor = 0
        for s in live:
            if s in spans:                    # decode: speculative span
                pos0, span, draft = spans[s]
                n = len(span)
                ids[cursor:cursor + n] = span
                token_row[cursor:cursor + n] = s
                positions[cursor:cursor + n] = pos0 + np.arange(n)
                kv_lens[s] = pos0 + n
                cand_idx[s * k1:s * k1 + n] = cursor + np.arange(n)
                info[s] = ("spec", pos0, draft)
                sampled[s] = True
                cursor += n
            else:                             # prefilling
                rem = len(self._pend[s])
                n = min(rem, budget)
                if n == 0:
                    continue                  # starved this round
                pos0 = int(self._pos[s])
                ids[cursor:cursor + n] = self._pend[s][:n]
                token_row[cursor:cursor + n] = s
                positions[cursor:cursor + n] = pos0 + np.arange(n)
                kv_lens[s] = pos0 + n
                budget -= n
                fed[s] = n
                self._pos[s] = pos0 + n
                if n == rem:
                    # prompt complete: this round's last logits are the
                    # row's first sample
                    cand_idx[s * k1] = cursor + n - 1
                    info[s] = ("first_sample",)
                    sampled[s] = True
                    self._pend[s] = None
                else:
                    self._pend[s] = self._pend[s][n:]
                cursor += n
        return ((ids, token_row, positions, kv_lens, cand_idx), info, fed,
                drafts, draft_len, sampled)

    def _verify_spec(self, toks, info, accepted):
        """Host commit over the dispatch's per-row verified tokens
        (``toks (slots, k+1)``, ``accepted (slots,)`` — both computed
        in-program by the verify/sampling epilogue, fused and unfused
        alike): deliver the accepted drafted prefix plus the epilogue's
        token at the first rejected lane (greedy: the model's own
        argmax; sampled: the rejection-sampling residual draw / the
        bonus draw), roll the paged KV back on rejection, deliver
        through the shared ``_deliver_tokens`` contract (callbacks,
        budget/EOS retire, reentrant cancel)."""
        for s in sorted(info):
            rid = self._slot_rid[s]
            if rid is None:
                continue                    # retired by a reentrant cancel
            entry = info[s]
            if entry[0] == "first_sample":
                self._deliver_tokens(s, [int(toks[s, 0])])
                continue
            _, pos0, draft = entry
            a = min(int(accepted[s]), len(draft))
            committed = pos0 + a + 1        # carry + accepted drafts
            self.spec.note_verify(len(draft), a)
            if a < len(draft):
                # rejection rollback: stale K/V *within* kept pages is
                # overwritten before anything attends to it (scatter-
                # first), but a page that exists only for rejected
                # positions is stranded — deref/free it now, never
                # dropping below the admission reservation
                keep = max(self.mgr.pages_for(committed),
                           int(self._reserved[s]))
                freed = self.mgr.truncate_pages(rid, keep)
                tbl = self.mgr._tables[rid]
                self._bt[s] = 0
                self._bt[s, :len(tbl)] = tbl
                self.spec.note_rollback(len(freed))
                emit_event("spec_rollback", request_id=rid,
                           trace_id=self._live[rid].trace_id,
                           drafted=len(draft), accepted=a,
                           freed_pages=len(freed))
            self._pos[s] = committed
            self.mgr._lens[rid] = committed
            self._deliver_tokens(
                s, [int(t) for t in draft[:a]] + [int(toks[s, a])])

    def _step_spec(self, params) -> int:
        """One speculative round: host-only admission, drafting + page
        growth, ONE dispatch whose candidate argmaxes verify every
        row's draft, host accept/reject + paged rollback. The single
        device→host transfer is the ``(slots*(spec_k+1),)`` candidate
        token vector — smaller than the unified step's emit matrix."""
        picked = self._admit_pick()
        for s, req, pages, lp, nc in picked:
            self._slot_rid[s] = req.rid
            self._live[req.rid] = req
            self._pos[s] = nc               # next position to write
            self._bt[s] = 0
            self._bt[s, :len(pages)] = pages
            self._pend[s] = np.asarray(req.prompt[nc:], np.int32)
            self._reserved[s] = len(pages)
            self._set_row_sampler(s, req)
        if not self._live:
            if self._check_invariants:
                self.mgr.check_conservation()
            return 0
        fresh = (self._spec_step is None
                 or self._spec_flags != _prefill_flags())
        if fresh:
            # the speculative engine's ONE compile-cache miss; a
            # set_flags flip of baked-in host state is the one
            # sanctioned extra (same contract as the unified step)
            self._spec_flags = _prefill_flags()
            recompiles.record_miss(
                "cbe.spec_step",
                (self.num_slots, self._spec_tokens, self.spec_k,
                 self._table_width, self._fused_tail, self.num_chips,
                 self._epilogue_on)
                + self._spec_flags)
            self._spec_step = self._build_spec_step()
        armed_chain = _chain_armed[0]
        tc0 = time.perf_counter_ns() if armed_chain else 0
        plan, info, fed, drafts, draft_len, sampled = self._plan_spec()
        if armed_chain:
            tc1 = time.perf_counter_ns()
            _note_chain(op_name="cbe.plan_step", dur_ns=tc1 - tc0)
            tc0 = tc1
        self._prefill_tokens += sum(fed)
        if fresh:
            c0 = time.perf_counter()
        t0_ns = time.perf_counter_ns() if spans_armed() else 0
        # fused and unfused spec programs share one signature since the
        # verify/sampling epilogue moved in-program for both
        (toks, accepted, self._gstate_dev, self.mgr.k_pages,
         self.mgr.v_pages) = self._spec_step(
            params, *(jnp.asarray(a) for a in plan),
            jnp.asarray(drafts), jnp.asarray(draft_len),
            jnp.asarray(sampled), self._gstate_dev, self._samp_dev,
            self._arena.device_table(), self.mgr.k_pages,
            self.mgr.v_pages, jnp.asarray(self._bt))
        if fresh:
            jax.block_until_ready(toks)
            recompiles.observe_compile("cbe.spec_step",
                                       time.perf_counter() - c0)
        toks = np.asarray(toks)                    # the one fence
        accepted = np.asarray(accepted)
        if armed_chain:
            tc1 = time.perf_counter_ns()
            if self._fused_tail:
                _note_chain(op_name="cbe.fused_spec_step",
                            dur_ns=tc1 - tc0)
            else:
                _note_chain(op_name="cbe.spec_step", dur_ns=tc1 - tc0)
            if self._epilogue_active():
                _note_chain(op_name="cbe.sample_epilogue", dur_ns=0)
            tc0 = tc1
        if t0_ns:
            t1_ns = time.perf_counter_ns()
            batch = []
            for s in range(self.num_slots):
                rid = self._slot_rid[s]
                if rid is None:
                    continue
                req = self._live[rid]
                if fed[s] > 0:
                    batch.append(make_span(
                        "engine.prefill", t0_ns, t1_ns, "Operator",
                        req.trace_id,
                        args={"request_id": rid, "slot": s,
                              "prefill_tokens": int(fed[s])}))
                if info.get(s, ("",))[0] == "spec":
                    batch.append(make_span(
                        "engine.spec_round", t0_ns, t1_ns, "Operator",
                        req.trace_id,
                        args={"request_id": rid, "slot": s,
                              "drafted": len(info[s][2])}))
            emit_spans(batch)
        self._verify_spec(toks, info, accepted)
        if armed_chain:
            _note_chain(op_name="cbe.decode_tail",
                        dur_ns=time.perf_counter_ns() - tc0)
        if self._check_invariants:
            # the ownership-model anchor, now also covering draft
            # growth and rejection rollback: audited after EVERY
            # speculative step (spec mode runs it even cache-off — the
            # base manager grew an exclusive-ownership audit for this)
            self.mgr.check_conservation()
        if self.cache is not None:
            self.cache.update_gauges()
        return len(self._live)

    def collect(self) -> Dict[int, list]:
        out = self._finished
        self._finished = {}
        return out

    def finished_checksum(self, rid: int) -> Optional[int]:
        """crc32 of the tokens ``_retire`` produced for ``rid`` (None if
        the request never finished, e.g. cancelled). Survives
        ``collect()`` so serving layers can stamp terminal journal
        frames after draining the finished map."""
        return self._finished_crc.get(rid)

    def serve(self, params, prompts) -> list:
        """Stream a list of prompts through the fixed slots; returns the
        generated token lists in submission order."""
        rids = [self.submit(p) for p in prompts]
        results: Dict[int, list] = {}
        while len(results) < len(rids):
            self.step(params)
            results.update(self.collect())
            if not self._live and not self._queue and \
                    len(results) < len(rids):
                raise RuntimeError("serve stalled with pending requests")
        return [results[r] for r in rids]
