"""Speculative decoding: drafters + verification bookkeeping for the
unified ragged step.

The engine-side mechanics live in ``decoding.ContinuousBatchingEngine``
(``speculative=True``); this module owns the two pieces that are policy,
not engine plumbing:

* **Drafters** — where candidate tokens come from. The default is
  :class:`NgramDrafter`, prompt-lookup *self*-drafting (no second
  model): propose the tokens that followed the most recent earlier
  occurrence of the history's trailing n-gram. Serving traffic is full
  of copied spans (templated prompts, quoted context, the quasi-cyclic
  tails greedy decoding settles into), so lookup drafts are free and
  surprisingly accurate. :class:`DraftModel` is the hook for a real
  draft model (a small Llama): anything with ``draft(history, k) ->
  tokens`` plugs into the engine unchanged.
* **Telemetry** — :class:`SpeculationTelemetry` declares the
  ``paddle_spec_*`` registry families (observability/catalog.py) and
  keeps the host-side mirror the benchmarks/``statusz`` read.

Why drafting composes with the ragged step for free: verifying k
drafted tokens is exactly a *short prefill* of k+1 tokens at
consecutive positions — the kernel's one mask rule
``key_pos <= position`` already covers it, and taking the model's
logits at every packed candidate index (instead of only each row's
last token) turns the single dispatch into the verifier. Greedy
accept/reject is then a host-side argmax comparison; the committed
stream is byte-identical to non-speculative greedy decoding by
construction (verify-then-commit).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Sequence

import numpy as np

from ..observability.registry import get_registry


class Drafter:
    """Pluggable draft-token source for the speculative engine.

    ``draft(history, k)`` proposes up to ``k`` continuation tokens for a
    row whose committed tokens (prompt + generated, most recent last)
    are ``history``. Returning fewer than ``k`` — or ``[]`` — is always
    legal: the row simply decodes plainly that round. Drafters must be
    pure host-side functions of the history (no device state), so a
    rejected draft leaves nothing to roll back outside the KV pool."""

    def draft(self, history: Sequence[int], k: int) -> List[int]:
        raise NotImplementedError


class NgramDrafter(Drafter):
    """Prompt-lookup self-drafting (no draft model).

    Find the longest trailing n-gram of the history (``max_ngram`` down
    to ``min_ngram``) that also occurs earlier, take the MOST RECENT
    earlier occurrence, and propose the ``k`` tokens that followed it.
    Longest-match-first keeps precision high; most-recent-first tracks
    the current cycle/template rather than a stale one."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"[{min_ngram}, {max_ngram}]")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def draft(self, history: Sequence[int], k: int) -> List[int]:
        h = np.asarray(history, np.int64)
        n_hist = len(h)
        if k <= 0 or n_hist < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, n_hist - 1),
                       self.min_ngram - 1, -1):
            pat = h[n_hist - n:]
            # all windows of length n except the trailing pattern itself;
            # a match must leave >= 1 continuation token
            wins = np.lib.stride_tricks.sliding_window_view(h, n)[:-1]
            hits = np.nonzero((wins == pat).all(axis=1))[0]
            if len(hits):
                start = int(hits[-1]) + n          # most recent match
                return [int(t) for t in h[start:start + k]]
        return []


class DraftModel(Drafter):
    """Draft-model hook: greedy-draft ``k`` tokens with a (much smaller)
    stacked-param Llama.

    The draft model runs cache-less over a right-padded ``window`` of
    the history — one compiled program total, k forwards per draft.
    That is deliberately the simplest correct thing: the hook exists so
    a real deployment can swap in a cached draft engine; the contract
    is only ``draft(history, k)``."""

    def __init__(self, params, config, window: int = 128):
        from ..models import llama as L
        import jax
        self.params = params
        self.config = config
        self.window = int(window)
        self._fwd = jax.jit(
            functools.partial(L.forward_stacked, config=config))

    def draft(self, history: Sequence[int], k: int) -> List[int]:
        import jax.numpy as jnp
        out: List[int] = []
        for _ in range(max(0, k)):
            tail = [int(t) for t in history][-self.window:]
            tail = (tail + out)[-self.window:]
            ids = np.zeros((1, self.window), np.int32)
            ids[0, :len(tail)] = tail
            logits = self._fwd(self.params, jnp.asarray(ids))
            out.append(int(jnp.argmax(
                logits[0, len(tail) - 1].astype(jnp.float32))))
        return out


class SpeculationTelemetry:
    """Registry families + host mirror for speculation health.

    One instance per speculative engine; ``replica`` is the label value
    (``ReplicaHandle`` stamps its replica id so the fleet view can tell
    the engines apart — a single-engine deployment keeps ``"0"``)."""

    def __init__(self, replica: str = "0"):
        self.replica = str(replica)
        self.stats: Dict[str, int] = {
            "rounds": 0, "drafted": 0, "accepted": 0, "rejected": 0,
            "rollbacks": 0, "rollback_pages": 0,
        }
        reg = get_registry()
        self._c_drafted = reg.counter(
            "paddle_spec_drafted_tokens_total",
            "draft tokens fed into speculative verification",
            labels=("replica",))
        self._c_accepted = reg.counter(
            "paddle_spec_accepted_tokens_total",
            "draft tokens verified equal to the greedy continuation",
            labels=("replica",))
        self._c_rejected = reg.counter(
            "paddle_spec_rejected_tokens_total",
            "draft tokens rejected (KV rolled back per row)",
            labels=("replica",))
        self._g_ratio = reg.gauge(
            "paddle_spec_acceptance_ratio",
            "cumulative accepted/drafted draft-token ratio",
            labels=("replica",))

    def note_verify(self, drafted: int, accepted: int) -> None:
        """Account one row's verify outcome (``accepted <= drafted``)."""
        self.stats["rounds"] += 1
        self.stats["drafted"] += drafted
        self.stats["accepted"] += accepted
        self.stats["rejected"] += drafted - accepted
        if drafted:
            self._c_drafted.inc(drafted, replica=self.replica)
            if accepted:
                self._c_accepted.inc(accepted, replica=self.replica)
            if drafted - accepted:
                self._c_rejected.inc(drafted - accepted,
                                     replica=self.replica)
            self._g_ratio.set(self.acceptance_ratio, replica=self.replica)

    def note_rollback(self, pages_freed: int) -> None:
        self.stats["rollbacks"] += 1
        self.stats["rollback_pages"] += pages_freed

    @property
    def acceptance_ratio(self) -> float:
        d = self.stats["drafted"]
        return self.stats["accepted"] / d if d else 0.0

    def snapshot(self) -> Dict[str, object]:
        out: Dict[str, object] = dict(self.stats)
        out["acceptance_ratio"] = round(self.acceptance_ratio, 4)
        return out
