"""Global device-mesh management — the TPU-native replacement for the
reference's process-group world.

Reference: CommunicateTopology builds a cartesian rank topology and one NCCL
communicator per axis-group (python/paddle/distributed/fleet/base/topology.py,
SURVEY.md §2.4 hybrid row). Here the SAME cartesian structure is ONE
``jax.sharding.Mesh`` whose named axes are the parallelism dimensions; "comm
groups" become mesh-axis handles, and collectives lower to XLA ICI/DCN ops.

Axis order follows the reference's hybrid order ["dp", "pp", "sharding",
"sep", "mp"] (+ "expert" folded over sharding×mp for MoE), so rank→coordinate
math matches Fleet's.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

HYBRID_ORDER = ("dp", "pp", "sharding", "sep", "mp")

_global_mesh: List[Optional[Mesh]] = [None]


def build_mesh(degrees: Dict[str, int], devices: Optional[Sequence] = None,
               order: Optional[Sequence[str]] = None) -> Mesh:
    """Build a Mesh over all devices with the hybrid axis order.

    degrees: mapping axis -> parallel degree; missing axes get 1. Any leftover
    device count is folded into 'dp'. ``order`` changes the device-assignment
    order (reference hybrid_configs['order']); axis names stay the same.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    axis_order = tuple(order) if order else HYBRID_ORDER
    if set(axis_order) != set(HYBRID_ORDER):
        missing = set(HYBRID_ORDER) - set(axis_order)
        axis_order = tuple(axis_order) + tuple(sorted(missing))
    degs = {ax: int(degrees.get(ax, 1)) for ax in axis_order}
    known = int(np.prod([d for d in degs.values()]))
    if degs["dp"] == 1 and n % known == 0 and n // known > 1:
        degs["dp"] = n // known
        known = n
    if known != n:
        raise ValueError(
            f"product of parallel degrees {degs} = {known} != #devices {n}")
    shape = tuple(degs[ax] for ax in axis_order)
    arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, axis_order)


def set_global_mesh(mesh: Mesh) -> None:
    _global_mesh[0] = mesh


def get_global_mesh() -> Optional[Mesh]:
    return _global_mesh[0]


def ensure_mesh(degrees: Optional[Dict[str, int]] = None) -> Mesh:
    if _global_mesh[0] is None:
        set_global_mesh(build_mesh(degrees or {}))
    return _global_mesh[0]


def axis_degree(axis: str) -> int:
    m = get_global_mesh()
    if m is None or axis not in m.shape:
        return 1
    return m.shape[axis]


def named_sharding(*spec) -> Optional[NamedSharding]:
    m = get_global_mesh()
    if m is None:
        return None
    return NamedSharding(m, P(*spec))


def current_axis_names() -> Tuple[str, ...]:
    m = get_global_mesh()
    return tuple(m.axis_names) if m is not None else ()


# ---------------------------------------------------------------------------
# Serving meshes (TP-sharded inference replicas + elastic resize)
# ---------------------------------------------------------------------------

def serving_mesh(num_chips: int, devices: Optional[Sequence] = None) -> Mesh:
    """A serving replica's TP mesh: ``num_chips`` devices on the ``mp``
    axis, every other hybrid axis 1. One replica of the sharded
    continuous-batching engine owns exactly one of these; the elastic
    resize controller rebuilds it over the surviving devices after a
    chip loss (``shrink_serving_mesh``)."""
    devs = list(devices if devices is not None else jax.devices())
    if num_chips < 1 or num_chips > len(devs):
        raise ValueError(
            f"serving mesh needs 1..{len(devs)} chips, got {num_chips}")
    return build_mesh({"mp": num_chips}, devices=devs[:num_chips])


def surviving_mp_degree(num_chips_left: int, num_kv_heads: int) -> int:
    """Largest TP degree usable after chip loss: the KV pool is
    head-sharded (whole GQA groups per chip), so the degree must divide
    ``num_kv_heads`` and fit the surviving chip count. Losing one chip
    of an mp=4 / 4-kv-head replica therefore re-shards to mp=2, not
    mp=3."""
    for d in range(min(max(num_chips_left, 1), num_kv_heads), 0, -1):
        if num_kv_heads % d == 0:
            return d
    return 1


def shrink_serving_mesh(mesh: Mesh, dead_chip: int,
                        num_kv_heads: int) -> Mesh:
    """The surviving serving mesh after ``dead_chip`` (an index into the
    mesh's flat device order) is lost: drop that device and rebuild at
    the largest head-divisible TP degree the survivors support. An
    out-of-range index raises — silently dropping nothing would report
    a "completed" resize that still contains the dead chip."""
    all_devs = mesh.devices.reshape(-1).tolist()
    if not 0 <= int(dead_chip) < len(all_devs):
        raise ValueError(
            f"dead chip index {dead_chip} outside the mesh's "
            f"{len(all_devs)} devices")
    devs = [d for i, d in enumerate(all_devs) if i != int(dead_chip)]
    if not devs:
        raise ValueError("mesh has no surviving devices")
    deg = surviving_mp_degree(len(devs), num_kv_heads)
    return serving_mesh(deg, devices=devs)
