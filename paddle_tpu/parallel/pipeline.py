"""Pipeline-parallel execution over a mesh axis (shard_map + ppermute).

TPU-native rebuild of the reference's PipelineParallel engine
(python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py +
pp_utils/p2p_communication.py — SURVEY.md §2.4 PP row). Instead of NCCL
send/recv between trainer processes, the whole pipeline is ONE compiled XLA
program: stages live on submeshes of the ``pp`` axis, activations rotate with
``lax.ppermute`` over ICI, and the microbatch loop is a ``lax.scan`` — XLA
overlaps the permute DMA with the next microbatch's compute, which is the
latency-hiding the reference gets from its separate comm stream.

Schedule: GPipe-style fill-drain (all-forward then AD-driven all-backward).
The bubble fraction is (S-1)/(M+S-1); interleaved/1F1B variants change peak
memory, not bubble math, and remat (jax.checkpoint on stage_fn) recovers the
memory the way 1F1B would.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from ..core.compat import axis_size


def pipeline_spmd(stage_fn: Callable, stage_params: Any, microbatches,
                  axis_name: str = "pp"):
    """Run inside shard_map. Executes the fill-drain pipeline.

    stage_fn(params, x) -> y : one stage's computation (same structure on
        every stage; per-stage weights come pre-sliced by shard_map).
    microbatches: (M, ...) — microbatch-major input, replicated over the pp
        axis (only stage 0 reads it).
    Returns (M, ...) outputs — valid on the LAST stage, zeros elsewhere.

    This is exactly the one-chunk-per-device special case of the
    interleaved schedule below; delegating keeps a single scan skeleton.
    """
    lifted = jax.tree_util.tree_map(lambda a: a[None], stage_params)
    return pipeline_spmd_interleaved(stage_fn, lifted, microbatches,
                                     num_chunks=1, axis_name=axis_name)


def last_stage_broadcast(x, axis_name: str = "pp"):
    """Broadcast the last pp-stage's value to all stages (psum of a mask)."""
    S = axis_size(axis_name)
    sid = lax.axis_index(axis_name)
    return lax.psum(jnp.where(sid == S - 1, x, jnp.zeros_like(x)), axis_name)


def stage_slice_info(axis_name: str = "pp"):
    """(stage_id, num_stages) inside shard_map."""
    return lax.axis_index(axis_name), axis_size(axis_name)


# ---------------------------------------------------------------------------
# Interleaved (virtual pipeline) schedule
# ---------------------------------------------------------------------------
def interleave_chunk_order(num_stages: int, num_chunks: int):
    """Host-side pre-permutation for the stacked chunk-param array.

    Model chunk j (contiguous layer block j of S*v) lives on device j % S
    (Megatron interleave assignment). shard_map shards the leading dim in
    contiguous blocks, so the stacked array must be reordered such that
    device d's block [d*v:(d+1)*v] holds model chunks (d, d+S, d+2S, ...):
    order[d*v + i] = d + i*S.
    """
    return [d + i * num_stages
            for d in range(num_stages) for i in range(num_chunks)]


def pipeline_spmd_interleaved(chunk_fn, chunk_params, microbatches,
                              num_chunks: int, axis_name: str = "pp"):
    """Interleaved virtual-pipeline schedule as ONE systolic scan.

    Reference: PipelineParallelWithInterleave (SURVEY.md §2.4 PP row).
    Each device holds ``v = num_chunks`` model chunks (chunk_params leaves:
    leading dim v, pre-arranged via :func:`interleave_chunk_order`). Every
    scan tick performs exactly one chunk-step per device and one ring
    ppermute; the work item of device d at tick t is

        w = t - d,  local chunk slot i = (w % (S*v)) // S,
        microbatch m = (w // (S*v)) * S + (w % S)

    which makes the ring deliver precisely the activation each device
    needs one tick before it needs it (the Megatron interleave order,
    with chunk boundaries crossing the ring seam d=S-1 → d=0 landing on
    slot i+1). Fill/drain bubble: S-1 *chunk*-ticks out of M*v + S - 1
    total — the v-fold bubble reduction over fill-drain, expressed so XLA
    overlaps the ppermute DMA with the next tick's compute.

    microbatches: (M, ...) with M % S == 0, replicated over the pp axis.
    Returns (M, ...) outputs — valid on the LAST stage, zeros elsewhere.
    """
    S = axis_size(axis_name)
    d = lax.axis_index(axis_name)
    v = num_chunks
    M = microbatches.shape[0]
    if v > 1 and M % S != 0:
        # the (slot, m) decomposition below needs whole microbatch groups;
        # v == 1 reduces to m = w, valid for any M
        raise ValueError(f"microbatch count {M} must divide by stages {S}")
    bad = [a.shape[0] for a in jax.tree_util.tree_leaves(chunk_params)
           if a.shape[0] != v]
    if bad:
        # dynamic_index_in_dim clamps, which would silently reuse a chunk
        raise ValueError(
            f"chunk_params leaves must have leading dim {v}, got {bad}")
    total_work = M * v
    T = total_work + S - 1
    perm = [(i, (i + 1) % S) for i in range(S)]

    state = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    outs = jnp.zeros(microbatches.shape, microbatches.dtype)

    def step(carry, t):
        state, outs = carry
        w = t - d
        valid = jnp.logical_and(w >= 0, w < total_work)
        wc = jnp.clip(w, 0, total_work - 1)
        slot = (wc % (S * v)) // S
        m = (wc // (S * v)) * S + (wc % S)
        inject = microbatches[m]
        x = jnp.where(jnp.logical_and(d == 0, slot == 0), inject, state)
        p_slot = jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(a, slot, 0, keepdims=False),
            chunk_params)
        y = chunk_fn(p_slot, x)
        emit = jnp.logical_and(valid,
                               jnp.logical_and(d == S - 1, slot == v - 1))
        outs = jnp.where(
            emit, lax.dynamic_update_index_in_dim(outs, y, m, 0), outs)
        state = lax.ppermute(y, axis_name, perm)
        return (state, outs), None

    (state, outs), _ = lax.scan(step, (state, outs), jnp.arange(T))
    return outs


# ---------------------------------------------------------------------------
# True 1F1B: hand-scheduled forward+backward, bounded activation memory
# ---------------------------------------------------------------------------
def pipeline_1f1b(stage_fn: Callable, stage_params: Any, microbatches,
                  labels, loss_fn: Callable, axis_name: str = "pp",
                  head_params: Any = None, strip_stage_dim: bool = True,
                  input_grad_reducer: Callable = None,
                  input_grad_init: Any = None):
    """Memory-scheduled 1F1B pipeline: ONE scan carrying forward AND
    backward work, with per-stage activation buffers of depth 2S instead of
    the fill-drain schedule's M in-flight microbatches.

    Reference: PipelineParallel 1F1B (python/paddle/distributed/fleet/
    meta_parallel/pipeline_parallel.py — SURVEY.md §2.4 PP row). There, each
    microbatch's backward runs as soon as its grad arrives, freeing that
    microbatch's activations; here the same clock is compiled into one SPMD
    program:

        F(m, d) at tick  t = d + m              (fill-drain forward clock)
        B(m, d) at tick  t = 2S - 2 - d + m     (drains one tick behind the
                                                 downstream stage's B)

    Each tick a device runs (masked) one F and one B; boundary activations
    live in a (2S, ...) rotating buffer — slot m % 2S is written by F and
    consumed (then overwritten 2S microbatches later) by B, so peak
    activation memory is O(S · microbatch), independent of M. The backward
    recomputes the stage forward from the stored boundary input (jax.vjp at
    B time) — the same FLOP tradeoff as fill-drain + remat, but with the
    1F1B memory profile the reference gets from eager per-microbatch
    backward. Bubble: 2(S-1) of M + 2S - 2 ticks.

    stage_fn(p, x) -> y; loss_fn(y, label) -> scalar (applied on the LAST
    stage; its gradient seeds the backward).
    microbatches, labels: (M, ...) replicated over the pp axis.
    Returns (mean_loss, grads) — loss valid on the last stage (broadcast it
    with :func:`last_stage_broadcast`), grads a pytree like stage_params
    (each stage's slice holds ∑_m of ITS stage's param grads, fp32).

    Extensions for the hybrid train step (models/llama.py
    ``pipeline_schedule='1f1b'``):

    * ``head_params`` — pytree of trainable parameters consumed by the loss
      head; loss_fn's signature becomes ``loss_fn(head_params, y, label)``
      and the return gains ``head_grads`` (mean over microbatches, valid on
      the LAST stage — broadcast before use).
    * ``strip_stage_dim=False`` — stage_params arrive as each stage's local
      slice with an arbitrary leading dim (e.g. layers-per-stage for a
      scanned multi-layer stage) instead of the (1, ...) shard_map slice;
      returned grads keep that local shape (no stage-dim reinsertion).
    * ``input_grad_reducer`` / ``input_grad_init`` — fold each microbatch's
      input gradient into an accumulator AS IT IS PRODUCED:
      ``reducer(acc, gx, m_b) -> acc`` runs at every backward tick and its
      result is kept only on stage 0 for valid ticks (masked elsewhere), so
      d(mean loss)/d(inputs) reaches the caller as a REDUCED quantity (e.g.
      an embedding-gradient table) without carrying an O(microbatches)
      buffer through the scan — the 1F1B memory profile is preserved. The
      returned accumulator (divided by M, valid on stage 0, zeros
      elsewhere) is what chains the embedding backward.

    Return shape: ``(loss, grads[, head_grads][, input_grad_acc])`` — the
    optional entries appear only when requested.

    On ZB-H1 (reference passes/pipeline_scheduler_pass.py:§0): zero-bubble
    schedules split backward into dgrad (critical path) and wgrad (bubble
    filler) so idle drain slots do weight-gradient work. In this ONE-program
    systolic formulation every tick already issues the (masked) F and B
    branches on every device — there is no per-stage idle compute to fill;
    wall-clock is ticks x (F + vjp) regardless of where wgrad lands, so
    ZB-H1 degenerates to the same cost as this 1F1B. It would pay only in a
    per-stage-asynchronous (multi-executable) runtime, which trades away the
    XLA-fused single program; deliberately out of scope.
    """
    S = axis_size(axis_name)
    d = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    depth = 2 * S
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]
    T = M + 2 * S - 2

    if strip_stage_dim:
        # shard_map slices the stacked (S, ...) params to (1, ...) per
        # stage; drop that stage dim so stage_fn sees its own weights
        bad = [a.shape[0] for a in jax.tree_util.tree_leaves(stage_params)
               if a.shape[0] != 1]
        if bad:
            raise ValueError(
                f"stage_params leaves must arrive stage-sliced (leading dim "
                f"1 under shard_map in_specs P(axis)), got leading dims {bad}")
        stage_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)

    x_shape = microbatches.shape[1:]
    last = S - 1

    def fwd_only(p, x):
        return stage_fn(p, x)

    def step(carry, t):
        fwd_state, grad_state, act_buf, gacc, loss_acc, hacc, gin = carry

        # ---- forward tick: F(m_f, d) at t = d + m_f --------------------
        m_f = jnp.clip(t - d, 0, M - 1)
        f_valid = jnp.logical_and(t - d >= 0, t - d < M)
        x_in = jnp.where(d == 0, microbatches[m_f], fwd_state)
        y = stage_fn(stage_params, x_in)
        slot_f = m_f % depth
        act_buf = jnp.where(
            f_valid,
            lax.dynamic_update_index_in_dim(act_buf, x_in, slot_f, 0),
            act_buf)

        # ---- backward tick: B(m_b, d) at t = 2S-2-d + m_b --------------
        wb = t - (2 * S - 2 - d)
        m_b = jnp.clip(wb, 0, M - 1)
        b_valid = jnp.logical_and(wb >= 0, wb < M)
        x_saved = lax.dynamic_index_in_dim(act_buf, m_b % depth, 0,
                                           keepdims=False)
        # one vjp per tick; the seed is the loss gradient on the last stage
        # and the ring-received gy elsewhere
        lab = labels[m_b]
        y_b, vjp = jax.vjp(fwd_only, stage_params, x_saved)
        if head_params is not None:
            loss_m, loss_vjp = jax.vjp(
                lambda hp, yy: loss_fn(hp, yy, lab), head_params, y_b)
            gh, gy_loss = loss_vjp(jnp.ones((), loss_m.dtype))
        else:
            loss_m, gy_loss = jax.value_and_grad(
                lambda yy: loss_fn(yy, lab))(y_b)
        is_last = d == last
        gy = jnp.where(is_last, gy_loss.astype(y_b.dtype), grad_state)
        gp, gx = vjp(gy)

        gacc = jax.tree_util.tree_map(
            lambda acc, g: acc + jnp.where(b_valid, g, 0.0).astype(acc.dtype),
            gacc, gp)
        loss_acc = loss_acc + jnp.where(
            jnp.logical_and(b_valid, is_last), loss_m, 0.0)
        if head_params is not None:
            on_last = jnp.logical_and(b_valid, is_last)
            hacc = jax.tree_util.tree_map(
                lambda acc, g: acc + jnp.where(on_last, g, 0.0)
                .astype(acc.dtype), hacc, gh)
        if input_grad_reducer is not None:
            # fold d loss_m / d microbatches[m_b] into the accumulator,
            # exact on stage 0 where the injection happened; masked so
            # other stages contribute zeros (the reducer may contain
            # collectives, so it runs unconditionally on every device)
            reduced = input_grad_reducer(gin, gx, m_b)
            keep = jnp.logical_and(b_valid, d == 0)
            gin = jax.tree_util.tree_map(
                lambda new, old: jnp.where(keep, new, old), reduced, gin)

        # ---- rings ------------------------------------------------------
        fwd_state = lax.ppermute(jnp.where(f_valid, y, jnp.zeros_like(y)),
                                 axis_name, fwd_perm)
        grad_state = lax.ppermute(jnp.where(b_valid, gx, jnp.zeros_like(gx)),
                                  axis_name, bwd_perm)
        return (fwd_state, grad_state, act_buf, gacc, loss_acc, hacc,
                gin), None

    fwd0 = jnp.zeros(x_shape, microbatches.dtype)
    grad0 = jnp.zeros(x_shape, microbatches.dtype)
    buf0 = jnp.zeros((depth,) + x_shape, microbatches.dtype)
    gacc0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, jnp.float32), stage_params)
    hacc0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, jnp.float32), head_params) \
        if head_params is not None else jnp.zeros((), jnp.float32)
    gin0 = input_grad_init if input_grad_reducer is not None \
        else jnp.zeros((), jnp.float32)
    carry, _ = lax.scan(
        step, (fwd0, grad0, buf0, gacc0, jnp.zeros((), jnp.float32),
               hacc0, gin0), jnp.arange(T))
    _, _, _, gacc, loss_acc, hacc, gin = carry
    # mean-over-microbatches semantics for every output (matches
    # grad(mean_m loss_m)); with strip_stage_dim restore the stage dim so
    # out_specs P(axis) reassembles the stack
    if strip_stage_dim:
        gacc = jax.tree_util.tree_map(lambda a: a[None] / M, gacc)
    else:
        gacc = jax.tree_util.tree_map(lambda a: a / M, gacc)
    res = (loss_acc / M, gacc)
    if head_params is not None:
        res = res + (jax.tree_util.tree_map(lambda a: a / M, hacc),)
    if input_grad_reducer is not None:
        res = res + (jax.tree_util.tree_map(lambda a: a / M, gin),)
    return res
