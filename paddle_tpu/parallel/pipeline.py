"""Pipeline-parallel execution over a mesh axis (shard_map + ppermute).

TPU-native rebuild of the reference's PipelineParallel engine
(python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py +
pp_utils/p2p_communication.py — SURVEY.md §2.4 PP row). Instead of NCCL
send/recv between trainer processes, the whole pipeline is ONE compiled XLA
program: stages live on submeshes of the ``pp`` axis, activations rotate with
``lax.ppermute`` over ICI, and the microbatch loop is a ``lax.scan`` — XLA
overlaps the permute DMA with the next microbatch's compute, which is the
latency-hiding the reference gets from its separate comm stream.

Schedule: GPipe-style fill-drain (all-forward then AD-driven all-backward).
The bubble fraction is (S-1)/(M+S-1); interleaved/1F1B variants change peak
memory, not bubble math, and remat (jax.checkpoint on stage_fn) recovers the
memory the way 1F1B would.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_spmd(stage_fn: Callable, stage_params: Any, microbatches,
                  axis_name: str = "pp"):
    """Run inside shard_map. Executes the fill-drain pipeline.

    stage_fn(params, x) -> y : one stage's computation (same structure on
        every stage; per-stage weights come pre-sliced by shard_map).
    microbatches: (M, ...) — microbatch-major input, replicated over the pp
        axis (only stage 0 reads it).
    Returns (M, ...) outputs — valid on the LAST stage, zeros elsewhere.
    """
    S = lax.axis_size(axis_name)
    sid = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    perm = [(i, (i + 1) % S) for i in range(S)]

    state = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    outs = jnp.zeros(microbatches.shape, microbatches.dtype)

    def step(carry, t):
        state, outs = carry
        # stage 0 injects microbatch t (clamped; beyond M the value is unused
        # because the corresponding output write is masked off downstream)
        inject = microbatches[jnp.clip(t, 0, M - 1)]
        state = jnp.where(sid == 0, inject, state)
        state = stage_fn(stage_params, state)
        # last stage emits microbatch t-(S-1) once the pipe is full
        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        valid = jnp.logical_and(sid == S - 1, t >= S - 1)
        outs = jnp.where(valid, lax.dynamic_update_index_in_dim(outs, state, out_idx, 0), outs)
        state = lax.ppermute(state, axis_name, perm)
        return (state, outs), None

    (state, outs), _ = lax.scan(step, (state, outs), jnp.arange(M + S - 1))
    return outs


def last_stage_broadcast(x, axis_name: str = "pp"):
    """Broadcast the last pp-stage's value to all stages (psum of a mask)."""
    S = lax.axis_size(axis_name)
    sid = lax.axis_index(axis_name)
    return lax.psum(jnp.where(sid == S - 1, x, jnp.zeros_like(x)), axis_name)


def stage_slice_info(axis_name: str = "pp"):
    """(stage_id, num_stages) inside shard_map."""
    return lax.axis_index(axis_name), lax.axis_size(axis_name)
