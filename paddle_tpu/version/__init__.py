"""``paddle_tpu.version`` — version info module (reference
python/paddle/version/__init__.py, generated at build time there)."""

full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
cuda_version = "False"     # reference reports the CUDA toolkit; none here
cudnn_version = "False"
xpu_version = "False"
istaged = False
commit = "unknown"
with_pip = True


def show():
    print(f"full_version: {full_version}")
    print(f"major: {major}")
    print(f"minor: {minor}")
    print(f"patch: {patch}")
    print(f"rc: {rc}")
    print(f"commit: {commit}")
    print(f"cuda: {cuda_version}")
    print(f"cudnn: {cudnn_version}")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version


def xpu():
    return xpu_version
