"""Process-local metrics for the fault-tolerant training runtime.

Counters (restores, corrupt checkpoints skipped, step retries, NaN
rollbacks, skipped steps, preempt flushes, save failures) plus a
save-latency histogram, exported the same two ways the serving sink is:
``summary()`` dict and Prometheus text.
"""

from __future__ import annotations

from typing import Dict

from ..core.histogram import Histogram


class ResilienceMetrics:
    def __init__(self, namespace: str = "paddle_resilience"):
        self.namespace = namespace
        self.counters: Dict[str, float] = {}
        self.save_latency_ms = Histogram()

    def inc(self, counter: str, by: float = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0.0) + by

    def get(self, counter: str) -> float:
        return self.counters.get(counter, 0.0)

    def observe_save_ms(self, value_ms: float) -> None:
        self.save_latency_ms.record(value_ms)
        self.inc("saves")

    def summary(self) -> Dict[str, object]:
        return {"counters": dict(self.counters),
                "save_latency_ms": self.save_latency_ms.summary()}

    def to_prometheus_text(self) -> str:
        ns = self.namespace
        lines = []
        for name in sorted(self.counters):
            lines.append(f"# TYPE {ns}_{name}_total counter")
            lines.append(f"{ns}_{name}_total {self.counters[name]:g}")
        h = self.save_latency_ms
        lines.append(f"# TYPE {ns}_save_latency_ms histogram")
        acc = 0
        for bound, n in zip(h.bounds, h.bucket_counts):
            acc += n
            lines.append(f'{ns}_save_latency_ms_bucket{{le="{bound:g}"}} {acc}')
        lines.append(f'{ns}_save_latency_ms_bucket{{le="+Inf"}} {h.count}')
        lines.append(f"{ns}_save_latency_ms_sum {h.sum:g}")
        lines.append(f"{ns}_save_latency_ms_count {h.count}")
        return "\n".join(lines) + "\n"
