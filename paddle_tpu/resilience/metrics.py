"""Process-local metrics for the fault-tolerant training runtime.

Counters (restores, corrupt checkpoints skipped, step retries, NaN
rollbacks, skipped steps, preempt flushes, save failures) plus a
save-latency histogram, exported the same two ways the serving sink is:
``summary()`` dict and Prometheus text. The sink registers into the
global :class:`~paddle_tpu.observability.registry.MetricsRegistry`
(namespace replaces on re-creation), so the process-wide ``/metrics``
document includes resilience alongside serving and runtime telemetry.
"""

from __future__ import annotations

from typing import Dict, List

from ..core.histogram import Histogram
from ..observability import format as _fmt
from ..observability.registry import get_registry


class ResilienceMetrics:
    def __init__(self, namespace: str = "paddle_resilience"):
        self.namespace = namespace
        self.counters: Dict[str, float] = {}
        self.save_latency_ms = Histogram()
        get_registry().register_sink(self.namespace, self._prometheus_lines,
                                     self.summary)

    def inc(self, counter: str, by: float = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0.0) + by

    def get(self, counter: str) -> float:
        return self.counters.get(counter, 0.0)

    def observe_save_ms(self, value_ms: float) -> None:
        self.save_latency_ms.record(value_ms)
        self.inc("saves")

    def summary(self) -> Dict[str, object]:
        return {"counters": dict(self.counters),
                "save_latency_ms": self.save_latency_ms.summary()}

    def _prometheus_lines(self) -> List[str]:
        ns = self.namespace
        lines: List[str] = []
        for name in sorted(self.counters):
            lines.extend(_fmt.counter_lines(f"{ns}_{name}_total",
                                            value=self.counters[name]))
        lines.extend(_fmt.histogram_lines(f"{ns}_save_latency_ms",
                                          self.save_latency_ms))
        return lines

    def to_prometheus_text(self) -> str:
        return "\n".join(self._prometheus_lines()) + "\n"
