"""``ResilientTrainer`` — the training loop that owns failure recovery.

Wraps a :class:`~paddle_tpu.distributed.checkpoint.TrainState` and a user
step function and guarantees forward progress through:

* **auto-resume** — on start, restore the newest *intact* durable
  checkpoint (corrupt ones skipped via checksums) and continue from its
  step;
* **preemption** — SIGTERM (or an injected preemption) finishes the
  current step, flushes a final durable save, and raises
  :class:`Preempted` so the supervisor can reschedule; nothing is lost;
* **NaN/Inf loss** — the offending step is rolled back by reloading the
  last good checkpoint and replaying (faults are one-shot, so the replay
  is clean); a step that keeps producing NaN beyond the budget is skipped;
* **transient step failures** — exceptions retry with bounded exponential
  backoff, then abort with a structured :class:`TrainingAborted`.

Because checkpoint round-trips are bit-exact (fp32/bf16 shards via npz)
and replay re-executes the same step function at the same step indices, a
chaos run converges to the *byte-identical* final state of an
uninterrupted run — the acceptance property tested in
``tests/test_resilience.py``.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Set

import numpy as np

from ..observability.events import emit_event
from ..observability.flight import flight_recorder
from ..observability.goodput import GoodputTracker, StragglerDetector
from ..observability.memory import (memory_armed, memory_ledger,
                                    pytree_nbytes)
from ..observability.step_timer import StepTimer
from ..observability.trace import trace_context
from .durable import (async_save_checkpoint, checkpoint_path, latest_step,
                      restore_train_state, save_checkpoint)
from .faults import ChaosError, FaultInjector
from .metrics import ResilienceMetrics

logger = logging.getLogger("paddle_tpu.resilience")


class Preempted(RuntimeError):
    """Raised after a preemption was handled cleanly: the final checkpoint
    is durable at ``checkpoint``; re-running the trainer resumes there."""

    def __init__(self, step: int, checkpoint: Optional[str]):
        super().__init__(
            f"preempted at step {step}; state flushed to {checkpoint!r}")
        self.step = step
        self.checkpoint = checkpoint


class TrainingAborted(RuntimeError):
    """Training gave up, with a structured reason."""

    def __init__(self, reason: str, step: int, **info: Any):
        super().__init__(f"training aborted at step {step}: {reason} "
                         f"{info or ''}".rstrip())
        self.reason = reason
        self.step = step
        self.info = info


@dataclass
class ResilienceConfig:
    checkpoint_dir: str
    save_interval: int = 100         # steps between durable saves
    keep: int = 3                    # retention: newest N checkpoints
    async_save: bool = True          # overlap shard IO with training
    max_step_retries: int = 3        # per-step exception retries
    retry_backoff: float = 0.05      # seconds; doubles per attempt
    retry_backoff_cap: float = 2.0
    max_nan_rollbacks: int = 2       # per-step; beyond it the step is skipped
    install_signal_handlers: bool = True
    fault_injector: Optional[FaultInjector] = None
    chaos_seed: Optional[int] = None  # build a seeded injector at run()
                                      # scaled to the actual run length
    # step-telemetry knobs (observability.StepTimer): tokens processed per
    # step for tokens/sec, model FLOPs per step + chip peak for the MFU
    # estimate; all optional (timer still reports host/device breakdown)
    tokens_per_step: int = 0
    flops_per_step: Optional[float] = None
    peak_flops_per_s: Optional[float] = None
    # straggler detection over per-step wall time (rolling MAD z-score;
    # flags count into paddle_stragglers_total and the event log)
    straggler_window: int = 32
    straggler_z: float = 4.0


class ResilientTrainer:
    def __init__(self, state, config: ResilienceConfig,
                 metrics: Optional[ResilienceMetrics] = None):
        self.state = state
        self.cfg = config
        self.metrics = metrics or ResilienceMetrics()
        self.step_timer = StepTimer(flops_per_step=config.flops_per_step,
                                    peak_flops_per_s=config.peak_flops_per_s)
        self.goodput = GoodputTracker()
        self.stragglers = StragglerDetector(window=config.straggler_window,
                                            z_threshold=config.straggler_z)
        self._goodput_hw = -1          # highest step already run in-process
        self._wasted_s = 0.0           # retry time inside the last step
        self.last_loss: Optional[float] = None
        self.resumed_from: Optional[int] = None
        self._pending = None           # in-flight AsyncSaveFuture
        self._pending_step: Optional[int] = None
        self._preempt_requested = False
        self._prev_handler = None
        self._handlers_installed = False
        self._nan_counts: Dict[int, int] = {}
        self._skip_steps: Set[int] = set()

    # -- checkpointing ------------------------------------------------------

    def resume(self) -> Optional[int]:
        """Restore the newest intact checkpoint into ``self.state``;
        returns the restored global step (None if nothing loadable)."""
        self._harvest(block=True)
        step = restore_train_state(self.state, self.cfg.checkpoint_dir,
                                   self.metrics)
        if step is not None:
            logger.info("auto-resume: restored step %d from %s", step,
                        self.cfg.checkpoint_dir)
        self.resumed_from = step
        return step

    def save(self, block: bool = False) -> Optional[str]:
        """Durable save at the current global step (async unless ``block``
        or the config says sync). Returns the committed path for a blocking
        save (None when it failed — failure is logged + counted; an interval
        save failing degrades durability but must not kill training).

        The time the TRAINING LOOP is blocked here — waiting out the
        previous async save, copying the state dict, the whole sync
        write — is goodput's ``checkpoint_stall`` bucket (overlapped
        async IO is free by construction)."""
        t_stall = time.perf_counter()
        try:
            self._harvest(block=True)  # serialize after the last save
            step = self.state.global_step
            sd = self.state.state_dict()
            if memory_armed[0]:
                # HBM ledger: the training side's resident state (params
                # + optimizer accumulators), dtype-aware, refreshed on
                # the save cadence — the "optimizer" class next to the
                # serving pool's kv_* classes
                memory_ledger.note_class("optimizer", pytree_nbytes(sd))
            if self.cfg.async_save and not block:
                self._pending = async_save_checkpoint(
                    sd, self.cfg.checkpoint_dir, step, keep=self.cfg.keep,
                    fault_injector=self.cfg.fault_injector)
                self._pending_step = step
                return None
            t0 = time.perf_counter()
            try:
                path = save_checkpoint(
                    sd, self.cfg.checkpoint_dir, step, keep=self.cfg.keep,
                    fault_injector=self.cfg.fault_injector)
            except Exception as e:
                self.metrics.inc("save_failures")
                emit_event("save_failure", step=step, error=repr(e))
                logger.warning("checkpoint save at step %d failed: %s",
                               step, e)
                return None
            self.metrics.observe_save_ms((time.perf_counter() - t0) * 1e3)
            return path
        finally:
            self.goodput.note("checkpoint_stall",
                              time.perf_counter() - t_stall)

    def _harvest(self, block: bool) -> None:
        """Collect the outcome of the in-flight async save, if any. A
        failed save degrades durability (logged + counted) but must not
        kill training — the next interval save re-establishes it."""
        fut = self._pending
        if fut is None:
            return
        if not block and not fut.done():
            return
        try:
            fut.result()
            self.metrics.observe_save_ms(
                getattr(fut, "elapsed_s", 0.0) * 1e3)
        except Exception as e:
            self.metrics.inc("save_failures")
            emit_event("save_failure", step=self._pending_step,
                       error=repr(e), asynchronous=True)
            logger.warning("async checkpoint save at step %s failed: %s",
                           self._pending_step, e)
        self._pending = None
        self._pending_step = None

    # -- signals / preemption -----------------------------------------------

    def _on_sigterm(self, signum, frame):  # noqa: ARG002 (signal signature)
        self._preempt_requested = True

    def _install_handlers(self) -> None:
        if not self.cfg.install_signal_handlers:
            return
        if threading.current_thread() is not threading.main_thread():
            return
        try:
            self._prev_handler = signal.signal(signal.SIGTERM,
                                               self._on_sigterm)
            self._handlers_installed = True
        except ValueError:  # non-main interpreter thread
            self._handlers_installed = False

    def _restore_handlers(self) -> None:
        if self._handlers_installed:
            signal.signal(signal.SIGTERM, self._prev_handler)
            self._handlers_installed = False

    def _simulate_preemption(self) -> None:
        self.metrics.inc("preemptions")
        if self._handlers_installed:
            os.kill(os.getpid(), signal.SIGTERM)  # the real signal path
        else:
            self._preempt_requested = True

    def _preempt_exit(self) -> "Preempted":
        """Flush a final durable checkpoint and build the Preempted error.
        If the flush itself fails, Preempted must NOT advertise a path that
        was never written — it points at the newest intact checkpoint
        instead (the one a rerun will actually resume from)."""
        self._harvest(block=True)
        path = self.save(block=True)
        self.metrics.inc("preempt_flushes")
        emit_event("preempt_flush", step=self.state.global_step,
                   checkpoint=path)
        if path is None:
            intact = latest_step(self.cfg.checkpoint_dir)
            path = (checkpoint_path(self.cfg.checkpoint_dir, intact)
                    if intact is not None else None)
        return Preempted(self.state.global_step, path)

    # -- failure handling ----------------------------------------------------

    def _step_with_retry(self, step_fn: Callable[[int], Any], step: int):
        delay = self.cfg.retry_backoff
        attempt = 0
        wasted = 0.0          # failed attempts + backoff -> goodput retry
        self._wasted_s = 0.0
        while True:
            t_attempt = time.perf_counter()
            try:
                fi = self.cfg.fault_injector
                if fi is not None and fi.fire("step_error", step):
                    raise ChaosError(f"injected step failure at step {step}")
                result = step_fn(step)
                self._wasted_s = wasted
                return result
            except (Preempted, TrainingAborted):
                raise
            except Exception as e:
                if attempt >= self.cfg.max_step_retries:
                    raise TrainingAborted(
                        "step_failed_after_retries", step,
                        retries=attempt, error=repr(e)) from e
                attempt += 1
                self.metrics.inc("step_retries")
                emit_event("step_retry", step=step, attempt=attempt,
                           error=repr(e), backoff_s=delay)
                logger.warning("step %d failed (%s); retry %d/%d in %.2fs",
                               step, e, attempt, self.cfg.max_step_retries,
                               delay)
                time.sleep(delay)
                waste = time.perf_counter() - t_attempt
                wasted += waste
                self.goodput.note("retry", waste)
                delay = min(delay * 2, self.cfg.retry_backoff_cap)

    def _rollback(self, offending_step: int, reason: str) -> None:
        """Reload the last good checkpoint and let the loop replay forward.
        One-shot faults will not re-fire during the replay, so a transient
        NaN converges back onto the uninterrupted trajectory."""
        t0 = time.perf_counter()
        # snapshot the moments leading into the rollback while they are
        # still in the flight ring (armed + dump_dir only, never raises)
        flight_recorder.auto_dump("nan_rollback")
        self._harvest(block=True)
        self.metrics.inc("nan_rollbacks")
        restored = restore_train_state(self.state, self.cfg.checkpoint_dir,
                                       self.metrics)
        if restored is None:
            raise TrainingAborted("no_intact_checkpoint", offending_step,
                                  detail=reason)
        emit_event("rollback", reason=reason, step=offending_step,
                   restored_step=restored)
        logger.warning("rolled back to step %d after %s at step %d",
                       restored, reason, offending_step)
        self.goodput.note("rollback_replay", time.perf_counter() - t0)

    def _note_nan(self, step: int) -> None:
        n = self._nan_counts.get(step, 0) + 1
        self._nan_counts[step] = n
        if n > self.cfg.max_nan_rollbacks:
            # genuinely divergent, not transient: skip it on replay
            self._skip_steps.add(step)
            self.metrics.inc("steps_skipped")
            emit_event("step_skipped", step=step, nan_count=n)
            logger.error("step %d produced NaN/Inf %d times; skipping it",
                         step, n)

    # -- the loop ------------------------------------------------------------

    def run(self, step_fn: Callable[[int], Any], num_steps: int,
            resume: bool = True) -> Dict[str, Any]:
        """Drive ``step_fn(step) -> loss`` until ``global_step`` reaches
        ``num_steps``, surviving crashes/preemptions/corruption along the
        way. Raises :class:`Preempted` after a clean preemption flush and
        :class:`TrainingAborted` when the failure budget is exhausted."""
        cfg = self.cfg
        t_run = time.perf_counter()
        # fresh accounting per run: a reused trainer must not bill a
        # previous run's buckets against this run's wall clock
        self.goodput = GoodputTracker()
        self.stragglers = StragglerDetector(window=cfg.straggler_window,
                                            z_threshold=cfg.straggler_z)
        self._goodput_hw = -1
        self._wasted_s = 0.0
        if cfg.fault_injector is None and cfg.chaos_seed is not None:
            # built here, where the real run length is known — seeding over
            # a huge fixed step space would schedule faults that never fire
            cfg.fault_injector = FaultInjector.seeded(cfg.chaos_seed,
                                                      num_steps=num_steps)
        if resume:
            t0 = time.perf_counter()
            self.resume()
            self.goodput.note("restart", time.perf_counter() - t0)
        if latest_step(cfg.checkpoint_dir) is None:
            # seed checkpoint: the rollback/preemption target must exist
            # before the first interval save
            self.save(block=True)
        self._install_handlers()
        try:
            while self.state.global_step < num_steps:
                step = self.state.global_step
                if self._preempt_requested:
                    raise self._preempt_exit()
                fi = cfg.fault_injector
                if fi is not None and fi.fire("preempt", step):
                    self._simulate_preemption()
                if step in self._skip_steps:
                    self.state.step()
                    continue
                with trace_context(step=step):
                    self.step_timer.begin()
                    loss = self._step_with_retry(step_fn, step)
                    lv = loss._value if hasattr(loss, "_value") else loss
                    self.step_timer.host_done()   # dispatch done; the
                    lf = float(np.asarray(lv))    # float() is the fence
                    step_s = self.step_timer.end(
                        tokens=cfg.tokens_per_step) or 0.0
                # goodput: the successful attempt's time (retries/backoff
                # were booked inside _step_with_retry) is productive only
                # when the step is NEW progress producing a finite loss;
                # a re-execution below the high-water mark is replay, a
                # NaN attempt is wasted work charged to the rollback
                useful_s = max(0.0, step_s - self._wasted_s)
                if not np.isfinite(lf):
                    self.goodput.note("rollback_replay", useful_s)
                    self._note_nan(step)
                    self._rollback(step, "nan_loss")
                    continue
                self.goodput.note(
                    "rollback_replay" if step <= self._goodput_hw
                    else "productive", useful_s)
                self._goodput_hw = max(self._goodput_hw, step)
                # judge only the successful attempt: retry/backoff time is
                # already counted in step_retries_total, and letting it in
                # would both misflag the step and pollute the MAD window
                z = self.stragglers.observe(useful_s, source="train_step")
                if z > self.stragglers.z_threshold:
                    emit_event("straggler", step=step,
                               step_ms=round(useful_s * 1e3, 3),
                               z=round(z, 2))
                self.last_loss = lf
                self.state.step()
                gs = self.state.global_step
                if cfg.save_interval and gs % cfg.save_interval == 0 \
                        and gs < num_steps:
                    self.save()
                if self._preempt_requested:
                    raise self._preempt_exit()
            # final state is always durable: a failed flush retries once
            # (a transient/injected fault is consumed) then aborts loudly
            # rather than reporting completion without a durable result
            if self.save(block=True) is None and self.save(block=True) is None:
                raise TrainingAborted("final_save_failed",
                                      self.state.global_step)
        finally:
            self._restore_handlers()
            self._harvest(block=True)
        return {"resumed_from": self.resumed_from,
                "end_step": self.state.global_step,
                "last_loss": self.last_loss,
                "skipped_steps": sorted(self._skip_steps),
                "metrics": self.metrics.summary(),
                "step_timer": self.step_timer.summary(),
                "goodput": self.goodput.finalize(
                    time.perf_counter() - t_run),
                "stragglers": self.stragglers.flagged}
