"""Durable checkpointing on top of ``distributed.checkpoint``.

Commit protocol: write the sharded checkpoint into a ``.tmp_step_<N>``
staging dir (every file atomically written + fsynced + CRC32'd by the
checkpoint package), atomically rename the staging dir to ``step_<N>``,
then flip the ``LATEST`` marker and GC old checkpoints. Load walks
``LATEST`` first, then the remaining checkpoints newest-first, verifying
checksums, and returns the newest *intact* one — a truncated or torn
checkpoint is logged, counted and skipped, never half-read.
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..distributed.checkpoint.async_save import (AsyncSaveFuture,
                                                 host_snapshot,
                                                 spawn_async_writer)
from ..distributed.checkpoint.load_state_dict import (load_state_dict,
                                                      read_metadata)
from ..distributed.checkpoint.save_state_dict import _BF16, save_state_dict
from ..distributed.checkpoint.utils import (CheckpointCorruptError,
                                            atomic_write, fsync_dir,
                                            unflatten_state_dict)

logger = logging.getLogger("paddle_tpu.resilience")

STEP_PREFIX = "step_"
STAGING_PREFIX = ".tmp_"
LATEST_MARKER = "LATEST"


def checkpoint_path(root: str, step: int) -> str:
    return os.path.join(root, f"{STEP_PREFIX}{int(step)}")


def list_checkpoints(root: str) -> List[Tuple[int, str]]:
    """Committed checkpoints under ``root``, oldest first."""
    out: List[Tuple[int, str]] = []
    if not os.path.isdir(root):
        return out
    for name in os.listdir(root):
        if not name.startswith(STEP_PREFIX):
            continue
        try:
            step = int(name[len(STEP_PREFIX):])
        except ValueError:
            continue
        full = os.path.join(root, name)
        if os.path.isdir(full):
            out.append((step, full))
    return sorted(out)


def latest_step(root: str) -> Optional[int]:
    """Step the ``LATEST`` marker points at (validated against disk), or
    the newest committed step dir when the marker is missing/stale."""
    marker = os.path.join(root, LATEST_MARKER)
    try:
        with open(marker, "r") as f:
            name = f.read().strip()
        if name.startswith(STEP_PREFIX) and \
                os.path.isdir(os.path.join(root, name)):
            return int(name[len(STEP_PREFIX):])
    except (OSError, ValueError):
        pass
    ckpts = list_checkpoints(root)
    return ckpts[-1][0] if ckpts else None


def _clean_staging(root: str) -> None:
    """Remove staging litter from crashed saves (saves are serialized, so
    any ``.tmp_*`` dir seen here is dead)."""
    if not os.path.isdir(root):
        return
    for name in os.listdir(root):
        if name.startswith(STAGING_PREFIX):
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)


def gc_checkpoints(root: str, keep: Optional[int]) -> List[int]:
    """Delete all but the newest ``keep`` committed checkpoints; returns
    the deleted steps. Stale staging dirs are cleaned regardless of
    ``keep`` — crash litter must not accumulate on the no-retention
    path."""
    deleted: List[int] = []
    _clean_staging(root)
    if keep is None or keep <= 0:
        return deleted
    ckpts = list_checkpoints(root)
    for step, path in ckpts[:-keep] if len(ckpts) > keep else []:
        shutil.rmtree(path, ignore_errors=True)
        deleted.append(step)
    if deleted:
        logger.info("checkpoint GC: dropped steps %s under %s", deleted, root)
    return deleted


def _commit(snapshot: Dict[str, Any], root: str, step: int,
            keep: Optional[int], fault_injector=None) -> str:
    """The write half of a durable save: stage → rename → LATEST → GC.
    Runs synchronously on the caller's thread or an async writer thread."""
    os.makedirs(root, exist_ok=True)
    staging = os.path.join(root, f"{STAGING_PREFIX}{STEP_PREFIX}{int(step)}")
    if os.path.isdir(staging):
        shutil.rmtree(staging)
    if fault_injector is not None and fault_injector.fire("write_fail", step):
        fault_injector.leave_partial_staging(staging)
        raise IOError(
            f"injected write failure during checkpoint save at step {step}")
    save_state_dict(snapshot, staging)
    final = checkpoint_path(root, step)
    if os.path.isdir(final):
        shutil.rmtree(final)
    os.rename(staging, final)  # atomic commit: the dir appears whole or not
    fsync_dir(root)
    atomic_write(os.path.join(root, LATEST_MARKER),
                 lambda f: f.write(f"{STEP_PREFIX}{int(step)}".encode()))
    if fault_injector is not None and fault_injector.fire("truncate_shard",
                                                          step):
        fault_injector.truncate_shard(final)
    gc_checkpoints(root, keep)  # also sweeps dead staging from past crashes
    return final


def save_checkpoint(state_dict: Dict[str, Any], root: str, step: int,
                    keep: Optional[int] = None,
                    fault_injector=None) -> str:
    """Durably save ``state_dict`` as ``<root>/step_<step>`` (sync)."""
    snapshot = host_snapshot(state_dict)
    return _commit(snapshot, root, step, keep, fault_injector)


def async_save_checkpoint(state_dict: Dict[str, Any], root: str, step: int,
                          keep: Optional[int] = None,
                          fault_injector=None) -> AsyncSaveFuture:
    """Durable save with the device→host snapshot taken now and the staged
    commit running on a background thread (serialized after any in-flight
    async save). ``result()`` returns the committed ``step_<N>`` path."""
    snapshot = host_snapshot(state_dict)
    fut = AsyncSaveFuture()
    fut.path = checkpoint_path(root, step)
    t0 = time.perf_counter()

    def write():
        _commit(snapshot, root, step, keep, fault_injector)
        fut.elapsed_s = time.perf_counter() - t0

    return spawn_async_writer(fut, write)


# -- load side ---------------------------------------------------------------

def _np_dtype(name: str):
    return jnp.bfloat16 if name == _BF16 else np.dtype(name)


def _target_from_metadata(meta) -> Dict[str, Any]:
    """Build a state dict covering EVERY key the checkpoint holds (zeros of
    the right global shape/dtype). Loading into a freshly-constructed
    ``TrainState`` would otherwise silently drop keys the fresh process has
    not materialised yet — e.g. optimizer moments before the first step."""
    flat: Dict[str, Any] = {}
    for key, shards in meta.state_dict_metadata.items():
        if not shards:
            continue
        ndim = len(shards[0].local_shape)
        gshape = [0] * ndim
        for s in shards:
            for d in range(ndim):
                gshape[d] = max(gshape[d],
                                s.global_offset[d] + s.local_shape[d])
        flat[key] = Tensor(jnp.zeros(tuple(gshape),
                                     _np_dtype(shards[0].dtype)))
    for key, value in getattr(meta, "aux", {}).items():
        flat.setdefault(key, value)
    return unflatten_state_dict(flat, meta.flat_mapping)


def _candidates(root: str) -> List[Tuple[int, str]]:
    """Checkpoints to try, best first: LATEST's target, then newest-first."""
    ckpts = dict(list_checkpoints(root))
    order: List[int] = []
    marked = latest_step(root)
    if marked is not None and marked in ckpts:
        order.append(marked)
    order.extend(s for s in sorted(ckpts, reverse=True) if s not in order)
    return [(s, ckpts[s]) for s in order]


# A checkpoint raising any of these on load is unusable, not fatal: skip
# it and fall back to the next-newest candidate. Deliberately narrow —
# the load path wraps every decode failure in CheckpointCorruptError, so
# a shape/key mismatch from an INTACT but incompatible checkpoint (e.g.
# the model changed) must surface, not silently restart from scratch.
_UNUSABLE = (CheckpointCorruptError, FileNotFoundError, OSError)


def _first_intact(root: str, load, metrics=None):
    """(step, load(path)) for the newest candidate that loads cleanly;
    unusable ones are logged, counted and skipped. (None, None) if none."""
    for step, path in _candidates(root):
        try:
            return step, load(path)
        except _UNUSABLE as e:
            logger.warning("skipping unusable checkpoint %s: %s", path, e)
            if metrics is not None:
                metrics.inc("corrupt_checkpoints_skipped")
    return None, None


def load_latest_checkpoint(state_dict: Dict[str, Any], root: str,
                           metrics=None) -> Optional[int]:
    """Fill ``state_dict`` from the newest *intact* checkpoint under
    ``root`` (checksums verified); corrupt/truncated ones are skipped with
    a warning. Returns the restored step, or None when nothing loadable
    exists."""
    step, _ = _first_intact(
        root, lambda path: load_state_dict(state_dict, path), metrics)
    return step


def restore_train_state(train_state, root: str,
                        metrics=None) -> Optional[int]:
    """Restore a ``TrainState`` from the newest intact checkpoint, building
    the load target from the checkpoint's own metadata so every saved key
    (including optimizer accumulators a fresh process has not created yet)
    round-trips. Returns the restored global step, or None."""

    def load(path):
        target = _target_from_metadata(read_metadata(path))
        load_state_dict(target, path)
        return target

    step, target = _first_intact(root, load, metrics)
    if step is None:
        return None
    train_state.set_state_dict(target)
    if metrics is not None:
        metrics.inc("restores")
    return train_state.global_step
