"""Deterministic fault injection for the training runtime.

A :class:`FaultInjector` holds a *schedule* of faults — either written out
explicitly or generated from a seed — and the runtime asks it at
well-known points whether a fault fires:

===================  ======================================================
event                asked by
===================  ======================================================
``write_fail``       ``durable._commit`` before writing (mid-save crash:
                     raises IOError, leaving only partial staging litter)
``truncate_shard``   ``durable._commit`` after commit (bitrot/torn-disk
                     simulation: truncates a committed shard file in place)
``step_error``       ``ResilientTrainer`` before running a step (the step
                     raises; exercises bounded retry)
``preempt``          ``ResilientTrainer`` before running a step (SIGTERM
                     to self — the real preemption signal path)
``replica_die``      ``FleetRouter.step`` per replica (the replica raises
                     on every subsequent step — a dead engine)
``replica_stall``    ``FleetRouter.step`` per replica (the replica raises
                     for a bounded wall-clock window — a hung step the
                     watchdog would flag — then recovers)
``replica_slow``     ``FleetRouter.step`` per replica (each step sleeps
                     extra for a bounded window — a straggling replica)
``chip_die``         ``ElasticServingController.step`` per replica (one
                     chip of the replica's TP mesh dies: the replica is
                     hard-ejected, its flights fail over, and it
                     re-shards onto the surviving mesh)
``chip_degraded``    ``ElasticServingController.step`` per replica (a
                     chip must be retired but still answers: graceful
                     drain → re-shard → undrain, no failovers)
``host_die``         ``HostFleetRouter.step`` per host (the engine
                     PROCESS is killed: heartbeats stop, health walks
                     SUSPECT → EJECTED, flights fail over from their
                     snapshots)
``host_stall``       ``HostFleetRouter.step`` per host (the process
                     stops answering for a bounded window — missed
                     heartbeats without death — then recovers)
``link_slow``        ``HostFleetRouter.step`` per host (every transport
                     call to that host gains ``delay_s`` of injected
                     DCN latency for a bounded window)
===================  ======================================================

Each scheduled fault fires exactly once (``fire`` consumes it), so a
rollback-and-replay of the same step proceeds clean — which is what makes
chaos runs deterministic and byte-identical to uninterrupted ones. Tests
may schedule custom events (e.g. ``nan``) and query them from their own
step functions. ``fired`` records every (event, step) that triggered —
replica-scoped faults append ``(event, step, replica)``.

Replica scoping: a :class:`Fault` may carry a ``replica`` id. The router
asks ``fire(event, step, replica=r)`` for each replica every step; a
fault with ``replica=None`` acts as a wildcard (consumed by the first
replica that asks at its step), while a replica-scoped fault fires only
for its replica. The one-shot consumption contract is unchanged, so a
router chaos run replays byte-for-byte from the same schedule.

Chip scoping: chip-level events additionally carry a ``chip`` index into
the replica's TP mesh (``chip=None`` wildcards to whichever chip the
consumer defaults to — chip 0). The elastic controller asks
``fire_chip(event, step, replica=r)`` and receives the chip index, so a
seeded chip storm (``seeded_chips``) deterministically names WHICH chip
of WHICH replica dies at WHICH step.

Host scoping mirrors chip scoping one level up: a host-level event
carries a ``host`` id (an engine PROCESS, not a chip) and — for
``link_slow`` — a ``delay_s`` injected per-transport-call latency. The
multi-host router asks ``fire_host(event, step, host=h)`` and receives
the whole :class:`Fault` (it needs ``delay_s``); ``seeded_hosts``
generates reproducible host storms with the same one-per-target rule as
``seeded_chips``.

This module is also the only place allowed to write checkpoint bytes
outside the atomic-write helper — it exists to corrupt them on purpose.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple


class ChaosError(RuntimeError):
    """The injected step exception."""


#: JSON schema version for :meth:`FaultInjector.to_json` round-trips
FAULTS_SCHEMA_VERSION = 1

_FAULT_FIELDS = ("event", "step", "replica", "chip", "host", "delay_s")


def _fault_id(event: str, step: int, replica=None, chip=None,
              host=None) -> str:
    """Stable, human-greppable id for one firing: scope parts that
    don't apply render as ``-`` so ids align in logs."""
    return (f"{event}@s{int(step)}"
            f":r{replica if replica is not None else '-'}"
            f":c{chip if chip is not None else '-'}"
            f":h{host if host is not None else '-'}")


@dataclass(frozen=True)
class Fault:
    """One scheduled fault: ``event`` fires when the runtime reaches
    ``step`` (for save events, the step being saved). ``replica``
    narrows a fleet fault to one replica id (None = unscoped: trainer
    faults, or a wildcard consumed by the first replica that asks);
    ``chip`` narrows a chip-level event to one chip of that replica's
    TP mesh (None = the consumer's default chip)."""
    event: str
    step: int
    replica: Optional[int] = None
    chip: Optional[int] = None
    #: host (engine-process) id for host-level events; None = wildcard
    host: Optional[int] = None
    #: injected per-call transfer latency (seconds) for ``link_slow``
    delay_s: Optional[float] = None

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in _FAULT_FIELDS}

    @classmethod
    def from_dict(cls, d: dict) -> "Fault":
        return cls(event=str(d["event"]), step=int(d["step"]),
                   replica=d.get("replica"), chip=d.get("chip"),
                   host=d.get("host"), delay_s=d.get("delay_s"))


@dataclass
class FaultInjector:
    schedule: List[Fault] = field(default_factory=list)
    #: (event, step) for unscoped faults, (event, step, replica) for
    #: replica-scoped ones — unpack accordingly when a schedule mixes both
    fired: List[Tuple] = field(default_factory=list)
    #: parallel record stream with STABLE ids + fully resolved scope
    #: (wildcards filled with the consumer that fired them) — the
    #: black-box journal's ``fault`` frames and :meth:`to_json` carry
    #: these; the legacy ``fired`` tuples stay unchanged for tests
    fired_records: List[dict] = field(default_factory=list)

    def _record_fired(self, f: Fault, replica=None, chip=None,
                      host=None) -> None:
        rec = {"id": _fault_id(f.event, f.step, replica, chip, host),
               "event": f.event, "step": int(f.step),
               "replica": replica, "chip": chip, "host": host,
               "delay_s": f.delay_s}
        self.fired_records.append(rec)
        try:        # chaos fires inside failure paths: a torn journal
            # tap must never break the injection itself
            from ..observability.journal import journal, journal_armed
            if journal_armed[0]:
                journal.note_fault(rec)
        except Exception:
            pass

    # -- JSON round-trip (sharing chaos repros; replay rebuilds) -----------

    def to_json(self) -> dict:
        """The injector as a JSON-able document: remaining schedule +
        resolved fired records, versioned for skew rejection."""
        return {"schema_version": FAULTS_SCHEMA_VERSION,
                "schedule": [f.as_dict() for f in self.schedule],
                "fired": [dict(r) for r in self.fired_records]}

    @classmethod
    def from_json(cls, doc: dict) -> "FaultInjector":
        ver = doc.get("schema_version")
        if ver != FAULTS_SCHEMA_VERSION:
            raise ValueError(
                f"fault schedule schema_version={ver!r}, this tree "
                f"speaks {FAULTS_SCHEMA_VERSION}")
        inj = cls(schedule=[Fault.from_dict(d)
                            for d in doc.get("schedule", [])])
        inj.fired_records = [dict(r) for r in doc.get("fired", [])]
        return inj

    @classmethod
    def seeded(cls, seed: int, num_steps: int,
               events: Sequence[str] = ("write_fail", "truncate_shard",
                                        "step_error", "preempt"),
               n_faults: int = 4) -> "FaultInjector":
        """A reproducible random schedule: same seed → same faults."""
        import numpy as np
        rng = np.random.RandomState(seed)
        steps = rng.choice(max(num_steps, 1), size=n_faults, replace=True)
        kinds = rng.choice(len(events), size=n_faults)
        faults = sorted((Fault(events[int(k)], int(s))
                         for s, k in zip(steps, kinds)),
                        key=lambda f: (f.step, f.event))
        return cls(schedule=list(faults))

    def pending(self, event: Optional[str] = None) -> List[Fault]:
        return [f for f in self.schedule
                if event is None or f.event == event]

    @classmethod
    def seeded_replicas(cls, seed: int, num_steps: int, num_replicas: int,
                        events: Sequence[str] = ("replica_die",
                                                 "replica_stall",
                                                 "replica_slow"),
                        n_faults: int = 2) -> "FaultInjector":
        """A reproducible replica-scoped schedule for router chaos runs:
        same seed → same (event, step, replica) triples. Steps are
        1-based (1..num_steps) to match ``FleetRouter.step`` numbering —
        the router increments its counter before asking, so a step-0
        fault could never fire. Triples are unique: the router consumes
        at most one (event, step, replica) per step, so a duplicate
        could never fire and would silently thin the chaos run."""
        import numpy as np
        rng = np.random.RandomState(seed)
        num_steps = max(num_steps, 1)
        num_replicas = max(num_replicas, 1)
        n_faults = min(n_faults, num_steps * len(events) * num_replicas)
        faults: List[Fault] = []
        seen = set()
        while len(faults) < n_faults:
            f = Fault(events[int(rng.choice(len(events)))],
                      int(rng.choice(num_steps)) + 1,
                      replica=int(rng.choice(num_replicas)))
            if f in seen:
                continue
            seen.add(f)
            faults.append(f)
        faults.sort(key=lambda f: (f.step, f.event, f.replica))
        return cls(schedule=faults)

    @classmethod
    def seeded_chips(cls, seed: int, num_steps: int, num_replicas: int,
                     num_chips: int,
                     events: Sequence[str] = ("chip_die",
                                              "chip_degraded"),
                     n_faults: int = 1) -> "FaultInjector":
        """A reproducible chip-scoped schedule for elastic-resize chaos
        runs: same seed → same (event, step, replica, chip) quadruples.
        Steps are 1-based like ``seeded_replicas`` (the controller
        increments its counter before asking). At most one chip event
        per replica is scheduled — a second loss would re-shard a
        replica twice, which the acceptance suite exercises explicitly
        rather than by accident."""
        import numpy as np
        rng = np.random.RandomState(seed)
        num_steps = max(num_steps, 1)
        num_replicas = max(num_replicas, 1)
        n_faults = min(n_faults, num_replicas)
        faults: List[Fault] = []
        used_replicas = set()
        while len(faults) < n_faults:
            f = Fault(events[int(rng.choice(len(events)))],
                      int(rng.choice(num_steps)) + 1,
                      replica=int(rng.choice(num_replicas)),
                      chip=int(rng.choice(max(num_chips, 1))))
            if f.replica in used_replicas:
                continue
            used_replicas.add(f.replica)
            faults.append(f)
        faults.sort(key=lambda f: (f.step, f.event, f.replica, f.chip))
        return cls(schedule=faults)

    @classmethod
    def seeded_hosts(cls, seed: int, num_steps: int, num_hosts: int,
                     events: Sequence[str] = ("host_die", "host_stall",
                                              "link_slow"),
                     n_faults: int = 1,
                     delay_s: float = 0.05) -> "FaultInjector":
        """A reproducible host-scoped schedule for multi-host chaos
        runs: same seed → same (event, step, host) triples, with
        ``link_slow`` faults carrying ``delay_s`` of injected transfer
        latency. Steps are 1-based like ``seeded_replicas``; at most
        one event per host (a host that died AND stalls is one arc the
        acceptance suite builds explicitly, not by collision)."""
        import numpy as np
        rng = np.random.RandomState(seed)
        num_steps = max(num_steps, 1)
        num_hosts = max(num_hosts, 1)
        n_faults = min(n_faults, num_hosts)
        faults: List[Fault] = []
        used_hosts = set()
        while len(faults) < n_faults:
            ev = events[int(rng.choice(len(events)))]
            f = Fault(ev, int(rng.choice(num_steps)) + 1,
                      host=int(rng.choice(num_hosts)),
                      delay_s=(float(delay_s) if ev == "link_slow"
                               else None))
            if f.host in used_hosts:
                continue
            used_hosts.add(f.host)
            faults.append(f)
        faults.sort(key=lambda f: (f.step, f.event, f.host))
        return cls(schedule=faults)

    def fire_host(self, event: str, step: int,
                  host: Optional[int] = None) -> Optional[Fault]:
        """One-shot host-level match: returns (and consumes) the
        scheduled :class:`Fault` — the caller reads ``delay_s`` off it —
        or None. A host-scoped fault must match the queried host, an
        unscoped one wildcards, a host-scoped fault never fires for an
        unscoped query; ``fired`` records (event, step, host)."""
        for f in self.schedule:
            if f.event != event or f.step != int(step):
                continue
            if f.host is not None and (host is None
                                       or int(host) != f.host):
                continue
            self.schedule.remove(f)
            h = f.host if f.host is not None else (
                int(host) if host is not None else None)
            self.fired.append((event, int(step), h))
            self._record_fired(f, host=h)
            return f
        return None

    def _match(self, event: str, step: int,
               replica: Optional[int]) -> Optional[Fault]:
        """One-shot schedule matching shared by :meth:`fire` and
        :meth:`fire_chip`: (event, step) must equal exactly; a
        replica-scoped fault must match the queried replica, an
        unscoped fault acts as a wildcard, and a replica-scoped fault
        never fires for an unscoped query. The matched fault is
        consumed (removed from the schedule)."""
        for f in self.schedule:
            if f.event != event or f.step != int(step):
                continue
            if f.replica is not None and (replica is None
                                          or int(replica) != f.replica):
                continue
            self.schedule.remove(f)
            return f
        return None

    def fire_chip(self, event: str, step: int,
                  replica: Optional[int] = None,
                  default_chip: int = 0) -> Optional[int]:
        """Like :meth:`fire` for chip-level events, returning WHICH chip
        the fault names (``default_chip`` for wildcard-chip faults) or
        None when nothing is scheduled. Consumption/one-shot/replica-
        wildcard semantics match :meth:`fire`; ``fired`` records the
        full (event, step, replica, chip) quadruple."""
        f = self._match(event, step, replica)
        if f is None:
            return None
        chip = f.chip if f.chip is not None else int(default_chip)
        r = f.replica if f.replica is not None else (
            int(replica) if replica is not None else None)
        self.fired.append((event, int(step), r, chip))
        self._record_fired(f, replica=r, chip=chip)
        return chip

    def fire(self, event: str, step: int,
             replica: Optional[int] = None) -> bool:
        """True (and consume) iff a fault for (event, step) is scheduled.
        With ``replica`` given, replica-scoped faults must match it
        exactly; unscoped faults act as a wildcard. A replica-scoped
        fault never fires for an unscoped query."""
        f = self._match(event, step, replica)
        if f is None:
            return False
        if replica is None and f.replica is None:
            self.fired.append((event, int(step)))
            self._record_fired(f)
        else:
            r = f.replica if f.replica is not None else int(replica)
            self.fired.append((event, int(step), r))
            self._record_fired(f, replica=r)
        return True

    # -- corruption tools (deliberately non-atomic writes) ------------------

    def leave_partial_staging(self, staging_dir: str) -> None:
        """Simulate a crash mid-save: a half-written shard in the staging
        dir that never gets committed."""
        os.makedirs(staging_dir, exist_ok=True)
        with open(os.path.join(staging_dir, "0_0.distcp.npz"), "wb") as f:
            f.write(b"PK\x03\x04 torn write, process died here")

    def truncate_shard(self, ckpt_dir: str) -> str:
        """Truncate a committed shard file to half its size, as a torn disk
        or partial upload would — the checkpoint must now fail checksum
        verification and be skipped on load."""
        shards = sorted(n for n in os.listdir(ckpt_dir)
                        if n.endswith(".distcp.npz"))
        if not shards:
            raise FileNotFoundError(f"no shard files under {ckpt_dir!r}")
        victim = os.path.join(ckpt_dir, shards[0])
        size = os.path.getsize(victim)
        with open(victim, "r+b") as f:
            f.truncate(max(size // 2, 1))
        return victim
