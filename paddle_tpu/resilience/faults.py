"""Deterministic fault injection for the training runtime.

A :class:`FaultInjector` holds a *schedule* of faults — either written out
explicitly or generated from a seed — and the runtime asks it at
well-known points whether a fault fires:

===================  ======================================================
event                asked by
===================  ======================================================
``write_fail``       ``durable._commit`` before writing (mid-save crash:
                     raises IOError, leaving only partial staging litter)
``truncate_shard``   ``durable._commit`` after commit (bitrot/torn-disk
                     simulation: truncates a committed shard file in place)
``step_error``       ``ResilientTrainer`` before running a step (the step
                     raises; exercises bounded retry)
``preempt``          ``ResilientTrainer`` before running a step (SIGTERM
                     to self — the real preemption signal path)
===================  ======================================================

Each scheduled fault fires exactly once (``fire`` consumes it), so a
rollback-and-replay of the same step proceeds clean — which is what makes
chaos runs deterministic and byte-identical to uninterrupted ones. Tests
may schedule custom events (e.g. ``nan``) and query them from their own
step functions. ``fired`` records every (event, step) that triggered.

This module is also the only place allowed to write checkpoint bytes
outside the atomic-write helper — it exists to corrupt them on purpose.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple


class ChaosError(RuntimeError):
    """The injected step exception."""


@dataclass(frozen=True)
class Fault:
    """One scheduled fault: ``event`` fires when the runtime reaches
    ``step`` (for save events, the step being saved)."""
    event: str
    step: int


@dataclass
class FaultInjector:
    schedule: List[Fault] = field(default_factory=list)
    fired: List[Tuple[str, int]] = field(default_factory=list)

    @classmethod
    def seeded(cls, seed: int, num_steps: int,
               events: Sequence[str] = ("write_fail", "truncate_shard",
                                        "step_error", "preempt"),
               n_faults: int = 4) -> "FaultInjector":
        """A reproducible random schedule: same seed → same faults."""
        import numpy as np
        rng = np.random.RandomState(seed)
        steps = rng.choice(max(num_steps, 1), size=n_faults, replace=True)
        kinds = rng.choice(len(events), size=n_faults)
        faults = sorted((Fault(events[int(k)], int(s))
                         for s, k in zip(steps, kinds)),
                        key=lambda f: (f.step, f.event))
        return cls(schedule=list(faults))

    def pending(self, event: Optional[str] = None) -> List[Fault]:
        return [f for f in self.schedule
                if event is None or f.event == event]

    def fire(self, event: str, step: int) -> bool:
        """True (and consume) iff a fault for (event, step) is scheduled."""
        for f in self.schedule:
            if f.event == event and f.step == int(step):
                self.schedule.remove(f)
                self.fired.append((event, int(step)))
                return True
        return False

    # -- corruption tools (deliberately non-atomic writes) ------------------

    def leave_partial_staging(self, staging_dir: str) -> None:
        """Simulate a crash mid-save: a half-written shard in the staging
        dir that never gets committed."""
        os.makedirs(staging_dir, exist_ok=True)
        with open(os.path.join(staging_dir, "0_0.distcp.npz"), "wb") as f:
            f.write(b"PK\x03\x04 torn write, process died here")

    def truncate_shard(self, ckpt_dir: str) -> str:
        """Truncate a committed shard file to half its size, as a torn disk
        or partial upload would — the checkpoint must now fail checksum
        verification and be skipped on load."""
        shards = sorted(n for n in os.listdir(ckpt_dir)
                        if n.endswith(".distcp.npz"))
        if not shards:
            raise FileNotFoundError(f"no shard files under {ckpt_dir!r}")
        victim = os.path.join(ckpt_dir, shards[0])
        size = os.path.getsize(victim)
        with open(victim, "r+b") as f:
            f.truncate(max(size // 2, 1))
        return victim
