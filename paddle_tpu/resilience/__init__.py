"""``paddle_tpu.resilience`` — fault-tolerant training runtime.

Makes a multi-hour run survive crashes, preemptions and corrupted
checkpoints without human intervention (ISSUE 2 tentpole; the MPK lesson
from PAPERS.md 2512.22219: the runtime, not the user loop, owns failure
recovery).

Checkpoint layout (``durable.py``)
----------------------------------
::

    <root>/
      .tmp_step_<N>/        # staging dir while a save is in flight
      step_<N>/             # committed checkpoint (atomic dir rename)
        0_0.distcp.npz      # shard payload  (per-file CRC32 in metadata)
        0_0.distcp.dtypes
        0_0.metadata        # written LAST = rank-local commit point
      LATEST                # text marker "step_<N>", atomically replaced

Every file inside a checkpoint is written via
``distributed.checkpoint.utils.atomic_write`` (stage + fsync + rename),
the whole staging dir is renamed to ``step_<N>`` only once complete, and
``LATEST`` flips afterwards — so a crash at ANY instant leaves either the
previous checkpoint or a fully-committed new one, never a torn state.
Retention GC keeps the newest ``keep`` checkpoints. On load, per-shard
CRC32s are verified and a truncated/corrupt checkpoint is transparently
skipped in favor of the newest *intact* one.

Pieces
------
* ``durable``  — ``save_checkpoint`` / ``async_save_checkpoint`` /
  ``load_latest_checkpoint`` / ``restore_train_state`` / ``gc_checkpoints``.
* ``trainer``  — ``ResilientTrainer``: auto-resume, SIGTERM/preemption
  flush-and-exit, NaN/Inf loss rollback-and-replay, bounded step retry.
* ``faults``   — deterministic ``FaultInjector`` (seeded schedule of write
  failures, shard truncation, step exceptions, simulated preemption) used
  by the tests and the chaos-mode flag.
* ``metrics``  — counters + save-latency histogram, Prometheus text.
"""

from .durable import (  # noqa: F401
    async_save_checkpoint, checkpoint_path, gc_checkpoints, latest_step,
    list_checkpoints, load_latest_checkpoint, restore_train_state,
    save_checkpoint,
)
from .faults import ChaosError, Fault, FaultInjector  # noqa: F401
from .metrics import ResilienceMetrics  # noqa: F401
from .trainer import (  # noqa: F401
    Preempted, ResilienceConfig, ResilientTrainer, TrainingAborted,
)
