"""``paddle.vision.ops`` — detection operators.

Rebuild of python/paddle/vision/ops.py over the phi detection kernels
(nms, roi_align, yolo_box, distribute_fpn_proposals — SURVEY.md §2.1
kernel corpus; workload #5's serving tail). TPU-first: everything is
STATIC-shape — NMS returns a fixed-size keep mask ordered by score (the
caller slices by the returned count), roi_align is a bilinear gather XLA
fuses, and IoU matrices are one broadcasted elementwise block.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["box_iou", "nms", "roi_align", "yolo_box", "box_coder"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _iou_matrix(a, b):
    """(N,4),(M,4) xyxy -> (N,M) IoU (fp32)."""
    a = a.astype(jnp.float32)
    b = b.astype(jnp.float32)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0) * jnp.clip(a[:, 3] - a[:, 1], 0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0) * jnp.clip(b[:, 3] - b[:, 1], 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def box_iou(boxes1, boxes2, name=None):
    """Pairwise IoU of two xyxy box sets (paddle.vision.ops.box_iou... the
    reference iou_similarity surface)."""
    return apply(_iou_matrix, _t(boxes1), _t(boxes2), op_name="box_iou")


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k: Optional[int] = None,
        name=None):
    """paddle.vision.ops.nms — greedy IoU suppression.

    TPU-native formulation: sort by score, compute the (N,N) IoU matrix
    once, then one ``lax.scan`` pass marks each box suppressed iff an
    earlier KEPT box overlaps it beyond the threshold — the same greedy
    result as the reference's sequential CUDA kernel, with static shapes.
    With ``category_idxs``/``categories`` suppression is per-class
    (batched NMS via the coordinate-offset trick). Returns the kept box
    indices sorted by descending score (eager: 1-D int array of the kept
    count, truncated to ``top_k`` when given — matching paddle).
    """
    def fn(bx, *rest):
        n = bx.shape[0]
        if rest:
            sc = rest[0].astype(jnp.float32)
        else:
            sc = -jnp.arange(n, dtype=jnp.float32)  # document order
        work = bx.astype(jnp.float32)
        if len(rest) > 1:
            # per-class suppression: shift each class to a disjoint tile
            cat = rest[1].astype(jnp.float32)[:, None]
            span = jnp.max(work) - jnp.min(work) + 1.0
            work = work + cat * span
        order = jnp.argsort(-sc)
        sorted_boxes = work[order]
        iou = _iou_matrix(sorted_boxes, sorted_boxes)

        def step(kept, i):
            # suppressed iff any higher-scoring KEPT box overlaps > thr
            over = (iou[i] > iou_threshold) & kept & \
                (jnp.arange(n) < i)
            keep_i = ~jnp.any(over)
            return kept.at[i].set(keep_i), keep_i

        kept0 = jnp.zeros((n,), bool)
        _, keep_sorted = lax.scan(step, kept0, jnp.arange(n))
        return order, keep_sorted

    args = [_t(boxes)]
    if scores is not None:
        args.append(_t(scores))
        if category_idxs is not None:
            args.append(_t(category_idxs))
    elif category_idxs is not None:
        raise ValueError("category_idxs requires scores")
    order, keep = apply(fn, *args, op_name="nms", n_outputs=2)
    # eager tail: materialize the ragged index list the reference returns
    order_np = np.asarray(order._value)
    keep_np = np.asarray(keep._value).astype(bool)
    kept_idx = order_np[keep_np]
    if top_k is not None:
        kept_idx = kept_idx[:top_k]
    return Tensor(jnp.asarray(kept_idx.astype(np.int64)))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True, name=None):
    """paddle.vision.ops.roi_align: (N,C,H,W) features + per-image xyxy
    rois -> (total_rois, C, oh, ow) via bilinear sampling (reference phi
    roi_align kernel:§0).

    Deviation from the reference (documented per ADVICE r3 #4): with
    ``sampling_ratio=-1`` the reference derives an adaptive per-RoI grid
    (``ceil(roi_size / pooled_size)`` samples per bin), which is a
    data-dependent shape XLA cannot compile statically. This implementation
    uses a fixed 2×2 grid per bin instead — exact for RoIs up to 2× the
    pooled size per bin and a bounded-error approximation for larger RoIs
    (bilinear sampling at bin centers, tolerance-tested in
    tests/test_vision_ops.py). Pass an explicit ``sampling_ratio`` to match
    the reference on large RoIs."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    ratio = sampling_ratio if sampling_ratio > 0 else 2

    def fn(feat, rois, rois_num):
        n, c, h, w = feat.shape
        total = rois.shape[0]
        # roi -> image index from boxes_num prefix sums
        starts = jnp.cumsum(rois_num) - rois_num
        img_of = jnp.searchsorted(jnp.cumsum(rois_num),
                                  jnp.arange(total), side="right")
        del starts
        off = 0.5 if aligned else 0.0
        rb = rois.astype(jnp.float32) * spatial_scale - off
        x1, y1, x2, y2 = rb[:, 0], rb[:, 1], rb[:, 2], rb[:, 3]
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_w = rw / ow
        bin_h = rh / oh
        # sample grid: (total, oh, ow, ratio, ratio) bilinear points
        gy = (y1[:, None, None] + (jnp.arange(oh)[None, :, None] +
              (jnp.arange(ratio)[None, None, :] + 0.5) / ratio)
              * bin_h[:, None, None])            # (T, oh, ratio)
        gx = (x1[:, None, None] + (jnp.arange(ow)[None, :, None] +
              (jnp.arange(ratio)[None, None, :] + 0.5) / ratio)
              * bin_w[:, None, None])            # (T, ow, ratio)

        def bilinear(ix, iy, t_img):
            x0 = jnp.floor(ix)
            y0 = jnp.floor(iy)
            wx = ix - x0
            wy = iy - y0
            x0i = jnp.clip(x0.astype(jnp.int32), 0, w - 1)
            x1i = jnp.clip(x0i + 1, 0, w - 1)
            y0i = jnp.clip(y0.astype(jnp.int32), 0, h - 1)
            y1i = jnp.clip(y0i + 1, 0, h - 1)
            fm = feat[t_img]                         # (C, H, W)
            v00 = fm[:, y0i, x0i]
            v01 = fm[:, y0i, x1i]
            v10 = fm[:, y1i, x0i]
            v11 = fm[:, y1i, x1i]
            return (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
                    + v10 * (1 - wx) * wy + v11 * wx * wy)

        def per_roi(t):
            # (oh, ratio) x (ow, ratio) grid -> mean over samples
            yy = gy[t][:, None, :, None]             # (oh,1,ratio,1)
            xx = gx[t][None, :, None, :]             # (1,ow,1,ratio)
            yb = jnp.broadcast_to(yy, (oh, ow, ratio, ratio)).reshape(-1)
            xb = jnp.broadcast_to(xx, (oh, ow, ratio, ratio)).reshape(-1)
            vals = bilinear(xb, yb, img_of[t])       # (C, oh*ow*r*r)
            vals = vals.reshape(c, oh, ow, ratio * ratio)
            return vals.mean(axis=-1)

        return jax.vmap(per_roi)(jnp.arange(total))

    return apply(fn, _t(x), _t(boxes), _t(boxes_num), op_name="roi_align")


def yolo_box(x, img_size, anchors: Sequence[int], class_num: int,
             conf_thresh: float = 0.005, downsample_ratio: int = 32,
             clip_bbox: bool = True, scale_x_y: float = 1.0,
             iou_aware: bool = False, iou_aware_factor: float = 0.5,
             name=None):
    """paddle.vision.ops.yolo_box: raw YOLO head (N, A*(5+cls), H, W) ->
    decoded boxes (N, A*H*W, 4) xyxy in image pixels + scores
    (N, A*H*W, cls). Static shapes; conf_thresh zeroes scores (the
    reference's filtering semantics without ragged output)."""
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    A = anchors.shape[0]

    def fn(xv, imgs):
        n, ch, h, w = xv.shape
        v = xv.reshape(n, A, 5 + class_num, h, w).astype(jnp.float32)
        tx, ty, tw, th, obj = (v[:, :, 0], v[:, :, 1], v[:, :, 2],
                               v[:, :, 3], v[:, :, 4])
        cls = v[:, :, 5:]                         # (N, A, cls, H, W)
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        alpha = scale_x_y
        beta = -0.5 * (scale_x_y - 1.0)
        cx = (jax.nn.sigmoid(tx) * alpha + beta + gx) / w
        cy = (jax.nn.sigmoid(ty) * alpha + beta + gy) / h
        aw = anchors[:, 0][None, :, None, None]
        ah = anchors[:, 1][None, :, None, None]
        bw = jnp.exp(tw) * aw / (w * downsample_ratio)
        bh = jnp.exp(th) * ah / (h * downsample_ratio)
        im_h = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        im_w = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - bw / 2) * im_w
        y1 = (cy - bh / 2) * im_h
        x2 = (cx + bw / 2) * im_w
        y2 = (cy + bh / 2) * im_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0, im_w - 1)
            y1 = jnp.clip(y1, 0.0, im_h - 1)
            x2 = jnp.clip(x2, 0.0, im_w - 1)
            y2 = jnp.clip(y2, 0.0, im_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)  # (N,A,H,W,4)
        conf = jax.nn.sigmoid(obj)
        conf = jnp.where(conf > conf_thresh, conf, 0.0)
        scores = jax.nn.sigmoid(cls) * conf[:, :, None]
        boxes = boxes.reshape(n, A * h * w, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(
            n, A * h * w, class_num)
        return boxes, scores

    return apply(fn, _t(x), _t(img_size), op_name="yolo_box", n_outputs=2)


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size",
              box_normalized: bool = True, axis: int = 0, name=None):
    """paddle.vision.ops.box_coder (SSD-style box encode/decode)."""
    def fn(prior, var, target):
        prior = prior.astype(jnp.float32)
        target = target.astype(jnp.float32)
        norm = 0.0 if box_normalized else 1.0
        pw = prior[:, 2] - prior[:, 0] + norm
        ph = prior[:, 3] - prior[:, 1] + norm
        pcx = prior[:, 0] + pw * 0.5
        pcy = prior[:, 1] + ph * 0.5
        if var is not None:
            var = var.astype(jnp.float32)
        if code_type == "encode_center_size":
            tw = target[:, 2] - target[:, 0] + norm
            th = target[:, 3] - target[:, 1] + norm
            tcx = target[:, 0] + tw * 0.5
            tcy = target[:, 1] + th * 0.5
            out = jnp.stack([
                (tcx[:, None] - pcx[None, :]) / pw[None, :],
                (tcy[:, None] - pcy[None, :]) / ph[None, :],
                jnp.log(tw[:, None] / pw[None, :]),
                jnp.log(th[:, None] / ph[None, :])], axis=-1)
            if var is not None:
                # (M, 4) var pairs rows with priors; a (4,) var applies to
                # every prior (same handling as the decode branch)
                out = out / (var[None, :, :] if var.ndim == 2 else var)
            return out
        # decode_center_size: target (N, M, 4) deltas over priors
        t = target
        if var is not None:
            if var.ndim == 2:
                # var rows pair with priors, so they broadcast on the same
                # dim the prior statistics use: dim 1 when axis==0, dim 0
                # when axis==1 (ADVICE r3 #2).
                t = t * (var[None, :, :] if axis == 0 else var[:, None, :])
            else:
                t = t * var
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (pw[None, :], ph[None, :],
                                    pcx[None, :], pcy[None, :])
        else:
            pw_, ph_, pcx_, pcy_ = (pw[:, None], ph[:, None],
                                    pcx[:, None], pcy[:, None])
        ocx = t[..., 0] * pw_ + pcx_
        ocy = t[..., 1] * ph_ + pcy_
        ow_ = jnp.exp(t[..., 2]) * pw_
        oh_ = jnp.exp(t[..., 3]) * ph_
        return jnp.stack([ocx - ow_ * 0.5, ocy - oh_ * 0.5,
                          ocx + ow_ * 0.5 - norm,
                          ocy + oh_ * 0.5 - norm], axis=-1)

    pv = _t(prior_box_var) if prior_box_var is not None else None
    if pv is None:
        def fn2(prior, target):
            return fn(prior, None, target)
        return apply(fn2, _t(prior_box), _t(target_box), op_name="box_coder")
    return apply(fn, _t(prior_box), pv, _t(target_box), op_name="box_coder")
