"""Vision Transformer (ViT) on the fused attention/FFN blocks.

Workload #5's transformer-vision surface (SURVEY.md §6: ViT-L is one of
the five benchmark configs). Pre-LN encoder built from the same fused
incubate blocks as the language models — patch embedding is a strided
Conv2D (one MXU matmul per patch grid), class token + learned positions,
mean/cls pooling head. Reference surface: the model-zoo
VisionTransformer family.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ...incubate.nn.layer.fused_transformer import (
    FusedFeedForward, FusedMultiHeadAttention)
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.common_layers import Conv2D, LayerNorm, Linear
from ...nn.layer import Layer, LayerList


class PatchEmbed(Layer):
    def __init__(self, img_size=224, patch_size=16, in_chans=3, embed_dim=768):
        super().__init__()
        if img_size % patch_size:
            raise ValueError("img_size must divide by patch_size")
        self.num_patches = (img_size // patch_size) ** 2
        self.proj = Conv2D(in_chans, embed_dim, kernel_size=patch_size,
                           stride=patch_size)

    def forward(self, x):
        x = self.proj(x)                       # (B, E, H/p, W/p)
        b, e = x.shape[0], x.shape[1]
        return x.reshape([b, e, -1]).transpose([0, 2, 1])  # (B, N, E)


class ViTEncoderLayer(Layer):
    def __init__(self, embed_dim, num_heads, mlp_ratio=4.0, epsilon=1e-6):
        super().__init__()
        self.attn = FusedMultiHeadAttention(
            embed_dim, num_heads, normalize_before=True, epsilon=epsilon)
        self.ffn = FusedFeedForward(
            embed_dim, int(embed_dim * mlp_ratio), activation="gelu",
            normalize_before=True, epsilon=epsilon)

    def forward(self, x):
        return self.ffn(self.attn(x, causal=False))


class VisionTransformer(Layer):
    """ViT backbone + classification head (class_num=0 → features only)."""

    def __init__(self, img_size=224, patch_size=16, in_chans=3,
                 class_num=1000, embed_dim=768, depth=12, num_heads=12,
                 mlp_ratio=4.0, epsilon=1e-6, representation_size=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.patch_embed = PatchEmbed(img_size, patch_size, in_chans,
                                      embed_dim)
        n = self.patch_embed.num_patches
        self.cls_token = self.create_parameter(
            (1, 1, embed_dim), default_initializer=I.Normal(0.0, 0.02))
        self.pos_embed = self.create_parameter(
            (1, n + 1, embed_dim), default_initializer=I.Normal(0.0, 0.02))
        self.blocks = LayerList([
            ViTEncoderLayer(embed_dim, num_heads, mlp_ratio, epsilon)
            for _ in range(depth)])
        self.norm = LayerNorm(embed_dim, epsilon=epsilon)
        self.head = (Linear(embed_dim, class_num) if class_num > 0 else None)

    def forward_features(self, x):
        from ...core.dispatch import apply
        x = self.patch_embed(x)

        def add_tokens(xv, cls, pos):
            b = xv.shape[0]
            cls_b = jnp.broadcast_to(cls, (b,) + cls.shape[1:])
            return jnp.concatenate([cls_b, xv], axis=1) + pos

        x = apply(add_tokens, x, self.cls_token, self.pos_embed,
                  op_name="vit_tokens")
        for blk in self.blocks:
            x = blk(x)
        return self.norm(x)

    def forward(self, x):
        feats = self.forward_features(x)
        cls = feats[:, 0]
        return self.head(cls) if self.head is not None else cls


def vit_base_patch16_224(**kwargs):
    return VisionTransformer(img_size=224, patch_size=16, embed_dim=768,
                             depth=12, num_heads=12, **kwargs)


def vit_large_patch16_224(**kwargs):
    return VisionTransformer(img_size=224, patch_size=16, embed_dim=1024,
                             depth=24, num_heads=16, **kwargs)


def vit_tiny_test(**kwargs):
    """Small config for tests/CI."""
    base = dict(img_size=16, patch_size=4, in_chans=3, class_num=10,
                embed_dim=32, depth=2, num_heads=4)
    base.update(kwargs)
    return VisionTransformer(**base)


# ===========================================================================
# Functional stacked path (round 4): lax.scan over the encoder stack
# ===========================================================================
# The imperative module above runs ~400 separate parameter tensors through
# ~838 XLA fusions per train step (PROFILE_vit_r4) — per-tensor optimizer
# updates and per-layer kernel launches cap the measured MFU near 41%. The
# stacked form is the same TPU-first design the llama flagship uses
# (models/llama.py): per-layer weights stack on a leading L axis, the
# encoder runs as ONE lax.scan, and AdamW updates ~16 fused arrays.

import jax
from jax import lax

VIT_LAYER_KEYS = ("ln1_s", "ln1_b", "qkv_w", "qkv_b", "out_w", "out_b",
                  "ln2_s", "ln2_b", "f1_w", "f1_b", "f2_w", "f2_b")


def stacked_params_from_module(net: "VisionTransformer") -> dict:
    """Stack a VisionTransformer module's weights into the functional
    layout (leading L axis on per-layer tensors)."""
    # COPY leaves: the train step donates its params, and aliasing the
    # module's live buffers would invalidate the module after one step
    g = lambda p: jnp.array(p._value, copy=True)
    out = {
        "patch_w": g(net.patch_embed.proj.weight),
        "patch_b": g(net.patch_embed.proj.bias),
        "cls": g(net.cls_token),
        "pos": g(net.pos_embed),
        "ln_f_s": g(net.norm.weight),
        "ln_f_b": g(net.norm.bias),
    }
    if net.head is not None:
        out["head_w"] = g(net.head.weight)
        out["head_b"] = g(net.head.bias)
    per = {k: [] for k in VIT_LAYER_KEYS}
    for blk in net.blocks:
        a, f = blk.attn, blk.ffn
        per["ln1_s"].append(g(a.pre_ln_scale))
        per["ln1_b"].append(g(a.pre_ln_bias))
        per["qkv_w"].append(g(a.qkv_weight))
        per["qkv_b"].append(g(a.qkv_bias))
        per["out_w"].append(g(a.linear_weight))
        per["out_b"].append(g(a.linear_bias))
        per["ln2_s"].append(g(f.ln_scale))
        per["ln2_b"].append(g(f.ln_bias))
        per["f1_w"].append(g(f.w1))
        per["f1_b"].append(g(f.b1))
        per["f2_w"].append(g(f.w2))
        per["f2_b"].append(g(f.b2))
    for k, vs in per.items():
        out[k] = jnp.stack(vs)
    return out


def vit_forward_stacked(params, x, num_heads: int, patch: int = 16,
                        eps: float = 1e-6, remat: str = "dots"):
    """(B, C, H, W) -> logits (or cls features when no head). Same math as
    VisionTransformer.forward over the stacked layout.

    ``remat='dots'`` checkpoints the scan body saving only matmul outputs:
    without it the scan hoists six (L, B, S, ff) activation stacks (>7 GB
    at ViT-L B=32) for the backward; recomputing just the elementwise ops
    (LN, gelu) costs negligible FLOPs. 'off' disables."""
    from ...ops import fused_transformer_block as ftb

    b = x.shape[0]
    dn = lax.conv_dimension_numbers(x.shape, params["patch_w"].shape,
                                    ("NCHW", "OIHW", "NCHW"))
    p = lax.conv_general_dilated(
        x, params["patch_w"].astype(x.dtype), (patch, patch), "VALID",
        dimension_numbers=dn)
    p = p + params["patch_b"].astype(x.dtype)[None, :, None, None]
    e = p.shape[1]
    tok = p.reshape(b, e, -1).transpose(0, 2, 1)            # (B, N, E)
    cls = jnp.broadcast_to(params["cls"].astype(x.dtype),
                           (b, 1, e))
    h = jnp.concatenate([cls, tok], axis=1) + params["pos"].astype(x.dtype)

    def body(carry, lp):
        xc = carry
        xn = ftb.layer_norm_array(xc, lp["ln1_s"], lp["ln1_b"], eps)
        qkv = xn @ lp["qkv_w"].astype(xn.dtype) + lp["qkv_b"].astype(xn.dtype)
        q, k, v = ftb._split_heads(qkv, num_heads)
        attn = ftb._prefill_attention(q, k, v, None, causal=False)
        bb, s, _ = xc.shape
        attn = attn.transpose(0, 2, 1, 3).reshape(bb, s, -1)
        xc = xc + (attn @ lp["out_w"].astype(attn.dtype)
                   + lp["out_b"].astype(attn.dtype)).astype(xc.dtype)
        xn = ftb.layer_norm_array(xc, lp["ln2_s"], lp["ln2_b"], eps)
        f = jax.nn.gelu(xn @ lp["f1_w"].astype(xn.dtype)
                        + lp["f1_b"].astype(xn.dtype))
        xc = xc + (f @ lp["f2_w"].astype(f.dtype)
                   + lp["f2_b"].astype(f.dtype)).astype(xc.dtype)
        return xc, None

    layer_stack = {k: params[k] for k in VIT_LAYER_KEYS}
    if remat == "dots":
        body = jax.checkpoint(
            body, policy=jax.checkpoint_policies.dots_saveable)
    h, _ = lax.scan(body, h, layer_stack)
    h = ftb.layer_norm_array(h, params["ln_f_s"], params["ln_f_b"], eps)
    cls_feat = h[:, 0]
    if "head_w" in params:
        return (cls_feat @ params["head_w"].astype(cls_feat.dtype)
                + params["head_b"].astype(cls_feat.dtype))
    return cls_feat


def build_vit_train_step(num_heads: int, patch: int = 16, eps: float = 1e-6,
                         learning_rate: float = 1e-4, dtype=jnp.bfloat16,
                         remat: str = "dots"):
    """Compiled single-device ViT train step over stacked params: fused
    AdamW on ~16 stacked arrays instead of ~400 module tensors (same
    optimizer hyperparameters as the llama flagship step)."""
    b1, b2, adam_eps, wd = 0.9, 0.999, 1e-8, 0.01

    def init_opt(params):
        return {"step": jnp.zeros((), jnp.int32),
                "m": jax.tree_util.tree_map(
                    lambda v: jnp.zeros_like(v, jnp.float32), params),
                "v": jax.tree_util.tree_map(
                    lambda v: jnp.zeros_like(v, jnp.float32), params)}

    def loss_fn(params, x, y):
        logits = vit_forward_stacked(params, x.astype(dtype), num_heads,
                                     patch, eps,
                                     remat=remat).astype(jnp.float32)
        lse = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(lse, y[:, None], axis=1)[:, 0]
        return jnp.mean(nll)

    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        t = opt_state["step"] + 1

        def upd(p, g, m, v):
            g32 = g.astype(jnp.float32)
            m2 = b1 * m + (1 - b1) * g32
            v2 = b2 * v + (1 - b2) * g32 * g32
            mh = m2 / (1 - b1 ** t.astype(jnp.float32))
            vh = v2 / (1 - b2 ** t.astype(jnp.float32))
            p2 = p.astype(jnp.float32) - learning_rate * (
                mh / (jnp.sqrt(vh) + adam_eps)
                + wd * p.astype(jnp.float32))
            return p2.astype(p.dtype), m2, v2

        new_p, new_m, new_v = {}, {}, {}
        for k in params:
            new_p[k], new_m[k], new_v[k] = upd(
                params[k], grads[k], opt_state["m"][k], opt_state["v"][k])
        return loss, new_p, {"step": t, "m": new_m, "v": new_v}

    return jax.jit(step, donate_argnums=(0, 1)), init_opt
