from .resnet import (  # noqa: F401
    ResNet, BasicBlock, BottleneckBlock,
    resnet18, resnet34, resnet50, resnet101, resnet152,
    wide_resnet50_2, wide_resnet101_2, resnext50_32x4d, resnext101_64x4d,
)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenet import (  # noqa: F401
    MobileNetV1, mobilenet_v1, MobileNetV2, mobilenet_v2,
)
from .lenet import LeNet  # noqa: F401
from .vit import (  # noqa: F401
    VisionTransformer, vit_base_patch16_224, vit_large_patch16_224,
    vit_tiny_test,
)
from .ppyoloe import (  # noqa: F401
    PPYOLOE, ppyoloe_s, ppyoloe_m, ppyoloe_l,
)
