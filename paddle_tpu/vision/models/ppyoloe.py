"""PP-YOLOE — anchor-free detector (workload #5, BASELINE.md: "ViT-L +
PP-YOLOE, conv/attn mix").

Rebuild of the PaddleDetection PP-YOLOE family consumed through this
framework (reference model zoo: ppdet/modeling/{backbones/cspresnet.py,
necks/custom_pan.py, heads/ppyoloe_head.py}:§0 — external repo; the core
framework supplies the conv/BN/pooling kernels, SURVEY.md §6 workload 5).

TPU-first notes: everything is static-shape — the detector emits a FIXED
set of per-level predictions (sum of H_i·W_i anchors); decode/NMS-style
selection uses top-k over that static set, so the whole forward jits
without dynamic shapes (the reference's CINN dynamic-shape story maps to
shape-bucketing at the input instead).

Components: CSPResNet backbone (ConvBN+SiLU, effective-SE), CSP-PAN neck,
ET-head with distribution-focal (DFL) box regression, and a training loss
(varifocal cls + DFL + IoU) under a static center-radius assigner.
"""

from __future__ import annotations

import math
from typing import List, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ... import nn
from ...nn import functional as F
from ...core.math_ops import concat
from ...core.dispatch import apply
from ...core.tensor import Tensor


class ConvBNLayer(nn.Layer):
    def __init__(self, ch_in, ch_out, filter_size=3, stride=1, groups=1,
                 padding=None, act="silu"):
        super().__init__()
        pad = (filter_size - 1) // 2 if padding is None else padding
        self.conv = nn.Conv2D(ch_in, ch_out, filter_size, stride=stride,
                              padding=pad, groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(ch_out)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        return F.silu(x) if self.act else x


class EffectiveSELayer(nn.Layer):
    """Effective squeeze-excite (CSPResNet's attention block)."""

    def __init__(self, channels):
        super().__init__()
        self.fc = nn.Conv2D(channels, channels, 1)

    def forward(self, x):
        s = x.mean(axis=[2, 3], keepdim=True)
        return x * F.hardsigmoid(self.fc(s))


class CSPResBlock(nn.Layer):
    def __init__(self, ch, shortcut=True):
        super().__init__()
        self.conv1 = ConvBNLayer(ch, ch, 3)
        self.conv2 = ConvBNLayer(ch, ch, 3)
        self.shortcut = shortcut

    def forward(self, x):
        y = self.conv2(self.conv1(x))
        return x + y if self.shortcut else y


class CSPResStage(nn.Layer):
    def __init__(self, ch_in, ch_out, n_blocks, stride=2, use_attn=True):
        super().__init__()
        self.down = ConvBNLayer(ch_in, ch_out, 3, stride=stride) \
            if stride > 1 or ch_in != ch_out else None
        mid = ch_out // 2
        self.conv1 = ConvBNLayer(ch_out, mid, 1)
        self.conv2 = ConvBNLayer(ch_out, mid, 1)
        self.blocks = nn.Sequential(*[CSPResBlock(mid)
                                      for _ in range(n_blocks)])
        self.attn = EffectiveSELayer(ch_out) if use_attn else None
        self.conv3 = ConvBNLayer(ch_out, ch_out, 1)

    def forward(self, x):
        if self.down is not None:
            x = self.down(x)
        y1 = self.conv1(x)
        y2 = self.blocks(self.conv2(x))
        y = concat([y1, y2], axis=1)
        if self.attn is not None:
            y = self.attn(y)
        return self.conv3(y)


class CSPResNet(nn.Layer):
    """Backbone returning strides 8/16/32 feature maps."""

    def __init__(self, width_mult=0.5, depth_mult=0.33):
        super().__init__()
        chs = [int(c * width_mult) for c in (64, 128, 256, 512, 1024)]
        ns = [max(round(n * depth_mult), 1) for n in (3, 6, 6, 3)]
        # stem stride 2; each stage halves again → stage outputs at strides
        # 4, 8, 16, 32 (the last three feed the neck)
        self.stem = nn.Sequential(
            ConvBNLayer(3, chs[0] // 2, 3, stride=2),
            ConvBNLayer(chs[0] // 2, chs[0], 3, stride=1))
        self.stages = nn.LayerList([
            CSPResStage(chs[0], chs[1], ns[0]),
            CSPResStage(chs[1], chs[2], ns[1]),
            CSPResStage(chs[2], chs[3], ns[2]),
            CSPResStage(chs[3], chs[4], ns[3]),
        ])
        self.out_channels = [chs[2], chs[3], chs[4]]

    def forward(self, x):
        x = self.stem(x)
        outs = []
        for i, st in enumerate(self.stages):
            x = st(x)
            if i >= 1:
                outs.append(x)
        return outs  # strides 8, 16, 32


class CSPPAN(nn.Layer):
    """Compact CSP-PAN: top-down fusion then bottom-up aggregation."""

    def __init__(self, in_channels: Sequence[int], out_ch=None):
        super().__init__()
        c3, c4, c5 = in_channels
        o = out_ch or c3
        self.reduce5 = ConvBNLayer(c5, o, 1)
        self.reduce4 = ConvBNLayer(c4, o, 1)
        self.reduce3 = ConvBNLayer(c3, o, 1)
        self.td4 = CSPResStage(2 * o, o, 1, stride=1, use_attn=False)
        self.td3 = CSPResStage(2 * o, o, 1, stride=1, use_attn=False)
        self.down3 = ConvBNLayer(o, o, 3, stride=2)
        self.bu4 = CSPResStage(2 * o, o, 1, stride=1, use_attn=False)
        self.down4 = ConvBNLayer(o, o, 3, stride=2)
        self.bu5 = CSPResStage(2 * o, o, 1, stride=1, use_attn=False)
        self.out_channels = [o, o, o]

    def forward(self, feats):
        f3, f4, f5 = feats
        p5 = self.reduce5(f5)
        up5 = F.interpolate(p5, scale_factor=2, mode="nearest")
        p4 = self.td4(concat([self.reduce4(f4), up5], axis=1))
        up4 = F.interpolate(p4, scale_factor=2, mode="nearest")
        p3 = self.td3(concat([self.reduce3(f3), up4], axis=1))
        n4 = self.bu4(concat([self.down3(p3), p4], axis=1))
        n5 = self.bu5(concat([self.down4(n4), p5], axis=1))
        return [p3, n4, n5]


class PPYOLOEHead(nn.Layer):
    """ET-head: per-level cls + DFL box-distribution branches.

    Emits (B, A, num_classes) scores and (B, A, 4) boxes (xyxy, input
    pixels) over the STATIC anchor set A = Σ H_i·W_i.
    """

    def __init__(self, in_channels: Sequence[int], num_classes=80,
                 reg_max=16, strides=(8, 16, 32)):
        super().__init__()
        self.num_classes = num_classes
        self.reg_max = reg_max
        self.strides = list(strides)
        self.stems_cls = nn.LayerList(
            [ConvBNLayer(c, c, 3) for c in in_channels])
        self.stems_reg = nn.LayerList(
            [ConvBNLayer(c, c, 3) for c in in_channels])
        self.pred_cls = nn.LayerList(
            [nn.Conv2D(c, num_classes, 1) for c in in_channels])
        self.pred_reg = nn.LayerList(
            [nn.Conv2D(c, 4 * (reg_max + 1), 1) for c in in_channels])
        proj = np.arange(reg_max + 1, dtype=np.float32)
        self._proj = proj  # DFL expectation projection

    def anchor_centers(self, shapes):
        """Static per-level anchor centers in input pixels: (A, 2), plus
        per-anchor stride (A,)."""
        pts, sts = [], []
        for (h, w), s in zip(shapes, self.strides):
            ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
            c = np.stack([(xs + 0.5) * s, (ys + 0.5) * s], -1).reshape(-1, 2)
            pts.append(c.astype(np.float32))
            sts.append(np.full((h * w,), s, np.float32))
        return np.concatenate(pts), np.concatenate(sts)

    def forward(self, feats):
        cls_list, reg_list, shapes = [], [], []
        for i, f in enumerate(feats):
            b, c, h, w = f.shape
            shapes.append((h, w))
            cl = self.pred_cls[i](self.stems_cls[i](f) + f)
            rg = self.pred_reg[i](self.stems_reg[i](f))
            cls_list.append(cl.reshape([b, self.num_classes, h * w]))
            reg_list.append(rg.reshape([b, 4 * (self.reg_max + 1), h * w]))
        cls = concat(cls_list, axis=-1).transpose([0, 2, 1])  # (B, A, C)
        reg = concat(reg_list, axis=-1).transpose([0, 2, 1])  # (B, A, 4*(m+1))
        return cls, reg, shapes

    def decode(self, cls, reg, shapes):
        """(scores (B,A,C) sigmoid, boxes (B,A,4) xyxy pixels)."""
        centers, strides = self.anchor_centers(shapes)
        m = self.reg_max
        proj = self._proj

        def fn(clv, rgv):
            b, a, _ = rgv.shape
            dist = jax.nn.softmax(
                rgv.reshape(b, a, 4, m + 1).astype(jnp.float32), axis=-1)
            d = jnp.einsum("bakm,m->bak", dist, jnp.asarray(proj))
            d = d * strides[None, :, None]
            cx, cy = centers[:, 0], centers[:, 1]
            x1 = cx[None] - d[..., 0]
            y1 = cy[None] - d[..., 1]
            x2 = cx[None] + d[..., 2]
            y2 = cy[None] + d[..., 3]
            boxes = jnp.stack([x1, y1, x2, y2], -1)
            return jax.nn.sigmoid(clv.astype(jnp.float32)), boxes

        return apply(fn, cls, reg, op_name="ppyoloe_decode", n_outputs=2)


class PPYOLOE(nn.Layer):
    """Backbone + neck + head. ``forward(images)`` → (scores, boxes) on the
    static anchor set; ``compute_loss`` trains with varifocal + DFL + IoU
    under a center-radius assigner (static shapes throughout)."""

    def __init__(self, num_classes=80, width_mult=0.5, depth_mult=0.33):
        super().__init__()
        self.backbone = CSPResNet(width_mult, depth_mult)
        self.neck = CSPPAN(self.backbone.out_channels)
        self.head = PPYOLOEHead(self.neck.out_channels, num_classes)
        self.num_classes = num_classes

    def forward(self, images):
        cls, reg, shapes = self.head(self.neck(self.backbone(images)))
        return self.head.decode(cls, reg, shapes)

    def predict(self, images, score_threshold=0.25, top_k=100):
        """Static-shape selection: top_k anchors by best class score."""
        scores, boxes = self(images)

        def fn(sv, bv):
            best = jnp.max(sv, axis=-1)                     # (B, A)
            label = jnp.argmax(sv, axis=-1)
            val, idx = jax.lax.top_k(best, top_k)
            sel = jnp.take_along_axis(bv, idx[..., None], axis=1)
            lab = jnp.take_along_axis(label, idx, axis=1)
            keep = val >= score_threshold
            return val, sel, lab.astype(jnp.int32), keep

        return apply(fn, scores, boxes, op_name="ppyoloe_predict",
                     n_outputs=4)

    def predict_with_nms(self, images, score_threshold=0.25, top_k=100,
                         nms_threshold=0.6, keep_top_k=30):
        """Full detection postprocess — the reference pipeline's
        multiclass_nms3 tail (ppdet post_process:§0): static top-k anchor
        selection on device, then per-image class-aware NMS
        (vision/ops.py). Returns per-image lists of
        (boxes (M,4), scores (M,), labels (M,)) numpy arrays."""
        from ...vision import ops as vops

        val, sel, lab, keep = self.predict(images, score_threshold, top_k)
        val_np = np.asarray(val._value)
        sel_np = np.asarray(sel._value)
        lab_np = np.asarray(lab._value)
        keep_np = np.asarray(keep._value)
        results = []
        for b in range(val_np.shape[0]):
            m = keep_np[b]
            if not m.any():
                results.append((np.zeros((0, 4), np.float32),
                                np.zeros((0,), np.float32),
                                np.zeros((0,), np.int64)))
                continue
            boxes = sel_np[b][m]
            scores = val_np[b][m]
            labels = lab_np[b][m]
            kept = np.asarray(vops.nms(
                Tensor(jnp.asarray(boxes)), nms_threshold,
                Tensor(jnp.asarray(scores)),
                Tensor(jnp.asarray(labels.astype(np.int32))),
                categories=list(range(self.num_classes)),
                top_k=keep_top_k)._value)
            results.append((boxes[kept], scores[kept],
                            labels[kept].astype(np.int64)))
        return results

    def predict_bucketed(self, images, score_threshold=0.25, top_k=100,
                         batch_buckets=(1, 2, 4, 8)):
        """Ragged-batch eval with shape bucketing — the workload-#5
        dynamic-shape story (SURVEY.md §2.5 CINN row).

        ``images``: (B, C, H, W) with B varying call-to-call (e.g. the last
        incomplete batch of an eval epoch, or a dynamic serving batch). The
        batch axis is padded up to the next bucket so the compiled program
        is reused across at most ``len(batch_buckets)`` signatures instead
        of recompiling per distinct B; padded rows are sliced off the
        outputs.
        """
        from ...jit.bucketing import pad_to_bucket
        padded, b = pad_to_bucket(images, axis=0, buckets=batch_buckets,
                                  pad_value=0.0)
        val, sel, lab, keep = self.predict(padded, score_threshold, top_k)
        return val[:b], sel[:b], lab[:b], keep[:b]

    def compute_loss(self, images, gt_boxes, gt_labels, radius=2.5):
        """gt_boxes (B, G, 4) xyxy pixels (pad: zeros), gt_labels (B, G)
        int (-1 = pad). Center-radius assignment: an anchor is positive for
        the first gt whose center is within radius·stride."""
        cls, reg, shapes = self.head(self.neck(self.backbone(images)))
        centers, strides = self.head.anchor_centers(shapes)
        m = self.head.reg_max
        C = self.num_classes
        proj = self.head._proj

        def fn(clv, rgv, gb, gl):
            b, a, _ = clv.shape
            g = gb.shape[1]
            cx = (gb[..., 0] + gb[..., 2]) / 2                 # (B, G)
            cy = (gb[..., 1] + gb[..., 3]) / 2
            valid_gt = gl >= 0
            dx = jnp.abs(centers[None, :, 0, None] - cx[:, None, :])
            dy = jnp.abs(centers[None, :, 1, None] - cy[:, None, :])
            rad = radius * strides[None, :, None]
            near = (dx < rad) & (dy < rad) & valid_gt[:, None, :]  # (B,A,G)
            assigned = jnp.argmax(near, axis=-1)               # first match
            pos = jnp.any(near, axis=-1)                       # (B, A)
            tgt_box = jnp.take_along_axis(gb, assigned[..., None], axis=1)
            tgt_lab = jnp.take_along_axis(gl, assigned, axis=1)

            # --- cls: varifocal-style BCE with IoU-free quality target ---
            onehot = jax.nn.one_hot(jnp.where(pos, tgt_lab, 0), C)
            tgt = onehot * pos[..., None]
            logits = clv.astype(jnp.float32)
            p = jax.nn.sigmoid(logits)
            weight = jnp.where(tgt > 0, tgt, 0.75 * p ** 2)
            bce = -(tgt * jnp.log(jnp.clip(p, 1e-7, 1.0)) +
                    (1 - tgt) * jnp.log(jnp.clip(1 - p, 1e-7, 1.0)))
            n_pos = jnp.maximum(jnp.sum(pos), 1.0)
            loss_cls = jnp.sum(weight * bce) / n_pos

            # --- box: DFL + L1 on positive anchors -----------------------
            lt = jnp.stack([centers[None, :, 0] - tgt_box[..., 0],
                            centers[None, :, 1] - tgt_box[..., 1]], -1)
            rb = jnp.stack([tgt_box[..., 2] - centers[None, :, 0],
                            tgt_box[..., 3] - centers[None, :, 1]], -1)
            dist_t = jnp.concatenate([lt, rb], -1) / strides[None, :, None]
            dist_t = jnp.clip(dist_t, 0, m - 0.01)             # (B, A, 4)
            dl = jnp.floor(dist_t)
            wr = dist_t - dl
            dl = dl.astype(jnp.int32)
            logd = jax.nn.log_softmax(
                rgv.reshape(b, a, 4, m + 1).astype(jnp.float32), axis=-1)
            pick = lambda idx: jnp.take_along_axis(  # noqa: E731
                logd, idx[..., None], axis=-1)[..., 0]
            dfl = -(pick(dl) * (1 - wr) + pick(dl + 1) * wr)
            dist_p = jnp.einsum("bakm,m->bak",
                                jnp.exp(logd), jnp.asarray(proj))
            l1 = jnp.abs(dist_p - dist_t)
            loss_box = jnp.sum((dfl + l1).mean(-1) * pos) / n_pos
            return loss_cls + 0.5 * loss_box

        return apply(fn, cls, reg,
                     gt_boxes if isinstance(gt_boxes, Tensor)
                     else Tensor(jnp.asarray(gt_boxes)),
                     gt_labels if isinstance(gt_labels, Tensor)
                     else Tensor(jnp.asarray(gt_labels)),
                     op_name="ppyoloe_loss")


def ppyoloe_s(num_classes=80, **kw):
    return PPYOLOE(num_classes, width_mult=0.5, depth_mult=0.33, **kw)


def ppyoloe_m(num_classes=80, **kw):
    return PPYOLOE(num_classes, width_mult=0.75, depth_mult=0.67, **kw)


def ppyoloe_l(num_classes=80, **kw):
    return PPYOLOE(num_classes, width_mult=1.0, depth_mult=1.0, **kw)


def pad_ground_truth(boxes_list, labels_list, buckets=(8, 16, 32, 64)):
    """Pad a ragged list of per-image ground truths into the dense
    (B, G_bucket, 4) / (B, G_bucket) layout ``compute_loss`` consumes
    (labels -1 = pad), with G rounded up to a bucket so the compiled loss
    sees a bounded signature set (workload-#5 dynamic-shape policy)."""
    from ...jit.bucketing import next_bucket
    b = len(boxes_list)
    gmax = max((np.shape(bx)[0] for bx in boxes_list), default=1)
    g = next_bucket(max(gmax, 1), buckets)
    boxes = np.zeros((b, g, 4), np.float32)
    labels = np.full((b, g), -1, np.int32)
    for i, (bx, lb) in enumerate(zip(boxes_list, labels_list)):
        n = np.shape(bx)[0]
        if n:
            boxes[i, :n] = np.asarray(bx, np.float32)
            labels[i, :n] = np.asarray(lb, np.int32)
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(labels))
