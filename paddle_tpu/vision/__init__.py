"""paddle_tpu.vision — vision model zoo, transforms, datasets.

Rebuild of the reference's python/paddle/vision/ (SURVEY.md §2.5 "Vision model
zoo": models/resnet.py, datasets/, transforms/). Models are built from the
framework's nn layers so they run through the same jax/XLA compute path
(NCHW public layout; XLA lays out convs for the MXU internally).
"""

from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401

from .models import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
    wide_resnet50_2, wide_resnet101_2, resnext50_32x4d, resnext101_64x4d,
    VGG, vgg11, vgg13, vgg16, vgg19,
    MobileNetV1, mobilenet_v1, MobileNetV2, mobilenet_v2,
    LeNet,
)
