"""``paddle_tpu.hub`` — hubconf-based model loading.

Parity with python/paddle/hub.py of the reference (list/help/load over a
``hubconf.py``). The ``local`` source is fully supported; ``github`` /
``gitee`` need network access, which this environment does not have —
they raise with that reason (the reference raises the same way when its
download fails).
"""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import List

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} under {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _check_source(source: str):
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network access (github/gitee "
            "download), unavailable in this environment; clone the repo "
            "and use source='local'")


def list(repo_dir: str, source: str = "local",
         force_reload: bool = False) -> List[str]:  # noqa: A001
    """Entrypoint names exported by the repo's hubconf."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir: str, model: str, source: str = "local",  # noqa: A001
         force_reload: bool = False) -> str:
    """The entrypoint's docstring."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"no callable entrypoint {model!r} in "
                           f"{repo_dir}/{_HUBCONF}")
    return fn.__doc__ or ""


def load(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False, **kwargs):
    """Call the hubconf entrypoint and return its model."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"no callable entrypoint {model!r} in "
                           f"{repo_dir}/{_HUBCONF}")
    return fn(**kwargs)
