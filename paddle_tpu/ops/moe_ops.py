"""MoE dispatch/capacity ops.

Rebuild of the reference's CUDA capacity kernels and collective dispatch ops
(SURVEY.md §2.4 EP row): ``number_count``, ``limit_by_capacity``,
``prune_gate_by_capacity``, ``random_routing``
(paddle/fluid/operators/collective/global_scatter_op.* and phi capacity
kernels, file:§0) — here as pure-jnp ops XLA fuses, plus the dense
GShard-style dispatch/combine einsums that replace global_scatter /
global_gather. On an ``expert``-sharded mesh the einsum's expert dim IS the
alltoall: GSPMD lowers the (N,E,C)×(N,d) contraction to an ICI all_to_all.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from ..core.compat import axis_size


def number_count(gate_idx, upper_range: int):
    """Histogram of expert assignments: out[e] = #tokens routed to e
    (reference number_count op)."""
    return jnp.bincount(gate_idx.reshape(-1).astype(jnp.int32),
                        length=upper_range)


def position_in_expert(gate_idx, num_experts: int):
    """For each token, its arrival position within its expert's queue
    (cumulative count of earlier tokens with the same expert)."""
    one_hot = jax.nn.one_hot(gate_idx, num_experts, dtype=jnp.int32)
    pos = jnp.cumsum(one_hot, axis=0) * one_hot  # (N, E)
    return pos.sum(axis=-1) - 1  # (N,) zero-based


def limit_by_capacity(expert_count, capacity, n_worker: int = 1):
    """Clamp per-expert counts at capacity (reference limit_by_capacity):
    returns the admitted counts."""
    cap = jnp.asarray(capacity)
    if cap.ndim == 0:
        cap = jnp.full(expert_count.shape, cap)
    return jnp.minimum(expert_count, cap)


def prune_gate_by_capacity(gate_idx, expert_count, n_expert: int,
                           n_worker: int = 1):
    """Set gate_idx to -1 for tokens beyond their expert's capacity
    (reference prune_gate_by_capacity)."""
    pos = position_in_expert(gate_idx, n_expert)
    cap = expert_count[gate_idx]
    return jnp.where(pos < cap, gate_idx, -1)


def random_routing(topk_idx, topk_value, prob, topk: int = 2):
    """GShard 2nd-expert random drop: keep expert #2 only when
    2*value > prob (reference random_routing op). prob ~ U[0,1) per token."""
    if topk != 2:
        raise ValueError("random_routing supports topk=2 only")
    keep = (2.0 * topk_value[:, 1]) > prob
    second = jnp.where(keep, topk_idx[:, 1], -1)
    return jnp.stack([topk_idx[:, 0], second], axis=1)


def dispatch_combine_masks(gate_idx, gate_prob, num_experts: int,
                           capacity: int):
    """Dense GShard dispatch: returns
      dispatch (N,E,C) bool — token n goes to slot c of expert e
      combine  (N,E,C) f32  — same mask scaled by the gate prob.
    Tokens with gate_idx -1 (pruned) or beyond capacity drop out.
    """
    valid = gate_idx >= 0
    safe_idx = jnp.where(valid, gate_idx, 0)
    oh_e = jax.nn.one_hot(safe_idx, num_experts, dtype=jnp.int32)
    oh_e = oh_e * valid[:, None].astype(jnp.int32)
    pos = jnp.cumsum(oh_e, axis=0) * oh_e  # 1-based where routed
    pos = pos.sum(axis=-1) - 1  # (N,), -1 where unrouted
    in_cap = (pos >= 0) & (pos < capacity)
    keep = (valid & in_cap).astype(jnp.float32)
    oh_c = jax.nn.one_hot(jnp.where(in_cap, pos, 0), capacity,
                          dtype=jnp.float32)
    disp = jnp.einsum("ne,nc->nec", oh_e.astype(jnp.float32), oh_c)
    disp = disp * keep[:, None, None]
    combine = disp * gate_prob[:, None, None]
    return disp, combine


def dispatch_masks_topk(gate_idx, num_experts: int, capacity: int):
    """Per-choice dispatch masks with joint capacity ordering (GShard:
    choice k's tokens queue after admitted tokens of choices < k). Returns a
    list of K raw (N,E,C) float32 masks — index-only, no gradient path, so
    callers can treat them as constants and keep probs differentiable."""
    n, K = gate_idx.shape
    masks = []
    admitted = jnp.zeros((num_experts,), jnp.int32)
    for k in range(K):
        idx = gate_idx[:, k]
        valid = idx >= 0
        safe = jnp.where(valid, idx, 0)
        oh = jax.nn.one_hot(safe, num_experts, dtype=jnp.int32) * \
            valid[:, None].astype(jnp.int32)
        pos = (jnp.cumsum(oh, axis=0) * oh).sum(-1) - 1 + admitted[safe]
        in_cap = valid & (pos >= 0) & (pos < capacity)
        keep = in_cap.astype(jnp.float32)
        oh_c = jax.nn.one_hot(jnp.where(in_cap, pos, 0), capacity,
                              dtype=jnp.float32)
        disp = jnp.einsum("ne,nc->nec", oh.astype(jnp.float32), oh_c) * \
            keep[:, None, None]
        masks.append(disp)
        admitted = admitted + (oh * in_cap[:, None].astype(jnp.int32)
                               ).sum(axis=0)
    return masks


def dispatch_combine_topk(gate_idx, gate_prob, num_experts: int,
                          capacity: int):
    """Joint top-K dispatch (GShard ordering: choice k's tokens queue after
    the admitted tokens of choices < k), so (token, k) pairs never collide
    in an expert's capacity slots. Returns summed (N,E,C) dispatch and
    combine masks."""
    masks = dispatch_masks_topk(gate_idx, num_experts, capacity)
    disp_sum = sum(masks)
    comb_sum = sum(m * gate_prob[:, k][:, None, None]
                   for k, m in enumerate(masks))
    return disp_sum, comb_sum


def moe_dispatch(x, dispatch_mask):
    """(N,d),(N,E,C) -> (E,C,d): the global_scatter equivalent — under an
    expert-sharded mesh XLA turns this contraction into the alltoall."""
    return jnp.einsum("nec,nd->ecd", dispatch_mask, x)


def moe_combine(expert_out, combine_mask):
    """(E,C,d),(N,E,C) -> (N,d): global_gather equivalent."""
    return jnp.einsum("nec,ecd->nd", combine_mask, expert_out)


# ---------------------------------------------------------------------------
# Expert-parallel execution inside shard_map (the ragged alltoall of
# global_scatter/global_gather over an ICI 'expert' axis — SURVEY §2.4 EP)
# ---------------------------------------------------------------------------
def expert_parallel_apply(x_local, gate_idx_local, gate_prob_local,
                          w1_local, w2_local, axis_name: str,
                          num_experts: int, capacity: int, act=None,
                          b1_local=None, b2_local=None):
    """Expert-parallel MoE FFN with PRE-COMPUTED gating (any gate works:
    naive/GShard/Switch indices with -1 = pruned token drop out of the
    dispatch masks). Call inside shard_map; see :func:`expert_parallel_ffn`
    for the data-path description.
    """
    from jax import lax

    n = axis_size(axis_name)
    if num_experts % n:
        raise ValueError(f"num_experts {num_experts} must be divisible by "
                         f"'{axis_name}' axis size {n}")
    e_local = num_experts // n
    if act is None:
        act = jax.nn.gelu

    # round 4: gather-based dispatch builds the same dense (E, C, d) slot
    # layout the all_to_all needs; all float movement is gathers (see
    # dispatch_plan)
    routes = dispatch_indices_topk(gate_idx_local, num_experts, capacity)
    in_dtype = x_local.dtype
    tfs, cfs, flats, oks = dispatch_plan(routes, num_experts, capacity,
                                         x_local.shape[0])
    slots = moe_dispatch_gather(x_local.astype(jnp.float32), tfs, flats,
                                oks, num_experts, capacity)   # (E, C, d)

    d_model = x_local.shape[-1]
    z = slots.reshape(n, e_local, capacity, d_model)
    # chunk i (this device's dispatch FOR expert-group i) goes to device i;
    # received leading dim then indexes the SOURCE device
    z = lax.all_to_all(z, axis_name, split_axis=0, concat_axis=0)
    z = jnp.swapaxes(z, 0, 1).reshape(e_local, n * capacity, d_model)

    h = jnp.einsum("ecd,edf->ecf", z.astype(in_dtype), w1_local)
    if b1_local is not None:
        h = h + b1_local[:, None, :]
    h = act(h)
    y = jnp.einsum("ecf,efd->ecd", h, w2_local)              # (E_local, nC, d)
    if b2_local is not None:
        y = y + b2_local[:, None, :]

    y = jnp.swapaxes(y.reshape(e_local, n, capacity, d_model), 0, 1)
    y = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0)
    y = y.reshape(num_experts, capacity, d_model)
    return moe_combine_gather(y.astype(jnp.float32), gate_prob_local,
                              flats, oks, tfs, cfs).astype(in_dtype)


def expert_parallel_ffn(x_local, gate_logits_local, w1_local, w2_local,
                        axis_name: str, num_experts: int, capacity: int,
                        topk: int = 1, act=None):
    """Run a MoE FFN with experts sharded over ``axis_name``.

    Call inside shard_map. Per device: T_local tokens, E_local =
    num_experts/n experts (w1_local (E_local, d, ff), w2_local
    (E_local, ff, d)); gating is over ALL experts (gate weights
    replicated → gate_logits_local (T_local, num_experts)).

    Data path (the reference's global_scatter → expert → global_gather,
    SURVEY §3.2 MoE):
      local dispatch (T_local, E, C) → (E, C, d)
      all_to_all over the expert axis → (E_local, n·C, d) per device
      local expert FFN
      inverse all_to_all → local combine back to (T_local, d)
    """
    from jax import lax

    probs = jax.nn.softmax(gate_logits_local.astype(jnp.float32), axis=-1)
    if topk == 1:
        gate_idx = jnp.argmax(probs, axis=-1)[:, None]       # (T, 1)
        gate_prob = jnp.take_along_axis(probs, gate_idx, axis=-1)
    else:
        gate_prob, gate_idx = lax.top_k(probs, topk)
    return expert_parallel_apply(x_local, gate_idx, gate_prob, w1_local,
                                 w2_local, axis_name, num_experts, capacity,
                                 act=act)


# ---------------------------------------------------------------------------
# Index-based dispatch (round 3): the (N,E,C) one-hot einsum dispatch costs
# O(N·E·C·d) FLOPs — at training scale orders of magnitude more than the
# expert matmuls it feeds. The same routing expressed as scatter/gather by
# slot index is O(N·d); the masks remain for the expert-parallel all_to_all
# layout, which needs the dense (E,C) slot structure anyway.
# ---------------------------------------------------------------------------
def dispatch_indices_topk(gate_idx, num_experts: int, capacity: int):
    """Index form of :func:`dispatch_masks_topk` with the SAME joint
    capacity ordering. Returns a list of K routes
    ``(flat_slot (N,), admitted (N,) bool)`` where flat_slot indexes the
    flattened (E*C) expert-slot space."""
    n, K = gate_idx.shape
    routes = []
    admitted = jnp.zeros((num_experts,), jnp.int32)
    for k in range(K):
        idx = gate_idx[:, k]
        valid = idx >= 0
        safe = jnp.where(valid, idx, 0)
        oh = jax.nn.one_hot(safe, num_experts, dtype=jnp.int32) * \
            valid[:, None].astype(jnp.int32)
        pos = (jnp.cumsum(oh, axis=0) * oh).sum(-1) - 1 + admitted[safe]
        in_cap = valid & (pos >= 0) & (pos < capacity)
        flat = safe * capacity + jnp.where(in_cap, pos, 0)
        routes.append((flat.astype(jnp.int32), in_cap))
        admitted = admitted + (oh * in_cap[:, None].astype(jnp.int32)
                               ).sum(axis=0)
    return routes


def moe_dispatch_indices(x, routes, num_experts: int, capacity: int):
    """(N,d) + routes -> (E,C,d) by scatter-add (slots are collision-free
    by construction, so add == set with exact gradients)."""
    out = jnp.zeros((num_experts * capacity, x.shape[-1]), x.dtype)
    for flat, ok in routes:
        out = out.at[jnp.where(ok, flat, 0)].add(
            jnp.where(ok[:, None], x, jnp.zeros_like(x)))
    return out.reshape(num_experts, capacity, x.shape[-1])


# ---------------------------------------------------------------------------
# Gather-based dispatch (round 4): the round-3 index dispatch scatters the
# full (N, d) activations into slots — TPU scatter of d-wide rows is the
# measured +8% step-time regression (BASELINE.md moe row). Both dispatch
# AND its gradient are expressible as gathers once the inverse slot->token
# map exists, and that map costs one N-element int32 scatter. custom_vjp
# keeps every float movement a gather (the fast path on TPU), mirroring
# what the reference's global_scatter CUDA kernel achieves with direct
# addressed writes (global_scatter_op:§0).
# ---------------------------------------------------------------------------
def dispatch_plan(routes, num_experts: int, capacity: int, n_tokens: int):
    """Invert routes into the full gather plan. Returns
    token_for_slot (E*C,) int32 (-1 = empty slot),
    choice_for_slot (E*C,) int32 (which top-k choice filled it),
    flats (N, K) int32 and oks (N, K) bool (the routes, stacked)."""
    ec = num_experts * capacity
    tfs = jnp.full((ec + 1,), -1, jnp.int32)     # +1 sentinel dump slot
    cfs = jnp.zeros((ec + 1,), jnp.int32)
    tok = jnp.arange(n_tokens, dtype=jnp.int32)
    for k, (flat, ok) in enumerate(routes):
        idx = jnp.where(ok, flat, ec)
        tfs = tfs.at[idx].set(jnp.where(ok, tok, -1))
        cfs = cfs.at[idx].set(k)
    flats = jnp.stack([f for f, _ in routes], axis=1)
    oks = jnp.stack([o for _, o in routes], axis=1)
    return tfs[:ec], cfs[:ec], flats, oks


def moe_dispatch_gather(x, token_for_slot, flats, oks, num_experts: int,
                        capacity: int):
    """(N,d) -> (E,C,d) where slot s holds x[token_for_slot[s]] (0 when
    empty). flats/oks: (N,K) flat slot per (token, choice) + admitted
    flags — used only by the backward gather."""
    d = x.shape[-1]

    @jax.custom_vjp
    def run(xv, tfs, fl, ok):
        valid = tfs >= 0
        slots = jnp.take(xv, jnp.clip(tfs, 0, None), axis=0)
        slots = jnp.where(valid[:, None], slots, 0)
        return slots.reshape(num_experts, capacity, d)

    def run_fwd(xv, tfs, fl, ok):
        return run(xv, tfs, fl, ok), (tfs, fl, ok)

    def run_bwd(res, g):
        tfs, fl, ok = res
        gf = g.reshape(num_experts * capacity, d)
        dx = 0.0
        for k in range(fl.shape[1]):
            rows = jnp.take(gf, fl[:, k], axis=0)
            dx = dx + jnp.where(ok[:, k][:, None], rows, 0)
        return (dx, np.zeros(tfs.shape, jax.dtypes.float0),
                np.zeros(fl.shape, jax.dtypes.float0),
                np.zeros(ok.shape, jax.dtypes.float0))

    run.defvjp(run_fwd, run_bwd)
    return run(x, token_for_slot, flats, oks)


def moe_combine_gather(expert_out, probs, flats, oks, token_for_slot,
                       choice_for_slot):
    """(E,C,d) + (N,K) probs -> (N,d): out[n] = sum_k ok*p_k*eo[slot(n,k)].
    Backward for expert_out/probs is gather-only via the slot->token maps."""
    e, c, d = expert_out.shape
    n, K = flats.shape

    @jax.custom_vjp
    def run(eo, pv, fl, ok, tfs, cfs):
        flat = eo.reshape(e * c, d)
        out = 0.0
        for k in range(K):
            vals = jnp.take(flat, fl[:, k], axis=0)
            w = pv[:, k] * ok[:, k].astype(pv.dtype)
            out = out + vals * w[:, None].astype(vals.dtype)
        return out

    def run_fwd(eo, pv, fl, ok, tfs, cfs):
        return run(eo, pv, fl, ok, tfs, cfs), (eo, pv, fl, ok, tfs, cfs)

    def run_bwd(res, g):
        eo, pv, fl, ok, tfs, cfs = res
        flat = eo.reshape(e * c, d)
        valid = tfs >= 0
        tok = jnp.clip(tfs, 0, None)
        # d_eo[s] = valid * g[token(s)] * p[token(s), choice(s)]
        g_rows = jnp.take(g, tok, axis=0)
        p_slot = jnp.take_along_axis(
            jnp.take(pv, tok, axis=0), cfs[:, None], axis=1)[:, 0]
        ok_slot = jnp.take_along_axis(
            jnp.take(ok, tok, axis=0), cfs[:, None], axis=1)[:, 0]
        w = p_slot * ok_slot.astype(p_slot.dtype)
        d_eo = jnp.where(valid[:, None],
                         g_rows * w[:, None].astype(g_rows.dtype), 0)
        # d_p[n,k] = ok * <g[n], eo[slot(n,k)]>
        dps = []
        for k in range(K):
            vals = jnp.take(flat, fl[:, k], axis=0)
            dp = jnp.sum(g.astype(jnp.float32) * vals.astype(jnp.float32),
                         axis=-1) * ok[:, k].astype(jnp.float32)
            dps.append(dp)
        d_pv = jnp.stack(dps, axis=1).astype(pv.dtype)
        return (d_eo.reshape(e, c, d).astype(eo.dtype), d_pv,
                np.zeros(fl.shape, jax.dtypes.float0),
                np.zeros(ok.shape, jax.dtypes.float0),
                np.zeros(tfs.shape, jax.dtypes.float0),
                np.zeros(cfs.shape, jax.dtypes.float0))

    run.defvjp(run_fwd, run_bwd)
    return run(expert_out, probs, flats, oks, token_for_slot,
               choice_for_slot)


def moe_combine_indices(expert_out, routes, gate_prob):
    """(E,C,d) + routes + (N,K) probs -> (N,d) by gather."""
    e, c, d = expert_out.shape
    flat = expert_out.reshape(e * c, d)
    out = None
    for k, (fs, ok) in enumerate(routes):
        vals = flat[jnp.where(ok, fs, 0)]
        w = (gate_prob[:, k] * ok.astype(gate_prob.dtype))[:, None]
        term = vals * w.astype(vals.dtype)
        out = term if out is None else out + term
    return out
