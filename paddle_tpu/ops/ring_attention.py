"""Ring attention — blockwise flash attention with KV rotation over a
context (``sep``) mesh axis.

Rebuild of the reference's ring-flash-attention layer (model-zoo
ring_flash_attention.py consuming core sep groups + batch_isend_irecv —
SURVEY.md §5.7 mechanism 3), designed TPU-first:

* the KV block rotates around the ICI ring via ``lax.ppermute`` (XLA
  double-buffers the permute against the block computation);
* each ring step's inner block runs the **Pallas flash kernel**
  (``_flash_fwd_pallas``) — no (B, H, S_local, S_local) score
  materialization, GQA KV heads shared through kernel index maps
  (``kv_rep``) instead of ``jnp.repeat``;
* per-block results merge with online-softmax (log-sum-exp) rescaling, so
  memory stays O(S_local) per device while attending to the full sequence;
* the ring is a ``lax.scan`` (compile size independent of the sep degree)
  with a **custom VJP**: the backward replays the ring, recomputing each
  block's probabilities from the saved global LSE (flash-style recompute —
  activations are never stored per block) while dK/dV partials travel
  around the ring with their KV chunk and arrive home after a full cycle.

Causality uses *global* positions: device i holds contiguous chunk i, so a
KV block that originated at chunk j is fully visible when j < i, causal
when j == i, and fully masked when j > i.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P
from ..core.compat import shard_map

from ._common import use_pallas
from ..core.dispatch import apply
from ..parallel import mesh as _mesh
from . import flash_attention as fa

_NEG_INF = -1e30


# ===========================================================================
# Inner block: (BHq, S, D) x (BHk, S, D) -> normalized out + lse
# ===========================================================================
def _block_ref(q3, k3, v3, scale, causal_blk, kv_rep):
    """XLA fallback with the same contract as the kernel: q3 (B*Hq, S, D),
    k3/v3 (B*Hk, S, D); GQA via reshape-grouping, not repeat."""
    bhq, s, d = q3.shape
    bhk = k3.shape[0]
    qg = q3.reshape(bhk, kv_rep, s, d)
    sc = jnp.einsum("grsd,gtd->grst", qg.astype(jnp.float32),
                    k3.astype(jnp.float32)) * scale
    if causal_blk:
        keep = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]
        sc = jnp.where(keep[None, None], sc, _NEG_INF)
    m = jnp.max(sc, axis=-1)
    m_safe = jnp.where(m <= _NEG_INF / 2, 0.0, m)
    p = jnp.exp(sc - m_safe[..., None])
    if causal_blk:
        p = jnp.where(keep[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("grst,gtd->grsd", p, v3.astype(jnp.float32))
    l_safe = jnp.maximum(l, 1e-12)
    lse = jnp.where(l > 0, m_safe + jnp.log(l_safe), _NEG_INF)
    return (out / l_safe[..., None]).reshape(bhq, s, d), \
        lse.reshape(bhq, s)


def _block_fwd(q3, k3, v3, scale, causal_blk, kv_rep):
    if fa._pallas_ok(q3, k3):
        bq, bk = fa._pick_blocks(q3.shape[1], k3.shape[1])
        out, lse = fa._flash_fwd_pallas(q3, k3, v3, scale, causal_blk,
                                        bq, bk, kv_rep=kv_rep)
        return out.astype(jnp.float32), lse
    return _block_ref(q3, k3, v3, scale, causal_blk, kv_rep)


def _block_bwd(q3, k3, v3, out, lse, g, scale, causal_blk, kv_rep):
    """Per-block grads of the GLOBAL softmax: p = exp(s - lse_global).
    Returns (dq, dk, dv) with dk/dv already reduced to KV heads."""
    if fa._pallas_ok(q3, k3):
        bq, bk = fa._pick_blocks(q3.shape[1], k3.shape[1])
        return fa._flash_bwd_pallas(q3, k3, v3, out, lse, g, scale,
                                    causal_blk, bq, bk, kv_rep=kv_rep)
    bhq, s, d = q3.shape
    bhk = k3.shape[0]
    qg = q3.reshape(bhk, kv_rep, s, d).astype(jnp.float32)
    gg = g.reshape(bhk, kv_rep, s, d).astype(jnp.float32)
    og = out.reshape(bhk, kv_rep, s, d).astype(jnp.float32)
    lseg = lse.reshape(bhk, kv_rep, s)
    k32 = k3.astype(jnp.float32)
    v32 = v3.astype(jnp.float32)
    sc = jnp.einsum("grsd,gtd->grst", qg, k32) * scale
    if causal_blk:
        keep = (jnp.arange(s)[:, None] >= jnp.arange(s)[None, :])[None, None]
        sc = jnp.where(keep, sc, _NEG_INF)
    p = jnp.exp(sc - lseg[..., None])
    if causal_blk:
        p = jnp.where(keep, p, 0.0)
    delta = jnp.sum(gg * og, axis=-1)
    dv = jnp.einsum("grst,grsd->gtd", p, gg)
    dp = jnp.einsum("grsd,gtd->grst", gg, v32)
    ds = p * (dp - delta[..., None])
    dq = jnp.einsum("grst,gtd->grsd", ds, k32) * scale
    dk = jnp.einsum("grst,grsd->gtd", ds, qg) * scale
    return (dq.reshape(bhq, s, d).astype(q3.dtype),
            dk.astype(k3.dtype), dv.astype(v3.dtype))


def _merge(out1, lse1, out2, lse2):
    """Online-softmax merge of two normalized partial results. Fully-masked
    sides carry lse = -1e30 (finite), so their weight underflows to exactly 0
    and the other side's weight to 1 — no extra guarding needed."""
    lse_new = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse_new)
    w2 = jnp.exp(lse2 - lse_new)
    return out1 * w1[..., None] + out2 * w2[..., None], lse_new


# ===========================================================================
# The ring (per-device program, runs inside shard_map)
# ===========================================================================
def ring_attention_array(q, k, v, axis_name: str, causal: bool = True,
                         scale: Optional[float] = None):
    """Per-device blockwise ring attention, called inside shard_map.

    q, k, v: (B, S_local, H, D) paddle layout (GQA: H_kv may divide H).
    Returns (B, S_local, H, D). Differentiable via a ring-replay custom
    VJP; per-device live memory is O(S_local) in both passes.
    """
    b, s_loc, hq, d = q.shape
    hk = k.shape[2]
    rep = hq // hk
    sc = scale if scale is not None else 1.0 / math.sqrt(d)

    p_size = lax.psum(1, axis_name)
    perm = [(i, (i + 1) % p_size) for i in range(p_size)]

    # flattened internal layout: q (B*Hq, S, D); k/v (B*Hk, S, D)
    q3 = q.transpose(0, 2, 1, 3).reshape(b * hq, s_loc, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * hk, s_loc, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * hk, s_loc, d)

    # NOTE: lax.axis_index is evaluated INSIDE each custom_vjp function —
    # a closure-captured index tracer escapes its trace under
    # jit(grad(shard_map(...))) (UnexpectedTracerError in the dryrun)

    def block_cases(my, src, qq, kr, vr):
        """(out, lse) for the chunk currently held, by visibility case."""
        def full(_):
            return _block_fwd(qq, kr, vr, sc, False, rep)

        def diag(_):
            return _block_fwd(qq, kr, vr, sc, True, rep)

        def skip(_):
            return (jnp.zeros((b * hq, s_loc, d), jnp.float32),
                    jnp.full((b * hq, s_loc), _NEG_INF, jnp.float32))

        if not causal:
            return full(None)
        idx = jnp.where(src > my, 0, jnp.where(src == my, 1, 2))
        return lax.switch(idx, [skip, diag, full], None)

    @jax.custom_vjp
    def ring(qq, kk, vv):
        out, _ = ring_fwd(qq, kk, vv)
        return out

    def ring_fwd(qq, kk, vv):
        my = lax.axis_index(axis_name)

        def step(carry, r):
            acc, lse, kr, vr = carry
            src = (my - r) % p_size
            out_r, lse_r = block_cases(my, src, qq, kr, vr)
            acc, lse = _merge(acc, lse, out_r, lse_r)
            kr, vr = (lax.ppermute(t, axis_name, perm) for t in (kr, vr))
            return (acc, lse, kr, vr), None

        init = (jnp.zeros((b * hq, s_loc, d), jnp.float32),
                jnp.full((b * hq, s_loc), _NEG_INF, jnp.float32),
                kk, vv)
        # scan p_size-1 steps, fold the LAST block outside: its trailing
        # ppermute would be dead work (the backward, by contrast, needs the
        # full cycle to bring dK/dV home)
        (acc, lse, kr, vr), _ = lax.scan(step, init,
                                         jnp.arange(p_size - 1))
        last = p_size - 1
        out_r, lse_r = block_cases(my, (my - last) % p_size, qq, kr, vr)
        acc, lse = _merge(acc, lse, out_r, lse_r)
        out = acc.astype(qq.dtype)
        return out, (qq, kk, vv, out, lse)

    def ring_bwd(res, g):
        qq, kk, vv, out, lse = res
        g = g.astype(qq.dtype)
        my = lax.axis_index(axis_name)

        def step(carry, r):
            dq, kr, vr, dkr, dvr = carry
            src = (my - r) % p_size

            def full(_):
                return _block_bwd(qq, kr, vr, out, lse, g, sc, False, rep)

            def diag(_):
                return _block_bwd(qq, kr, vr, out, lse, g, sc, True, rep)

            def skip(_):
                return (jnp.zeros_like(qq), jnp.zeros_like(kr),
                        jnp.zeros_like(vr))

            if causal:
                idx = jnp.where(src > my, 0, jnp.where(src == my, 1, 2))
                dq_r, dk_r, dv_r = lax.switch(idx, [skip, diag, full], None)
            else:
                dq_r, dk_r, dv_r = full(None)
            dq = dq + dq_r.astype(jnp.float32)
            # dK/dV partials travel WITH their KV chunk: after the full
            # cycle each chunk is home with every device's contribution
            dkr = dkr + dk_r.astype(jnp.float32)
            dvr = dvr + dv_r.astype(jnp.float32)
            kr, vr, dkr, dvr = (lax.ppermute(t, axis_name, perm)
                                for t in (kr, vr, dkr, dvr))
            return (dq, kr, vr, dkr, dvr), None

        init = (jnp.zeros((b * hq, s_loc, d), jnp.float32), kk, vv,
                jnp.zeros((b * hk, s_loc, d), jnp.float32),
                jnp.zeros((b * hk, s_loc, d), jnp.float32))
        (dq, _, _, dk, dv), _ = lax.scan(step, init, jnp.arange(p_size))
        return (dq.astype(qq.dtype), dk.astype(kk.dtype),
                dv.astype(vv.dtype))

    ring.defvjp(ring_fwd, ring_bwd)
    out = ring(q3, k3, v3)
    return out.reshape(b, hq, s_loc, d).transpose(0, 2, 1, 3).astype(q.dtype)


def ring_flash_attention(query, key, value, group=None, causal: bool = True,
                         scale: Optional[float] = None, axis: str = "sep"):
    """Eager/global-array entry: inputs (B, S, H, D) with S the FULL
    sequence; runs the ring program over the mesh's ``sep`` (context) axis
    and returns the full-sequence result. Differentiable (tape-recorded)."""
    mesh = _mesh.ensure_mesh() if group is None else group.mesh
    ax = getattr(group, "axis", axis)
    deg = mesh.shape.get(ax, 1)

    def fn(qv, kv, vv):
        if deg <= 1:
            return fa._sdpa_array(qv, kv, vv, scale=scale or
                                  1.0 / math.sqrt(qv.shape[-1]), causal=causal)
        prog = shard_map(
            partial(ring_attention_array, axis_name=ax, causal=causal,
                    scale=scale),
            mesh=mesh, in_specs=(P(None, ax), P(None, ax), P(None, ax)),
            out_specs=P(None, ax), check_vma=False)
        return prog(qv, kv, vv)

    return apply(fn, query, key, value, op_name="ring_flash_attention")
