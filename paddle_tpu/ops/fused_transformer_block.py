"""Fused decoder-stack kernel — the ``fused_multi_transformer`` rebuild.

Reference: paddle/fluid/operators/fused/fused_multi_transformer_op.cu(.h):§0 —
a megakernel that loops over all decoder layers inside ONE op: per layer
pre-LayerNorm → QKV GEMM → FMHA (with KV cache + ``time_step`` decode path) →
out-proj → residual → FFN-LN → FFN1 → act → FFN2 → residual. Python surface:
python/paddle/incubate/nn/functional/fused_transformer.py:§0 and the
``FusedMultiTransformer`` layer (SURVEY.md §2.2).

TPU-native design: the layer loop is a ``lax.scan`` over stacked parameters
(one XLA computation for the whole stack — the compile-time analogue of the
reference's in-kernel loop), attention goes through the Pallas flash kernel
for prefill and a fused masked-softmax decode path for ``time_step`` steps,
and LayerNorm/residual/FFN fuse under XLA. KV cache layout is
``[L, 2, B, nh, S_max, hd]`` (k=0 / v=1), decode writes one slot per step.

Stacked parameter pytree (leading dim L = num layers):
  ln_scale, ln_bias        [L, H]
  qkv_w [L, H, 3H], qkv_b  [L, 3H]
  out_w [L, H, H],  out_b  [L, H]
  ffn_ln_scale/bias        [L, H]
  ffn1_w [L, H, F], ffn1_b [L, F]
  ffn2_w [L, F, H], ffn2_b [L, H]
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from . import flash_attention as fa

_ACTS = {
    "gelu": jax.nn.gelu,
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
}


def layer_norm_array(x, scale=None, bias=None, eps=1e-5):
    """fp32-accumulated LayerNorm (fused by XLA; parity with the reference's
    in-kernel LN in fused_multi_transformer_op.cu.h:§0). scale/bias optional
    so fused epilogues (bias_dropout_residual_ln) share ONE LN numerics.

    With FLAGS_use_pallas_layer_norm the scale+bias form routes through
    the single-pass Pallas kernel (ops/layer_norm_fused.py)."""
    if scale is not None and bias is not None:
        from .layer_norm_fused import _use_pallas_ln, layer_norm_fused
        from .rms_norm import _pick_block_rows
        h = x.shape[-1]
        rows = 1
        for s_ in x.shape[:-1]:
            rows *= s_
        if _use_pallas_ln() and h % 128 == 0 and _pick_block_rows(rows, h):
            return layer_norm_fused(x, scale, bias, eps)
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + eps)
    if scale is not None:
        y = y * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def _split_heads(qkv, num_heads):
    # (B, S, 3H) -> 3 × (B, nh, S, hd)
    b, s, three_h = qkv.shape
    h = three_h // 3
    hd = h // num_heads
    qkv = qkv.reshape(b, s, 3, num_heads, hd)
    q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
    return q, k, v


def _prefill_attention(q, k, v, attn_mask, causal=True, seg_ids=None):
    b, nh, s, hd = q.shape
    scale = 1.0 / math.sqrt(hd)
    if seg_ids is not None:
        if attn_mask is not None:
            raise ValueError("seg_ids and attn_mask are mutually exclusive")
        return fa.flash_attention_segmented(q, k, v, seg_ids, scale=scale,
                                            causal=causal)
    if attn_mask is None:
        out = fa.flash_attention_bhsd(
            q.reshape(b * nh, s, hd), k.reshape(b * nh, s, hd),
            v.reshape(b * nh, s, hd), scale, causal)
        return out.reshape(b, nh, s, hd)
    logits = jnp.einsum("bnqd,bnkd->bnqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        logits = jnp.where(jnp.tril(jnp.ones((s, s), bool)), logits, -jnp.inf)
    logits = logits + attn_mask.astype(jnp.float32)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bnkd->bnqd", p, v)


def _decode_attention(q, k_cache, v_cache, cur_len, seq_lens=None,
                      new_span=None):
    """Single-step attention against the cache: q (B, nh, 1, hd),
    cache (B, nh, Smax, hd); positions >= cur_len masked out.

    ``seq_lens`` (B,) handles ragged batches: prefix positions are valid only
    below each sequence's own prefill length, while ``new_span=(start, s)``
    (the slots the current step just wrote) stays valid for everyone — the
    reference kernel gets the same effect from its decode attn_mask
    (fused_multi_transformer_op.cu.h:§0).
    """
    hd = q.shape[-1]
    scale = 1.0 / math.sqrt(hd)
    logits = jnp.einsum("bnqd,bnkd->bnqk", q, k_cache,
                        preferred_element_type=jnp.float32) * scale
    pos = jnp.arange(k_cache.shape[2])
    if seq_lens is None:
        valid = pos[None, None, None, :] < cur_len
    else:
        start, s = new_span
        prefix = pos[None, :] < seq_lens[:, None]           # (B, Smax)
        new = (pos >= start) & (pos < start + s)
        valid = (prefix | new[None, :])[:, None, None, :]
    logits = jnp.where(valid, logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bnkd->bnqd", p, v_cache)


def _int8_mm(x, wq, w_scale, in_scale=None):
    """A8W8 matmul on the MXU's int8 path: x (..., K) float, wq (K, N)
    int8, w_scale (N,) per-output-channel. Activations quantize per-token
    (dynamic amax) unless a calibrated scalar ``in_scale`` is given —
    the reference fused_multi_transformer_int8's *_in_scale attributes
    (fused_multi_transformer_int8_op.cu:§0). int8×int8→int32 accumulate,
    one dequant multiply on the way out."""
    xf = x.astype(jnp.float32)
    if in_scale is None:
        amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
        xs = jnp.maximum(amax, 1e-6) / 127.0           # (..., 1)
    else:
        # Calibrated in_scale follows the reference convention: the scale is
        # the max-abs RANGE (q = round(127*x/in_scale)), so the quantization
        # STEP is in_scale/127 — a calibrated scale equal to the observed
        # amax must reproduce the dynamic path exactly.
        xs = jnp.asarray(in_scale, jnp.float32) / 127.0
    xq = jnp.clip(jnp.round(xf / xs), -127, 127).astype(jnp.int8)
    y = lax.dot_general(xq, wq, (((xq.ndim - 1,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32)
    return y.astype(jnp.float32) * xs * w_scale


def _one_layer(x, p, *, num_heads, act, eps, attn_mask, kv_cache, time_step,
               seq_lens=None, mm=None):
    """One fused decoder layer. Returns (y, (k, v)) where k/v are this
    layer's new cache contents (or the per-step k/v in decode mode).
    ``mm(xn, w_key, b_key)`` overrides the four projection matmuls (the
    int8 path routes them through _int8_mm)."""
    if mm is None:
        def mm(t, wk, bk):
            return t @ p[wk] + p[bk]
    b, s, h = x.shape
    xn = layer_norm_array(x, p["ln_scale"], p["ln_bias"], eps)
    qkv = mm(xn, "qkv_w", "qkv_b")
    q, k, v = _split_heads(qkv, num_heads)

    if kv_cache is not None and time_step is not None:
        k_cache, v_cache = kv_cache
        k_cache = lax.dynamic_update_slice_in_dim(k_cache, k, time_step, axis=2)
        v_cache = lax.dynamic_update_slice_in_dim(v_cache, v, time_step, axis=2)
        attn = _decode_attention(q, k_cache, v_cache, time_step + s,
                                 seq_lens=seq_lens, new_span=(time_step, s))
        new_kv = (k_cache, v_cache)
    else:
        attn = _prefill_attention(q, k, v, attn_mask)
        new_kv = (k, v)

    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, h)
    x = x + mm(attn, "out_w", "out_b").astype(x.dtype)

    xn = layer_norm_array(x, p["ffn_ln_scale"], p["ffn_ln_bias"], eps)
    f = _ACTS[act](mm(xn, "ffn1_w", "ffn1_b"))
    x = x + mm(f, "ffn2_w", "ffn2_b").astype(x.dtype)
    return x, new_kv


def fused_multi_transformer_array(
        x, params, *, num_heads: int, act: str = "gelu", epsilon: float = 1e-5,
        attn_mask=None, cache_kv=None, time_step: Optional[int] = None,
        max_cache_len: Optional[int] = None, seq_lens=None,
        int8: bool = False):
    """Run the whole decoder stack as one scanned computation.

    Prefill (``time_step=None``): causal flash attention; when
    ``max_cache_len`` is set, returns a right-padded KV cache ready for
    decode. Decode (``time_step`` set, S==1): reads/updates ``cache_kv``
    in place (functionally) and attends over the valid prefix.

    ``int8=True`` (reference fused_multi_transformer_int8_op.cu:§0): the
    four projection weights arrive quantized — ``{name}_q`` int8 +
    ``{name}_scale`` per-out-channel, with optional calibrated
    ``{name}_in_scale`` activation scales — and the matmuls run
    int8×int8→int32 on the MXU with a fused dequant multiply.

    Returns ``(out, cache_kv)`` — ``cache_kv`` is ``[L, 2, B, nh, Sc, hd]``
    or None when no cache was requested.
    """

    def make_mm(p):
        if not int8:
            return None

        def mm(t, wk, bk):
            return _int8_mm(t, p[wk + "_q"], p[wk + "_scale"],
                            p.get(wk + "_in_scale")) + p[bk]

        return mm

    if time_step is not None:
        if cache_kv is None:
            raise ValueError("decode mode (time_step set) requires cache_kv")

        def step(carry, layer_in):
            p, kv = layer_in
            y, new_kv = _one_layer(
                carry, p, num_heads=num_heads, act=act, eps=epsilon,
                attn_mask=None, kv_cache=(kv[0], kv[1]), time_step=time_step,
                seq_lens=seq_lens, mm=make_mm(p))
            return y, jnp.stack(new_kv)

        out, new_cache = lax.scan(step, x, (params, cache_kv))
        return out, new_cache

    def step(carry, p):
        y, (k, v) = _one_layer(
            carry, p, num_heads=num_heads, act=act, eps=epsilon,
            attn_mask=attn_mask, kv_cache=None, time_step=None,
            mm=make_mm(p))
        return y, jnp.stack([k, v])

    out, kv = lax.scan(step, x, params)
    if max_cache_len is None and cache_kv is None:
        return out, None
    target = max_cache_len or cache_kv.shape[4]
    s = x.shape[1]
    pad = target - s
    if pad < 0:
        raise ValueError(f"sequence {s} exceeds cache length {target}")
    kv = jnp.pad(kv, ((0, 0), (0, 0), (0, 0), (0, 0), (0, pad), (0, 0)))
    return out, kv


def init_stacked_block_params(num_layers, hidden, ffn_hidden, seed=0,
                              dtype=jnp.float32):
    """Convenience init for the stacked parameter pytree (tests/benches)."""
    import numpy as np
    rng = np.random.RandomState(seed)

    def w(*shape, scale=0.02):
        return jnp.asarray(rng.normal(0, scale, shape), dtype)

    L, H, F = num_layers, hidden, ffn_hidden
    return {
        "ln_scale": jnp.ones((L, H), dtype), "ln_bias": jnp.zeros((L, H), dtype),
        "qkv_w": w(L, H, 3 * H), "qkv_b": jnp.zeros((L, 3 * H), dtype),
        "out_w": w(L, H, H), "out_b": jnp.zeros((L, H), dtype),
        "ffn_ln_scale": jnp.ones((L, H), dtype),
        "ffn_ln_bias": jnp.zeros((L, H), dtype),
        "ffn1_w": w(L, H, F), "ffn1_b": jnp.zeros((L, F), dtype),
        "ffn2_w": w(L, F, H), "ffn2_b": jnp.zeros((L, H), dtype),
    }
