"""Fused LayerNorm (forward + backward) — Pallas TPU kernel with XLA
fallback, mirroring ops/rms_norm.py's structure.

Rebuild target: the reference's fused LayerNorm CUDA kernels
(paddle/phi/kernels/gpu/layer_norm_kernel.cu — SURVEY.md §2.2). Round-4
motivation: the ViT-L profile (benchmarks/PROFILE_vit_r4.md) shows the
encoder's 49 LayerNorm instances compiling to multiply_reduce +
convert_reduce chains worth 19.2 ms/step — a single-pass kernel holds the
row block in VMEM across mean, variance, normalize, and the backward's
three reductions.

Math (fp32 accumulation):
    mu = mean(x); var = mean((x-mu)^2); inv = rsqrt(var+eps)
    xhat = (x-mu)*inv;  y = xhat*w + b
    dx = inv * (wg - mean(wg) - xhat * mean(wg*xhat)),  wg = w*g
    dw = sum_rows(g*xhat);  db = sum_rows(g)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import use_pallas
from .rms_norm import _pick_block_rows


def _use_pallas_ln() -> bool:
    from ..flags import flag_value
    return use_pallas() and flag_value("use_pallas_layer_norm")


def _ln_ref(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    xhat = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (xhat * w.astype(jnp.float32)
            + b.astype(jnp.float32)).astype(x.dtype)


def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    xhat = (x - mu) * jax.lax.rsqrt(var + eps)
    y_ref[...] = (xhat * w_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(y_ref.dtype)


def _bwd_kernel(x_ref, w_ref, g_ref, dx_ref, dw_ref, db_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = (x - mu) * inv
    wg = w * g
    m1 = jnp.mean(wg, axis=-1, keepdims=True)
    m2 = jnp.mean(wg * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (inv * (wg - m1 - xhat * m2)).astype(dx_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    dw_ref[...] += jnp.sum(g * xhat, axis=0, keepdims=True)
    db_ref[...] += jnp.sum(g, axis=0, keepdims=True)


def _pallas_fwd(x2, w, b, eps, interpret=False):
    rows, h = x2.shape
    br = _pick_block_rows(rows, h)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(rows // br,),
        interpret=interpret,
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, h), x2.dtype),
    )(x2, w.reshape(1, h), b.reshape(1, h))


def _pallas_bwd(x2, w, g2, eps, interpret=False):
    rows, h = x2.shape
    br = _pick_block_rows(rows, h)
    dx, dw, db = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps),
        grid=(rows // br,),
        interpret=interpret,
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((br, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, h), x2.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
    )(x2, w.reshape(1, h), g2)
    return dx, dw.reshape(h), db.reshape(h)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layer_norm_fused(x, w, b, eps=1e-5):
    y, _ = _ln_fwd(x, w, b, eps)
    return y


def _rows(x):
    r = 1
    for s in x.shape[:-1]:
        r *= s
    return r


def _ln_fwd(x, w, b, eps):
    h = x.shape[-1]
    rows = _rows(x)
    if _use_pallas_ln() and h % 128 == 0 and _pick_block_rows(rows, h):
        y = _pallas_fwd(x.reshape(rows, h), w, b, eps)
        return y.reshape(x.shape), (x, w, b)
    return _ln_ref(x, w, b, eps), (x, w, b)


def _ln_bwd(eps, res, g):
    x, w, b = res
    h = x.shape[-1]
    rows = _rows(x)
    if _use_pallas_ln() and h % 128 == 0 and _pick_block_rows(rows, h):
        dx, dw, db = _pallas_bwd(x.reshape(rows, h), w,
                                 g.reshape(rows, h), eps)
        return dx.reshape(x.shape), dw.astype(w.dtype), db.astype(b.dtype)
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = (xf - mu) * inv
    wg = wf * gf
    m1 = jnp.mean(wg, axis=-1, keepdims=True)
    m2 = jnp.mean(wg * xhat, axis=-1, keepdims=True)
    dx = (inv * (wg - m1 - xhat * m2)).astype(x.dtype)
    red = tuple(range(x.ndim - 1))
    dw = jnp.sum(gf * xhat, axis=red).astype(w.dtype)
    db = jnp.sum(gf, axis=red).astype(b.dtype)
    return dx, dw, db


layer_norm_fused.defvjp(_ln_fwd, _ln_bwd)
