"""Fused RMSNorm (forward + backward) — Pallas TPU kernel with XLA fallback.

Rebuild of the reference's ``rms_norm`` CUDA kernel
(paddle/phi/kernels/gpu/rms_norm_kernel.cu, python wrapper
python/paddle/incubate/nn/functional/fused_rms_norm.py — SURVEY.md §2.2).

Math (fp32 accumulation regardless of input dtype):
    inv = rsqrt(mean(x^2, -1) + eps);  y = x * inv * w
    dx  = inv * (w*g) - x * inv^3 / H * sum(w*g*x, -1)
    dw  = sum_batch(g * x * inv)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import use_pallas, next_multiple


def _use_pallas_rms() -> bool:
    # dedicated knob so the round-4 win-or-delete decision (VERDICT r3
    # weak-4) can isolate rms_norm from the other Pallas kernels
    from ..flags import flag_value
    return use_pallas() and flag_value("use_pallas_rms_norm")
from ..core.dispatch import apply


# ---------------------------------------------------------------------------
# XLA reference path (numerics oracle; used on CPU and in tests)
# ---------------------------------------------------------------------------
def _rms_norm_ref(x, w, eps):
    xf = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * inv * w.astype(jnp.float32)).astype(x.dtype)


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------
_BLOCK_ROWS = 256


def _fwd_kernel(x_ref, w_ref, y_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    y_ref[...] = (x * inv * w).astype(y_ref.dtype)


def _bwd_kernel(x_ref, w_ref, g_ref, dx_ref, dw_ref, *, eps):
    # dw is a (1, h) accumulator revisited by every grid step (TPU grid is
    # sequential): Mosaic rejects a (1, h) block into an (nb, h) array
    # (row-block 1 < 8), but a block equal to the whole array is legal.
    # inv is RECOMPUTED from x (x is already in VMEM) rather than stored in
    # fwd: saves a (rows, 128) fp32 HBM round-trip per layer.
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    h = x.shape[-1]
    wg = w * g
    dot = jnp.sum(wg * x, axis=-1, keepdims=True)
    dx = inv * wg - x * (inv ** 3) * (dot / h)
    dx_ref[...] = dx.astype(dx_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    dw_ref[...] += jnp.sum(g * x * inv, axis=0, keepdims=True)


# chip evidence (round 2, v5e): ISOLATED microbenchmarks show XLA ahead at
# wide rows (h=2048: 4.5 vs 7.6 ms) — but END-TO-END the 876M h=3072 bench
# drops 50.6% -> 48.7% MFU when rms_norm falls back to XLA, so Pallas stays
# engaged at every width: inside the full graph the custom_vjp boundary
# changes XLA's surrounding fusion in our favour. Trust the end-to-end
# number over the microbenchmark.
def _pick_block_rows(rows: int, h: int = 128) -> int:
    """Largest row block dividing ``rows`` whose bwd working set fits VMEM.

    The bwd kernel holds ~6 (br, h) fp32 buffers (x, w·g, dx, g, intermediates)
    in the ~16MB VMEM; budget 12MB with a 2x safety margin → br·h·32B cap.
    (Round-2 fix: br=256 at h=4096 hit 'Ran out of memory in memory space
    vmem ... 18.16M > 16.00M' on the real chip.)"""
    budget = 12 * 1024 * 1024
    for br in (256, 128, 64, 32, 16, 8):
        if rows % br == 0 and br * h * 32 <= budget:
            return br
    return 0


def _pallas_fwd(x2, w, eps, interpret=False):
    rows, h = x2.shape
    br = _pick_block_rows(rows, h)
    grid = (rows // br,)
    y = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        interpret=interpret,
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, h), x2.dtype),
    )(x2, w.reshape(1, h))
    return y


def _pallas_bwd(x2, w, g2, eps, interpret=False):
    rows, h = x2.shape
    br = _pick_block_rows(rows, h)
    nb = rows // br
    dx, dw_part = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps),
        grid=(nb,),
        interpret=interpret,
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((br, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, h), x2.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
    )(x2, w.reshape(1, h), g2)
    return dx, dw_part.reshape(h)


# ---------------------------------------------------------------------------
# custom_vjp wiring
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm_array(x, w, eps=1e-6):
    y, _ = _rms_fwd(x, w, eps)
    return y


def _rms_fwd(x, w, eps):
    h = x.shape[-1]
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    if _use_pallas_rms() and h % 128 == 0 and _pick_block_rows(rows, h):
        x2 = x.reshape(rows, h)
        y = _pallas_fwd(x2, w, eps)
        return y.reshape(x.shape), (x, w)
    return _rms_norm_ref(x, w, eps), (x, w)


def _rms_bwd(eps, res, g):
    x, w = res
    h = x.shape[-1]
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    if _use_pallas_rms() and h % 128 == 0 and _pick_block_rows(rows, h):
        dx, dw = _pallas_bwd(x.reshape(rows, h), w, g.reshape(rows, h), eps)
        return dx.reshape(x.shape), dw.astype(w.dtype)
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    wg = wf * gf
    dot = jnp.sum(wg * xf, axis=-1, keepdims=True)
    dx = (inv * wg - xf * (inv ** 3) * (dot / h)).astype(x.dtype)
    dw = jnp.sum(gf * xf * inv, axis=tuple(range(x.ndim - 1))).astype(w.dtype)
    return dx, dw


rms_norm_array.defvjp(_rms_fwd, _rms_bwd)


# ---------------------------------------------------------------------------
# Tensor-level API
# ---------------------------------------------------------------------------
def rms_norm(x, weight, epsilon=1e-6):
    return apply(lambda xv, wv: rms_norm_array(xv, wv, epsilon), x, weight,
                 op_name="rms_norm")
