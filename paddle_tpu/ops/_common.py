"""Shared helpers for the kernel library."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..flags import flag_value


@functools.lru_cache(maxsize=1)
def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def use_pallas() -> bool:
    return on_tpu() and flag_value("use_pallas_kernels")


def next_multiple(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m
