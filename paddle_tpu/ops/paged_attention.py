"""Paged KV-cache attention + block-table cache manager.

The serving-side replacement for the reference's contiguous CacheKV in
fused_multi_transformer (paddle/fluid/operators/fused/
fused_multi_transformer_op.cu.h:§0 — SURVEY.md §2.2, §2.7 #18): KV lives in
fixed-size *pages*; each sequence owns a list of pages via a block table,
so ragged batches don't reserve max_len × batch HBM and finished sequences
return pages to the pool immediately (vLLM-style, and the layout of the
TPU ragged-paged-attention kernels referenced in PAPERS.md).

Two compute paths behind one dispatcher (:func:`paged_attention`):

* XLA fallback — gather of the sequence's pages + masked softmax, fused by
  XLA; runs everywhere (CPU tests included).
* Pallas kernel (:func:`paged_attention_pallas`) — the block table rides
  scalar prefetch, each grid step streams exactly ONE physical page
  HBM→VMEM (Mosaic double-buffers consecutive steps), online-softmax
  accumulation in VMEM scratch. HBM traffic is precisely the pages each
  sequence owns — the point of paging on a bandwidth-bound decode.
"""

from __future__ import annotations

import functools
import math
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30  # additive mask fill AND m_ref init — must stay identical


# ---------------------------------------------------------------------------
# Array-level op
# ---------------------------------------------------------------------------

def paged_attention_array(q, k_pages, v_pages, block_tables, seq_lens,
                          scale: Optional[float] = None):
    """Decode-time attention over paged KV.

    q:            (B, nh, d)        — one query token per sequence
    k_pages:      (P, page, nkv, d) — global page pool
    v_pages:      (P, page, nkv, d)
    block_tables: (B, max_pages) int32 — page ids per sequence (pad: 0)
    seq_lens:     (B,) int32 — valid KV length per sequence
    Returns (B, nh, d).
    """
    b, nh, d = q.shape
    page = k_pages.shape[1]
    nkv = k_pages.shape[2]
    max_pages = block_tables.shape[1]
    rep = nh // nkv

    # gather each sequence's pages: (B, max_pages, page, nkv, d)
    k = jnp.take(k_pages, block_tables, axis=0)
    v = jnp.take(v_pages, block_tables, axis=0)
    k = k.reshape(b, max_pages * page, nkv, d)
    v = v.reshape(b, max_pages * page, nkv, d)

    s = scale if scale is not None else 1.0 / math.sqrt(d)
    mask = jnp.arange(max_pages * page)[None, :] < seq_lens[:, None]
    if rep > 1:
        # grouped attention without materializing repeated KV (a
        # jnp.repeat here streamed rep x the gathered cache bytes — the
        # exact bandwidth GQA exists to save; same fix as
        # models/llama._cached_attention, round 5)
        qg = q.reshape(b, nkv, rep, d)
        scores = jnp.einsum("bgrd,bsgd->bgrs", qg.astype(jnp.float32),
                            k.astype(jnp.float32)) * s
        scores = jnp.where(mask[:, None, None, :], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bgrs,bsgd->bgrd", probs.astype(v.dtype), v)
        return out.reshape(b, nh, d)
    scores = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * s
    scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhs,bshd->bhd", probs.astype(v.dtype), v)


def paged_write_array(k_pages, v_pages, k_new, v_new, block_tables, positions):
    """Write one token's K/V into its page slot.

    k_new/v_new: (B, nkv, d); positions: (B,) absolute position of the new
    token. Returns updated (k_pages, v_pages).
    """
    page = k_pages.shape[1]
    page_idx = positions // page          # (B,) which logical page
    page_off = positions % page           # (B,) slot within the page
    phys = jnp.take_along_axis(block_tables, page_idx[:, None], axis=1)[:, 0]
    k_pages = k_pages.at[phys, page_off].set(k_new.astype(k_pages.dtype))
    v_pages = v_pages.at[phys, page_off].set(v_new.astype(v_pages.dtype))
    return k_pages, v_pages


def paged_prefill_attention_array(q, k_pages, v_pages, block_tables, q_start,
                                  scale: Optional[float] = None):
    """Chunked/suffix prefill attention over paged KV.

    The prefix-cache path (paddle_tpu.kvcache): a request whose leading
    tokens are already resident in shared pages prefills only its suffix.
    The suffix queries sit at absolute positions ``q_start + t`` and must
    attend to BOTH the cached prefix pages and the suffix's own (already
    scattered) K/V — so unlike the in-prompt causal mask of the full
    prefill, the mask here is ``key_pos <= q_start + t`` over the gathered
    page span.

    q:            (B, T, nh, d)  — suffix queries (right-padded)
    k_pages:      (P, page, nkv, d) — page pool (suffix K/V already written)
    v_pages:      (P, page, nkv, d)
    block_tables: (B, max_pages) int32 (pad: 0, the reserved garbage page)
    q_start:      (B,) int32 — absolute position of each row's first query
    Returns (B, T, nh, d).
    """
    b, t, nh, d = q.shape
    page = k_pages.shape[1]
    nkv = k_pages.shape[2]
    max_pages = block_tables.shape[1]
    rep = nh // nkv
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    k = jnp.take(k_pages, block_tables, axis=0)     # (B, max_pages, page, ..)
    v = jnp.take(v_pages, block_tables, axis=0)
    k = k.reshape(b, max_pages * page, nkv, d)
    v = v.reshape(b, max_pages * page, nkv, d)

    q_pos = q_start[:, None] + jnp.arange(t)[None, :]          # (B, T)
    mask = (jnp.arange(max_pages * page)[None, None, :]
            <= q_pos[:, :, None])                              # (B, T, S)
    if rep > 1:
        # grouped attention without materializing repeated KV (same
        # bandwidth argument as paged_attention_array)
        qg = q.reshape(b, t, nkv, rep, d)
        scores = jnp.einsum("btgrd,bsgd->bgrts", qg.astype(jnp.float32),
                            k.astype(jnp.float32)) * s
        scores = jnp.where(mask[:, None, None], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bgrts,bsgd->btgrd", probs.astype(v.dtype), v)
        return out.reshape(b, t, nh, d)
    scores = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * s
    scores = jnp.where(mask[:, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Ragged paged attention: ONE program for mixed prefill+decode rows
# ---------------------------------------------------------------------------

def ragged_paged_attention_array(q, k_pages, v_pages, block_tables, token_row,
                                 positions, kv_lens=None,
                                 scale: Optional[float] = None):
    """XLA reference of the unified ragged kernel (gather/mask composition).

    The serving engine's single-dispatch step packs every live row's
    tokens — decode rows contribute one token, prefill rows a chunk of
    their prompt — into one flat token axis. Each token attends to ITS
    row's pages under the one mask rule that subsumes both phases::

        key_pos <= positions[t]            (self-inclusive causality)

    A decode token at absolute position p sees keys [0, p] — exactly
    ``paged_attention``'s ``pos < kv_len`` with ``kv_len = p+1``; a
    prefill token at p sees the cached/scattered prefix plus itself —
    exactly ``paged_prefill_attention_array``'s ``key_pos <= q_start+t``.

    q:            (T, nh, d)   — packed queries (pad slots: token_row -1)
    k_pages:      (P, page, nkv, d)
    v_pages:      (P, page, nkv, d)
    block_tables: (R, max_pages) int32 (pad: reserved page 0)
    token_row:    (T,) int32 — owning row per token; -1 = pad slot
    positions:    (T,) int32 — absolute KV position per token
    kv_lens:      (R,) int32 — per-row attendable span (page-skip hint for
                  the Pallas kernel; unused by this reference)
    Returns (T, nh, d).
    """
    t, nh, d = q.shape
    page = k_pages.shape[1]
    nkv = k_pages.shape[2]
    n_rows, max_pages = block_tables.shape
    rep = nh // nkv
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    row_c = jnp.clip(token_row, 0, n_rows - 1)
    bt_tok = jnp.take(block_tables, row_c, axis=0)      # (T, max_pages)
    k = jnp.take(k_pages, bt_tok, axis=0)               # (T, W, page, ..)
    v = jnp.take(v_pages, bt_tok, axis=0)
    k = k.reshape(t, max_pages * page, nkv, d)
    v = v.reshape(t, max_pages * page, nkv, d)

    key_pos = jnp.arange(max_pages * page)[None, :]     # (1, S)
    mask = (key_pos <= positions[:, None]) & (token_row >= 0)[:, None]
    if rep > 1:
        # grouped attention without materializing repeated KV (same
        # bandwidth argument as paged_attention_array)
        qg = q.reshape(t, nkv, rep, d)
        scores = jnp.einsum("tgrd,tsgd->tgrs", qg.astype(jnp.float32),
                            k.astype(jnp.float32)) * s
        scores = jnp.where(mask[:, None, None, :], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("tgrs,tsgd->tgrd", probs.astype(v.dtype), v)
        return out.reshape(t, nh, d)
    scores = jnp.einsum("thd,tshd->ths", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * s
    scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("ths,tshd->thd", probs.astype(v.dtype), v)


def _ragged_attention_kernel(block_tables_ref, kv_lens_ref, token_row_ref,
                             positions_ref, q_ref, k_ref, v_ref, o_ref,
                             m_ref, l_ref, acc_ref, *, page: int,
                             n_pages: int, n_rows: int, scale: float,
                             nh: int, nkv: int, d: int, t: int):
    r = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when((r == 0) & (j == 0))
    def _zero_out():
        # pad slots (token_row -1) belong to no row and are never merged;
        # zero the whole output once so their lanes hold finite values
        # (uninitialized VMEM garbage scattered into the pool could poison
        # masked softmax lanes of OTHER rows via 0 * NaN)
        o_ref[...] = jnp.zeros_like(o_ref)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # skip pages beyond this row's attendable span (rows with no tokens
    # this round carry kv_len 0 and stream nothing)
    run = j * page < kv_lens_ref[r]

    @pl.when(run)
    def _compute():
        rep = nh // nkv
        q = q_ref[...].astype(jnp.float32)          # (T, nh, d)
        k = k_ref[0].astype(jnp.float32)            # (page, nkv, d)
        v = v_ref[0].astype(jnp.float32)
        tr = token_row_ref[...]                     # (T, 1) int32
        pos = positions_ref[...]                    # (T, 1) int32
        key_pos = j * page + jax.lax.broadcasted_iota(
            jnp.int32, (t, page), 1)                # (T, page)
        mask = (tr == r) & (key_pos <= pos)         # (T, page)
        # batched matmul wants the batch (kv-head) dim leading on both
        # operands (Mosaic "batch dims must be equal" — round-2 finding)
        qg = q.reshape(t, nkv, rep, d).swapaxes(0, 1).reshape(
            nkv, t * rep, d)
        kt = k.swapaxes(0, 1)                       # (nkv, page, d)
        vt = v.swapaxes(0, 1)
        s = jax.lax.dot_general(
            qg, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        mg = jnp.broadcast_to(mask[None, :, None, :], (nkv, t, rep, page)
                              ).reshape(nkv, t * rep, page)
        s = jnp.where(mg, s, _NEG_INF)
        # flatten to (T*nh, page) rows for the online-softmax state
        s2 = s.reshape(nkv, t, rep, page).swapaxes(0, 1).reshape(
            t * nh, page)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s2, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s2 - m_new)                     # (T*nh, page)
        l_ref[...] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True),
            l_ref.shape)
        pg = p.reshape(t, nkv, rep, page).swapaxes(0, 1).reshape(
            nkv, t * rep, page)
        pv = jax.lax.dot_general(
            pg, vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)     # (nkv, T*rep, d)
        pv2 = pv.reshape(nkv, t, rep, d).swapaxes(0, 1).reshape(t * nh, d)
        acc_ref[...] = acc_ref[...] * alpha + pv2
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == n_pages - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out = (acc_ref[...] / safe_l).reshape(t, nh, d)
        mine = (token_row_ref[...] == r)            # (T, 1)
        o_ref[...] = jnp.where(mine[:, :, None], out.astype(o_ref.dtype),
                               o_ref[...])


def ragged_paged_attention_pallas(q, k_pages, v_pages, block_tables,
                                  token_row, positions, kv_lens,
                                  scale: Optional[float] = None,
                                  interpret: bool = False):
    """Pallas ragged kernel: same contract as
    :func:`ragged_paged_attention_array`.

    Grid (rows, pages): each step streams exactly ONE physical page of
    one row HBM→VMEM via the scalar-prefetched block table (Mosaic
    double-buffers consecutive steps) and folds it into the online
    softmax of every packed token that belongs to the row — decode and
    prefill tokens alike, so a mixed batch is one dispatch whose shape
    is invariant to the request mix (PAPERS.md ragged paged attention).
    """
    t, nh, d = q.shape
    page = k_pages.shape[1]
    nkv = k_pages.shape[2]
    n_rows, max_pages = block_tables.shape
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block_tables, kv_lens
        grid=(n_rows, max_pages),
        in_specs=[
            pl.BlockSpec((t, 1), lambda r, j, bt, kvl: (0, 0)),
            pl.BlockSpec((t, 1), lambda r, j, bt, kvl: (0, 0)),
            pl.BlockSpec((t, nh, d), lambda r, j, bt, kvl: (0, 0, 0)),
            pl.BlockSpec((1, page, nkv, d),
                         lambda r, j, bt, kvl: (bt[r, j], 0, 0, 0)),
            pl.BlockSpec((1, page, nkv, d),
                         lambda r, j, bt, kvl: (bt[r, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((t, nh, d), lambda r, j, bt, kvl: (0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((t * nh, 128), jnp.float32),
            pltpu.VMEM((t * nh, 128), jnp.float32),
            pltpu.VMEM((t * nh, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _ragged_attention_kernel, page=page, n_pages=max_pages,
        n_rows=n_rows, scale=s, nh=nh, nkv=nkv, d=d, t=t)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, nh, d), v_pages.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), kv_lens.astype(jnp.int32),
      token_row.astype(jnp.int32).reshape(t, 1),
      positions.astype(jnp.int32).reshape(t, 1),
      q, k_pages, v_pages)


def ragged_paged_attention(q, k_pages, v_pages, block_tables, token_row,
                           positions, kv_lens, scale: Optional[float] = None,
                           mesh=None, mp_axis: str = "mp"):
    """Dispatcher: Pallas ragged kernel on TPU (FLAGS_use_pallas_kernels),
    XLA gather/mask fallback elsewhere — selected automatically, same
    contract either way (see ragged_paged_attention_array).

    ``mesh`` (a serving TP mesh with ``mp_axis`` degree > 1) only
    matters on the Pallas path: ``pallas_call`` cannot be partitioned by
    GSPMD, so the kernel runs under ``shard_map`` — each chip holds its
    GQA group slice of ``q``/``k_pages``/``v_pages`` (head-sharded
    pool), the row metadata is replicated, and the per-chip kernels are
    byte-identical to the single-chip kernel over their head slice
    (attention has no cross-head reduction, so there is no collective
    here at all). The XLA path ignores ``mesh``: GSPMD partitions the
    gather/einsum graph from the operand shardings alone."""
    from ._common import use_pallas
    if use_pallas():
        # not a traced-shape branch: Mesh.shape is the STATIC axis-degree
        # mapping of a construction-time mesh (engine compile keys carry
        # the chip count, so the specialisation is deliberate + counted)
        # tpu-lint: disable=trace-shape-branch
        if mesh is not None and mp_axis in mesh.shape \
                and mesh.shape[mp_axis] > 1:
            return _ragged_paged_attention_shard_mapped(
                q, k_pages, v_pages, block_tables, token_row, positions,
                kv_lens, scale, mesh, mp_axis)
        return ragged_paged_attention_pallas(
            q, k_pages, v_pages, block_tables, token_row, positions,
            kv_lens, scale)
    return ragged_paged_attention_array(
        q, k_pages, v_pages, block_tables, token_row, positions, kv_lens,
        scale)


def _ragged_paged_attention_shard_mapped(q, k_pages, v_pages, block_tables,
                                         token_row, positions, kv_lens,
                                         scale, mesh, mp_axis: str,
                                         interpret: bool = False):
    """The Pallas ragged kernel over a head-sharded pool: shard_map over
    ``mp_axis`` with whole GQA groups per chip. q: (T, nh, d) sharded on
    heads; pools: (LP, page, nkv, d) sharded on kv heads; metadata
    replicated; out (T, nh, d) sharded on heads. ``interpret`` runs the
    kernel in Pallas interpret mode (the CPU parity test for this
    multi-chip wrapper)."""
    from jax.sharding import PartitionSpec as P
    from ..core.compat import shard_map

    def local(q_l, kp_l, vp_l, bt, tr, pos, kvl):
        return ragged_paged_attention_pallas(
            q_l, kp_l, vp_l, bt, tr, pos, kvl, scale, interpret=interpret)

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(None, mp_axis, None),
                  P(None, None, mp_axis, None),
                  P(None, None, mp_axis, None),
                  P(None, None), P(None), P(None), P(None)),
        out_specs=P(None, mp_axis, None),
        check_vma=False,
    )(q, k_pages, v_pages, block_tables, token_row, positions, kv_lens)


# ---------------------------------------------------------------------------
# Host-side page pool (the allocator metadata; device arrays hold the data)
# ---------------------------------------------------------------------------

class PagedKVCacheManager:
    """Page pool + per-sequence block tables.

    The reference's KV memory comes from the C++ caching allocator
    (SURVEY.md §2.1 allocators row); on TPU the pool is one pre-allocated
    device array per layer and this class manages only host metadata
    (free list, per-sequence page lists) — no device allocation per step.
    Page 0 is reserved as the pad/garbage page so padded block-table slots
    always point at valid memory.
    """

    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 num_kv_heads: int, head_dim: int, dtype=jnp.bfloat16):
        self.page_size = page_size
        self.num_pages = num_pages
        shape = (num_layers, num_pages, page_size, num_kv_heads, head_dim)
        self.k_pages = jnp.zeros(shape, dtype)
        self.v_pages = jnp.zeros(shape, dtype)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))  # 0 reserved
        self._tables: dict = {}   # seq_id -> List[int]
        self._lens: dict = {}     # seq_id -> int
        self._page_nb: int = 0    # page_nbytes memo (geometry is fixed)
        #: TP chips the pool is head-sharded over (1 = single-chip);
        #: set by shard_heads — the memory ledger splits per-chip bytes
        #: off it and the engine stamps it into its compile keys
        self.mesh_chips: int = 1

    # -- allocation ---------------------------------------------------------

    def can_allocate(self, n_tokens: int) -> bool:
        return len(self._free) >= self.pages_for(n_tokens)

    @staticmethod
    def pages_needed(n_tokens: int, page_size: int) -> int:
        """Pages covering ``n_tokens`` at ``page_size`` granularity — THE
        page-math helper; every layer (scheduler, engines, kvcache)
        delegates here instead of re-deriving the ceil-div."""
        return (n_tokens + page_size - 1) // page_size

    def pages_for(self, n_tokens: int) -> int:
        return self.pages_needed(n_tokens, self.page_size)

    # deprecated alias (pre-kvcache spelling); new code uses pages_for()
    _pages_for = pages_for

    @property
    def usable_pages(self) -> int:
        """Allocatable pool capacity (page 0 is the reserved pad page)."""
        return self.num_pages - 1

    @property
    def page_nbytes(self) -> int:
        """Measured device bytes of ONE page (K + V slabs across every
        layer) — the memory ledger's byte unit; an int8 pool halves it
        automatically because it is read off the actual arrays.
        Memoized: the pool's geometry and dtype never change after
        construction."""
        pb = self._page_nb
        if not pb:
            pb = self._page_nb = (
                int(self.k_pages.nbytes)
                + int(self.v_pages.nbytes)) // self.num_pages
        return pb

    def _oom(self, source: str, need: int) -> None:
        """Allocation-failure forensics hook: every ``MemoryError`` this
        pool raises first lands in the HBM ledger (``oom_pressure``
        event + once-per-reason ``memory.json`` flight bundle). Gated on
        ``memory_armed`` inside; lazy import keeps the hot allocator
        free of the observability package at import time."""
        from ..observability.memory import note_oom
        note_oom(source, self, need_pages=need,
                 free_pages=len(self._free))

    def allocate(self, seq_id, n_tokens: int) -> List[int]:
        """Reserve pages for a new sequence of n_tokens (prefill)."""
        need = self.pages_for(n_tokens)
        if len(self._free) < need:
            self._oom("allocate", need)
            raise MemoryError(
                f"KV pool exhausted: need {need} pages, "
                f"{len(self._free)} free")
        pages = [self._free.pop() for _ in range(need)]
        self._tables[seq_id] = pages
        self._lens[seq_id] = n_tokens
        return pages

    def extend(self, seq_id, n_new: int = 1) -> None:
        """Grow a sequence; acquires a page on boundary crossings."""
        cur = self._lens[seq_id]
        new_len = cur + n_new
        have = len(self._tables[seq_id])
        need = self.pages_for(new_len)
        for _ in range(need - have):
            if not self._free:
                self._oom("extend", 1)
                raise MemoryError("KV pool exhausted on extend")
            self._tables[seq_id].append(self._free.pop())
        self._lens[seq_id] = new_len

    def free(self, seq_id) -> None:
        self._free.extend(reversed(self._tables.pop(seq_id)))
        self._lens.pop(seq_id)

    def sequence_pages(self, seq_id) -> List[int]:
        """The sequence's block table in token order (a copy — callers
        such as cross-host page export must not alias pool metadata)."""
        return list(self._tables.get(seq_id, ()))

    def sequence_len(self, seq_id) -> int:
        """Committed token length of a live sequence (0 if unknown)."""
        return int(self._lens.get(seq_id, 0))

    # -- speculative tail growth / rollback ----------------------------------

    def grow_to(self, seq_id, n_tokens: int) -> List[int]:
        """Ensure the sequence's block table covers ``n_tokens`` without
        committing them: speculative (drafted) tokens write into page
        tail positions past the committed length, so the pages must
        exist before the dispatch but the committed length (``_lens``)
        stays put until the host verifies the draft. Appended pages come
        fresh from the free list; raises ``MemoryError`` (leaving the
        table untouched) when the pool can't cover the span — callers
        shrink the draft instead. Returns the pages added."""
        need = self.pages_for(n_tokens) - len(self._tables[seq_id])
        if need <= 0:
            return []
        if len(self._free) < need:
            self._oom("grow_to", need)
            raise MemoryError(
                f"KV pool exhausted on speculative grow: need {need} "
                f"pages, {len(self._free)} free")
        added = [self._free.pop() for _ in range(need)]
        self._tables[seq_id].extend(added)
        return added

    def truncate_pages(self, seq_id, keep_pages: int) -> List[int]:
        """Roll a sequence's page span back to its first ``keep_pages``
        pages: the speculative-rollback primitive. A rejected draft
        strands any page that exists only to hold rejected tokens —
        those return to the pool here (stale K/V *within* kept pages
        needs no scrub: the next token at a position overwrites its slot
        before anything attends to it, the same scatter-first contract
        over-decoded garbage already relies on). The committed length is
        clamped into the kept span. Returns the pages returned to the
        free list."""
        table = self._tables[seq_id]
        freed: List[int] = []
        while len(table) > keep_pages:
            p = table.pop()
            self._free.append(p)
            freed.append(p)
        if self._lens.get(seq_id, 0) > keep_pages * self.page_size:
            self._lens[seq_id] = keep_pages * self.page_size
        return freed

    def check_conservation(self) -> None:
        """Exclusive-ownership audit (the refcounted subclass replaces
        this with the shared-ownership version): every usable page is
        either free or owned by exactly one sequence exactly once, the
        two sets are disjoint, and reserved page 0 never circulates.
        The serving engine runs this after every speculative step even
        without the prefix cache — draft growth/rollback is the first
        path that returns pages mid-sequence, so the books get audited
        on every round that can move them."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise RuntimeError("duplicate pages on the free list")
        owned: List[int] = []
        for table in self._tables.values():
            owned.extend(table)
        owned_set = set(owned)
        if len(owned) != len(owned_set):
            raise RuntimeError("page owned by two sequences (or twice "
                               "by one) under exclusive ownership")
        if free & owned_set:
            raise RuntimeError(
                f"page state overlap: free∩owned={free & owned_set}")
        if 0 in free | owned_set:
            raise RuntimeError("reserved page 0 entered circulation")
        total = len(free) + len(owned_set)
        if total != self.usable_pages:
            raise RuntimeError(
                f"page conservation violated: {len(free)} free + "
                f"{len(owned_set)} owned = {total} != "
                f"{self.usable_pages} usable")

    # -- multi-chip layout (TP-sharded serving) ------------------------------

    def shard_heads(self, mesh, mp_axis: str = "mp") -> None:
        """Head-shard both page pools over the mesh's ``mp_axis``: whole
        GQA (kv-head) groups per chip, so every page's bytes split
        evenly across the TP mesh and attention stays head-local. Pure
        LAYOUT — the allocator metadata (free list, tables, lens) is
        host-side and chip-agnostic, which is what makes an elastic
        resize a rebuild-and-replay rather than a data migration. The
        kv-head axis must divide by the mesh degree (whole groups per
        chip; a split group would split single heads across chips)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        chips = int(mesh.shape[mp_axis])
        nkv = self.k_pages.shape[3]
        if nkv % chips:
            raise ValueError(
                f"num_kv_heads={nkv} must divide by the TP degree "
                f"{chips} (whole GQA groups per chip)")
        ns = NamedSharding(mesh, P(None, None, None, mp_axis, None))
        self.k_pages = jax.device_put(self.k_pages, ns)
        self.v_pages = jax.device_put(self.v_pages, ns)
        self.mesh_chips = chips

    # -- views for the op ---------------------------------------------------

    @property
    def num_free_pages(self) -> int:
        return len(self._free)

    def seq_len(self, seq_id) -> int:
        return self._lens[seq_id]

    def block_tables(self, seq_ids) -> Tuple[np.ndarray, np.ndarray]:
        """(block_tables (B, max_pages), seq_lens (B,)) for a batch;
        padded slots point at reserved page 0."""
        tables = [self._tables[s] for s in seq_ids]
        width = max(len(t) for t in tables)
        bt = np.zeros((len(tables), width), np.int32)
        for i, t in enumerate(tables):
            bt[i, :len(t)] = t
        lens = np.asarray([self._lens[s] for s in seq_ids], np.int32)
        return bt, lens


# ---------------------------------------------------------------------------
# Pallas decode kernel (TPU): double-buffered page fetch via scalar-prefetched
# block tables — the ragged-paged-attention pattern (PAPERS.md)
# ---------------------------------------------------------------------------
def _paged_decode_kernel(block_tables_ref, seq_lens_ref, q_ref, k_ref, v_ref,
                         o_ref, m_ref, l_ref, acc_ref, *, page: int,
                         n_pages: int, scale: float, nh: int, nkv: int,
                         d: int):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    seq_len = seq_lens_ref[b]
    # skip pages entirely beyond this sequence's length
    run = j * page < seq_len

    @pl.when(run)
    def _compute():
        rep = nh // nkv
        q = q_ref[0].astype(jnp.float32)            # (nh, d)
        k = k_ref[0].astype(jnp.float32)            # (page, nkv, d)
        v = v_ref[0].astype(jnp.float32)
        qg = q.reshape(nkv, rep, d)
        # Mosaic's batched matmul requires the batch dim LEADING on both
        # operands ("batch dims must be equal" otherwise — round-2 chip
        # finding), so bring kv heads to the front first.
        kt = k.swapaxes(0, 1)                       # (nkv, page, d)
        vt = v.swapaxes(0, 1)                       # (nkv, page, d)
        # (nkv, rep, d) x (nkv, page, d) -> (nkv, rep, page)
        s = jax.lax.dot_general(
            qg, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        pos = j * page + jax.lax.broadcasted_iota(
            jnp.int32, (nkv, rep, page), 2)
        s = jnp.where(pos < seq_len, s, _NEG_INF)
        s2 = s.reshape(nh, page)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s2, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s2 - m_new)                     # (nh, page)
        l_ref[...] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
        pg = p.reshape(nkv, rep, page)
        # (nkv, rep, page) x (nkv, page, d) -> (nkv, rep, d)
        pv = jax.lax.dot_general(
            pg, vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + pv.reshape(nh, d)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == n_pages - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)


def paged_attention_pallas(q, k_pages, v_pages, block_tables, seq_lens,
                           scale: Optional[float] = None,
                           interpret: bool = False):
    """Pallas decode kernel: same contract as paged_attention_array.

    Each grid step fetches ONE physical page via the scalar-prefetched
    block table (Mosaic double-buffers the HBM→VMEM stream), so HBM
    traffic is exactly the pages each sequence owns — the fused
    gather+softmax the XLA fallback approximates.
    """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, nh, d = q.shape
    page = k_pages.shape[1]
    nkv = k_pages.shape[2]
    max_pages = block_tables.shape[1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block_tables, seq_lens
        grid=(b, max_pages),
        in_specs=[
            pl.BlockSpec((1, nh, d), lambda bi, j, bt, sl: (bi, 0, 0)),
            pl.BlockSpec((1, page, nkv, d),
                         lambda bi, j, bt, sl: (bt[bi, j], 0, 0, 0)),
            pl.BlockSpec((1, page, nkv, d),
                         lambda bi, j, bt, sl: (bt[bi, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nh, d), lambda bi, j, bt, sl: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, 128), jnp.float32),
            pltpu.VMEM((nh, 128), jnp.float32),
            pltpu.VMEM((nh, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_decode_kernel, page=page, n_pages=max_pages, scale=s,
        nh=nh, nkv=nkv, d=d)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nh, d), v_pages.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), seq_lens.astype(jnp.int32),
      q, k_pages, v_pages)


def paged_attention(q, k_pages, v_pages, block_tables, seq_lens,
                    scale: Optional[float] = None):
    """Dispatcher: Pallas kernel on TPU (FLAGS_use_pallas_kernels), XLA
    gather fallback elsewhere. Same contract as paged_attention_array."""
    from ._common import use_pallas
    if use_pallas():
        return paged_attention_pallas(q, k_pages, v_pages, block_tables,
                                      seq_lens, scale)
    return paged_attention_array(q, k_pages, v_pages, block_tables,
                                 seq_lens, scale)
