"""Flash attention (forward + backward) — Pallas TPU kernels + XLA fallback.

Rebuild of the reference's ``flash_attn`` path: CUDA glue
paddle/phi/kernels/gpu/flash_attn_kernel.cu + vendored libflashattn, Python
surface python/paddle/nn/functional/flash_attention.py (SURVEY.md §2.2).
Here the kernel itself is written in Pallas (online-softmax tiling over KV
blocks; fp32 accumulators in VMEM; LSE saved for the backward pass), which is
the TPU-native equivalent of FlashAttention-2.

Internal layout: (BH, S, D) with batch*heads flattened into the leading grid
dimension. Public entry points accept the paddle layout (B, S, H, D).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import use_pallas
from ..core.dispatch import apply, unwrap
from ..core.tensor import Tensor
from .. import random as _random

_NEG_INF = -1e30


def _mult(a: int, b: int) -> bool:
    return a % b == 0


# ===========================================================================
# Forward kernel
# ===========================================================================
def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, bq, bk, nkv,
                has_seg=False, kv_valid=None, causal_offset=0):
    if has_seg:
        segq_ref, segk_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = (j * bk < (i + 1) * bq + causal_offset) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (BQ, BK)
        if causal or kv_valid is not None or has_seg:
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = jnp.ones((bq, bk), dtype=bool)
            if causal:
                keep &= row + causal_offset >= col
            if kv_valid is not None:
                # static bound: keys beyond the unpadded length are masked
                keep &= col < kv_valid
            if has_seg:
                keep &= (segq_ref[0, 0][:, None] == segk_ref[0, 0][None, :])
            s = jnp.where(keep, s, _NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if causal or kv_valid is not None or has_seg:
            # exp(s - m) degenerates to 1 when EVERY entry of the block is
            # masked (m == s == -inf); zero masked probabilities explicitly
            # so fully-masked rows (in-row padding) produce 0, not mean(v)
            p = jnp.where(keep, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nkv - 1)
    def _finalize():
        l = l_ref[:, :1]
        m = m_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        # lse is carried as (BH, 1, S): a lane-major row per bh so the block
        # shape (1, 1, bq) satisfies Mosaic's (sublane, lane) tiling rule.
        lse_ref[0, 0] = (m + jnp.log(safe_l))[:, 0]


def _seg3(seg, bh):
    """Normalize segment ids for the kernels: (S,) -> shared (1, 1, S);
    (R, S) -> per-row (R, 1, S) with bh = R * rep heads per row. Returns
    (array, row_of_bh) where row_of_bh maps grid index b -> seg row."""
    if seg.ndim == 1:
        return seg[None, None, :], (lambda b: 0)
    rep = bh // seg.shape[0]
    return seg[:, None, :], (lambda b: b // rep)


def _flash_fwd_pallas(q, k, v, scale, causal, bq, bk, seg_q=None, seg_k=None,
                      kv_valid=None, causal_offset=0, interpret=False,
                      kv_rep=1):
    """``kv_rep`` implements GQA without materializing repeated KV: q has
    B*Hq rows, k/v have B*Hk rows (Hq = Hk*kv_rep, heads consecutive per
    batch entry), and the k/v BlockSpec index map shares each KV row across
    its kv_rep query heads."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nkv = sq // bq, sk // bk
    grid = (bh, nq, nkv)
    has_seg = seg_q is not None
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b // kv_rep, j, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b // kv_rep, j, 0)),
    ]
    args = [q, k, v]
    if has_seg:
        # segment ids travel lane-major as (R, 1, S): one row shared by
        # every bh (packed varlen) or one per batch row (packed batches)
        sq3, rowq = _seg3(seg_q, bh)
        sk3, rowk = _seg3(seg_k, bh)
        in_specs += [
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (rowq(b), 0, i)),
            pl.BlockSpec((1, 1, bk), lambda b, i, j: (rowk(b), 0, j))]
        args += [sq3, sk3]
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nkv=nkv, has_seg=has_seg,
                          kv_valid=kv_valid, causal_offset=causal_offset),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out, lse[:, 0]


# ===========================================================================
# Backward kernels
# ===========================================================================
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                   scale, causal, bq, bk, nkv, has_seg=False, kv_valid=None,
                   causal_offset=0):
    if has_seg:
        segq_ref, segk_ref, dq_ref, acc_ref = rest
    else:
        dq_ref, acc_ref = rest
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = (j * bk < (i + 1) * bq + causal_offset) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        p = jnp.exp(s - lse)
        if causal or kv_valid is not None or has_seg:
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = jnp.ones((bq, bk), dtype=bool)
            if causal:
                keep &= row + causal_offset >= col
            if kv_valid is not None:
                keep &= col < kv_valid
            if has_seg:
                keep &= (segq_ref[0, 0][:, None] == segk_ref[0, 0][None, :])
            p = jnp.where(keep, p, 0.0)
        dp = jax.lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        acc_ref[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(j == nkv - 1)
    def _finalize():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                    scale, causal, bq, bk, nq, has_seg=False, kv_valid=None,
                    causal_offset=0):
    if has_seg:
        segq_ref, segk_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = ((i + 1) * bq + causal_offset > j * bk) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        p = jnp.exp(s - lse)
        if causal or kv_valid is not None or has_seg:
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = jnp.ones((bq, bk), dtype=bool)
            if causal:
                keep &= row + causal_offset >= col
            if kv_valid is not None:
                keep &= col < kv_valid
            if has_seg:
                keep &= (segq_ref[0, 0][:, None] == segk_ref[0, 0][None, :])
            p = jnp.where(keep, p, 0.0)
        pt = p.astype(do.dtype)
        dv_acc[...] += jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32) * scale

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, g, scale, causal, bq, bk,
                      seg_q=None, seg_k=None, kv_valid=None, causal_offset=0,
                      interpret=False, kv_rep=1):
    """With ``kv_rep`` > 1 (GQA), k/v carry B*Hk rows shared across query
    heads via index maps; dk/dv are reduced over each KV row's kv_rep query
    heads before returning, so the caller always gets KV-shaped grads."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nkv = sq // bq, sk // bk
    has_seg = seg_q is not None
    # lse/delta travel as (BH, 1, S) — see _fwd_kernel note on Mosaic tiling.
    lse3 = lse[:, None, :]
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, None, :]

    dq_in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b // kv_rep, j, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b // kv_rep, j, 0)),
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
    ]
    dq_args = [q, k, v, g, lse3, delta]
    if has_seg:
        sq3, rowq = _seg3(seg_q, bh)
        sk3, rowk = _seg3(seg_k, bh)
        dq_in_specs += [
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (rowq(b), 0, i)),
            pl.BlockSpec((1, 1, bk), lambda b, i, j: (rowk(b), 0, j))]
        dq_args += [sq3, sk3]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nkv=nkv, has_seg=has_seg,
                          kv_valid=kv_valid, causal_offset=causal_offset),
        grid=(bh, nq, nkv),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(*dq_args)

    dkv_in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, j, i: (b // kv_rep, j, 0)),
        pl.BlockSpec((1, bk, d), lambda b, j, i: (b // kv_rep, j, 0)),
        pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, 0, i)),
        pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, 0, i)),
    ]
    dkv_args = [q, k, v, g, lse3, delta]
    if has_seg:
        dkv_in_specs += [
            pl.BlockSpec((1, 1, bq), lambda b, j, i: (rowq(b), 0, i)),
            pl.BlockSpec((1, 1, bk), lambda b, j, i: (rowk(b), 0, j))]
        dkv_args += [sq3, sk3]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, has_seg=has_seg,
                          kv_valid=kv_valid, causal_offset=causal_offset),
        grid=(bh, nkv, nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_args)
    if kv_rep != 1:
        # per-query-head dk/dv partials -> reduce over each KV row's group
        # (consecutive q heads share a KV head)
        dk = dk.reshape(bh // kv_rep, kv_rep, sk, d).sum(axis=1)
        dv = dv.reshape(bh // kv_rep, kv_rep, sk, d).sum(axis=1)
    return dq, dk, dv


# ===========================================================================
# XLA reference path (oracle + fallback), layout (BH, S, D)
# ===========================================================================
def _attn_ref(q, k, v, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


# ===========================================================================
# custom_vjp dispatcher
# ===========================================================================
def _pick_blocks(sq, sk):
    def pick(s):
        for b in (512, 256, 128):
            if s % b == 0:
                return b
        return None
    return pick(sq), pick(sk)


def _pad_to(s: int, mult: int = 128) -> int:
    return -(-s // mult) * mult


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_bhsd_inner(q, k, v, scale, causal, kv_valid, causal_offset):
    out, _ = _fa_fwd(q, k, v, scale, causal, kv_valid, causal_offset)
    return out


def _pallas_ok(q, k):
    bq, bk = _pick_blocks(q.shape[1], k.shape[1])
    # d=64 compiles cleanly under Mosaic (verified on chip: fwd+bwd parity
    # 4e-3 bf16) — required for the encoder family, whose hd = 1024/16 =
    # 64. Other non-128 multiples (192, 320, ...) stay on the fallback
    # until verified. Sequence threshold is measured: at S<=256 the XLA
    # einsum path wins (ViT-L S=197->256: 222 vs 215 img/s end-to-end);
    # from S=512 up the kernel wins (BERT S=512 d=64: 6.75 vs 10.8 ms;
    # llama S=2048 d=128: 1.7x) and the score materialization the kernel
    # avoids grows quadratically.
    d = q.shape[2]
    return use_pallas() and bq is not None and bk is not None and \
        (_mult(d, 128) or d == 64) and \
        max(q.shape[1], k.shape[1]) >= 512


def _fa_fwd(q, k, v, scale, causal, kv_valid, causal_offset):
    if _pallas_ok(q, k):
        bq, bk = _pick_blocks(q.shape[1], k.shape[1])
        out, lse = _flash_fwd_pallas(q, k, v, scale, causal, bq, bk,
                                     kv_valid=kv_valid,
                                     causal_offset=causal_offset)
        return out, (q, k, v, out, lse)
    out = _attn_ref_kv(q, k, v, scale, causal, kv_valid, causal_offset)
    return out, (q, k, v, out, None)


def _fa_bwd(scale, causal, kv_valid, causal_offset, res, g):
    q, k, v, out, lse = res
    if lse is not None and _pallas_ok(q, k):
        bq, bk = _pick_blocks(q.shape[1], k.shape[1])
        return _flash_bwd_pallas(q, k, v, out, lse, g, scale, causal, bq, bk,
                                 kv_valid=kv_valid,
                                 causal_offset=causal_offset)
    _, vjp = jax.vjp(
        lambda a, b, c: _attn_ref_kv(a, b, c, scale, causal, kv_valid,
                                     causal_offset),
        q, k, v)
    return vjp(g)


_flash_bhsd_inner.defvjp(_fa_fwd, _fa_bwd)


def _attn_ref_kv(q, k, v, scale, causal, kv_valid, causal_offset=0):
    """Reference path with the kernel's mask semantics: causal keeps
    row + causal_offset >= col (causal_offset = sk - sq of the ORIGINAL
    shapes — the end-aligned decode convention, 0 for self-attention) and
    cols >= kv_valid are masked. Slicing k instead would shift _attn_ref's
    end-aligned convention under padding."""
    if kv_valid is None and causal_offset == 0:
        return _attn_ref(q, k, v, scale, causal)
    sq, sk = q.shape[1], k.shape[1]
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    keep = (jnp.arange(sk) < (kv_valid if kv_valid is not None else sk)
            )[None, :]
    if causal:
        keep = keep & (jnp.arange(sq)[:, None] + causal_offset
                       >= jnp.arange(sk)[None, :])
    s = jnp.where(keep[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


def flash_attention_bhsd(q, k, v, scale, causal):
    """(BH, S, D) flash attention; differentiable; pallas on TPU.

    Ragged lengths (S % 128 != 0) no longer silently fall back to XLA:
    q/k/v are zero-padded to the next 128 multiple, padded KEYS are masked
    in-kernel via the static ``kv_valid`` bound, and the output is sliced
    back (padded-query rows carry zero cotangents, so gradients are exact).
    """
    sq, sk = q.shape[1], k.shape[1]
    # end-aligned causal for sq != sk (decode over a KV prefix): real row i
    # attends cols <= i + (sk - sq), matching _attn_ref / flash-attn
    offset = (sk - sq) if causal and sq != sk else 0
    psq, psk = _pad_to(sq), _pad_to(sk)
    if psq == sq and psk == sk:
        return _flash_bhsd_inner(q, k, v, scale, causal, None, offset)
    qp = jnp.pad(q, ((0, 0), (0, psq - sq), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, psk - sk), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, psk - sk), (0, 0)))
    out = _flash_bhsd_inner(qp, kp, vp, scale, causal,
                            sk if psk != sk else None, offset)
    return out[:, :sq]


# ===========================================================================
# Varlen (unpadded / packed) attention
# ===========================================================================
def _segments_from_cu(cu, total):
    """cu_seqlens (B+1,) -> per-token segment ids (total,), int32."""
    cu = jnp.asarray(cu, jnp.int32)
    return jnp.searchsorted(cu[1:], jnp.arange(total, dtype=jnp.int32),
                            side="right").astype(jnp.int32)


def _varlen_ref(q, k, v, seg_q, seg_k, scale, causal):
    """(H, T, D) packed reference path with segment + causal mask."""
    s = jnp.einsum("htd,hsd->hts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    keep = seg_q[:, None] == seg_k[None, :]
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        keep &= (jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :])
    s = jnp.where(keep[None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # rows with no visible key (padding segments) are fully masked; their
    # softmax is a uniform garbage row — zero it
    any_keep = jnp.any(keep, axis=-1)
    p = jnp.where(any_keep[None, :, None], p, 0.0)
    return jnp.einsum("hts,hsd->htd", p, v.astype(jnp.float32)).astype(q.dtype)


def flash_attention_varlen(q, k, v, cu_seqlens_q, cu_seqlens_k,
                           scale: Optional[float] = None,
                           causal: bool = True):
    """Unpadded (packed) flash attention — the reference's
    ``flash_attn_unpadded`` (python/paddle/nn/functional/flash_attention.py
    :§0, SURVEY.md §2.2).

    q/k/v: (total_tokens, H, D) with every sequence's tokens CONTIGUOUS;
    cu_seqlens_*: (B+1,) int cumulative lengths. TPU-native formulation: the
    packed stream runs as ONE dense kernel invocation with per-token
    segment ids masked in-kernel (cross-sequence attention blocked; causal
    within each sequence falls out of global positions because packing is
    order-preserving) — no per-sequence padding, no wasted MXU tiles
    beyond the final 128-alignment pad.
    """
    tq, h, d = q.shape
    tk = k.shape[0]
    if causal:
        # causal in packed coordinates is only defined when both sides
        # share the packing (self-attention); a drifting q/k offset would
        # silently zero-mask real rows
        if tq != tk or jnp.shape(cu_seqlens_q) != jnp.shape(cu_seqlens_k):
            raise ValueError(
                "flash_attention_varlen: causal=True requires "
                "cu_seqlens_q == cu_seqlens_k (self-attention packing)")
        try:
            same = bool(jnp.all(jnp.asarray(cu_seqlens_q)
                                == jnp.asarray(cu_seqlens_k)))
            if not same:
                raise ValueError(
                    "flash_attention_varlen: causal=True requires "
                    "cu_seqlens_q == cu_seqlens_k (self-attention packing)")
        except jax.errors.TracerBoolConversionError:
            pass  # traced lengths: requirement is documented
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    seg_q = _segments_from_cu(cu_seqlens_q, tq)
    seg_k = _segments_from_cu(cu_seqlens_k, tk)
    ptq, ptk = _pad_to(tq), _pad_to(tk)
    qp = jnp.pad(q, ((0, ptq - tq), (0, 0), (0, 0)))
    kp = jnp.pad(k, ((0, ptk - tk), (0, 0), (0, 0)))
    vp = jnp.pad(v, ((0, ptk - tk), (0, 0), (0, 0)))
    # distinct pad ids per side so padded q never matches padded k
    seg_qp = jnp.pad(seg_q, (0, ptq - tq), constant_values=-1)
    seg_kp = jnp.pad(seg_k, (0, ptk - tk), constant_values=-2)
    qt = jnp.moveaxis(qp, 1, 0)                      # (H, T, D)
    kt = jnp.moveaxis(kp, 1, 0)
    vt = jnp.moveaxis(vp, 1, 0)

    use_kernel = _pallas_ok(qt, kt)

    # seg ids are explicit custom_vjp arguments (NOT closure captures) so
    # grad(jax.jit(fn)) works when cu_seqlens is traced — a closure-captured
    # tracer escapes its trace and fails with "No constant handler for type
    # DynamicJaxprTracer" (ADVICE r3 #3). Their cotangents are float0
    # (integer-typed primals).
    @jax.custom_vjp
    def run(qq, kk, vv, sq_ids, sk_ids):
        out, _ = run_fwd(qq, kk, vv, sq_ids, sk_ids)
        return out

    def run_fwd(qq, kk, vv, sq_ids, sk_ids):
        if use_kernel:
            bq, bk = _pick_blocks(qq.shape[1], kk.shape[1])
            out, lse = _flash_fwd_pallas(qq, kk, vv, sc, causal, bq, bk,
                                         seg_q=sq_ids, seg_k=sk_ids)
            return out, (qq, kk, vv, sq_ids, sk_ids, out, lse)
        return _varlen_ref(qq, kk, vv, sq_ids, sk_ids, sc, causal), \
            (qq, kk, vv, sq_ids, sk_ids, None, None)

    def run_bwd(res, g):
        qq, kk, vv, sq_ids, sk_ids, out, lse = res
        zq = np.zeros(sq_ids.shape, jax.dtypes.float0)
        zk = np.zeros(sk_ids.shape, jax.dtypes.float0)
        if lse is not None:
            bq, bk = _pick_blocks(qq.shape[1], kk.shape[1])
            dq, dk, dv = _flash_bwd_pallas(qq, kk, vv, out, lse, g, sc,
                                           causal, bq, bk, seg_q=sq_ids,
                                           seg_k=sk_ids)
            return dq, dk, dv, zq, zk
        _, vjp = jax.vjp(
            lambda a, b, c: _varlen_ref(a, b, c, sq_ids, sk_ids, sc, causal),
            qq, kk, vv)
        dq, dk, dv = vjp(g)
        return dq, dk, dv, zq, zk

    run.defvjp(run_fwd, run_bwd)
    out = run(qt, kt, vt, seg_qp, seg_kp)             # (H, Tq_pad, D)
    return jnp.moveaxis(out, 0, 1)[:tq]


def _seg_ref_batched(q, k, v, seg, scale, causal):
    """(B, nh, S, D) reference path with per-row segment mask."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    keep = seg[:, None, :, None] == seg[:, None, None, :]
    keep &= (seg >= 0)[:, None, :, None]     # pads attend to nothing
    if causal:
        sq = q.shape[2]
        keep &= (jnp.arange(sq)[:, None] >= jnp.arange(sq)[None, :]
                 )[None, None]
    s = jnp.where(keep, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    any_keep = jnp.any(keep, axis=-1)
    p = jnp.where(any_keep[..., None], p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def flash_attention_segmented(q, k, v, seg_ids, scale=None, causal=False):
    """Sequence-packed batched attention: (B, nh, S, D) q/k/v with per-row
    segment ids (B, S) — tokens attend only within their own segment
    (negative ids = padding, attend to nothing). The TPU-native encoder
    packing path (reference: varlen glue in
    paddle/phi/kernels/gpu/flash_attn_kernel.cu:§0 feeding
    fused_multi_transformer's packed ERNIE pretraining batches): one
    Pallas flash invocation over the whole batch, segment mask applied
    in-kernel — no (B, H, S, S) score materialization, no per-sequence
    padding beyond the row length.
    """
    b, nh, s, d = q.shape
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    ps = _pad_to(s)
    seg = jnp.asarray(seg_ids, jnp.int32)
    if ps != s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, ps - s), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, ps - s), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, ps - s), (0, 0)))
        seg = jnp.pad(seg, ((0, 0), (0, ps - s)), constant_values=-1)
    # padded-query rows must never match padded keys: distinct ids per side
    seg_q = jnp.where(seg < 0, -1, seg)
    seg_k = jnp.where(seg < 0, -2, seg)
    qf = q.reshape(b * nh, ps, d)
    kf = k.reshape(b * nh, ps, d)
    vf = v.reshape(b * nh, ps, d)
    use_kernel = _pallas_ok(qf, kf)

    @jax.custom_vjp
    def run(qq, kk, vv, sq_ids, sk_ids):
        out, _ = run_fwd(qq, kk, vv, sq_ids, sk_ids)
        return out

    def run_fwd(qq, kk, vv, sq_ids, sk_ids):
        if use_kernel:
            bq, bk = _pick_blocks(qq.shape[1], kk.shape[1])
            out, lse = _flash_fwd_pallas(qq, kk, vv, sc, causal, bq, bk,
                                         seg_q=sq_ids, seg_k=sk_ids)
            return out, (qq, kk, vv, sq_ids, sk_ids, out, lse)
        ref = _seg_ref_batched(qq.reshape(b, nh, ps, d),
                               kk.reshape(b, nh, ps, d),
                               vv.reshape(b, nh, ps, d),
                               jnp.where(sq_ids < 0, -1, sq_ids), sc, causal)
        return ref.reshape(b * nh, ps, d), \
            (qq, kk, vv, sq_ids, sk_ids, None, None)

    def run_bwd(res, g):
        qq, kk, vv, sq_ids, sk_ids, out, lse = res
        zq = np.zeros(sq_ids.shape, jax.dtypes.float0)
        zk = np.zeros(sk_ids.shape, jax.dtypes.float0)
        if lse is not None:
            bq, bk = _pick_blocks(qq.shape[1], kk.shape[1])
            dq, dk, dv = _flash_bwd_pallas(qq, kk, vv, out, lse, g, sc,
                                           causal, bq, bk, seg_q=sq_ids,
                                           seg_k=sk_ids)
            return dq, dk, dv, zq, zk

        def ref_flat(a, bb, c):
            r = _seg_ref_batched(a.reshape(b, nh, ps, d),
                                 bb.reshape(b, nh, ps, d),
                                 c.reshape(b, nh, ps, d),
                                 jnp.where(sq_ids < 0, -1, sq_ids), sc,
                                 causal)
            return r.reshape(b * nh, ps, d)

        _, vjp = jax.vjp(ref_flat, qq, kk, vv)
        dq, dk, dv = vjp(g)
        return dq, dk, dv, zq, zk

    run.defvjp(run_fwd, run_bwd)
    out = run(qf, kf, vf, seg_q, seg_k)
    return out.reshape(b, nh, ps, d)[:, :, :s]


# ===========================================================================
# Public paddle-layout entry points
# ===========================================================================
def _sdpa_array(q, k, v, *, scale, causal):
    """(B, S, H, D) in/out; handles GQA by repeating KV heads."""
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    if hk != hq:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qt = q.transpose(0, 2, 1, 3).reshape(b * hq, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hq, k.shape[1], d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hq, v.shape[1], d)
    out = flash_attention_bhsd(qt, kt, vt, scale, causal)
    return out.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)


def _sdpa_masked(q, k, v, mask, *, scale, dropout_p, dropout_key, causal):
    """XLA path with arbitrary mask / dropout. (B, S, H, D)."""
    hq, hk = q.shape[2], k.shape[2]
    if hk != hq:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(cm, s, _NEG_INF)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            s = jnp.where(mask, s, _NEG_INF)
        else:
            s = s + mask.astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_p > 0.0:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def scaled_dot_product_attention(query: Tensor, key: Tensor, value: Tensor,
                                 attn_mask=None, dropout_p=0.0, is_causal=False,
                                 training=True, scale=None):
    """Paddle-layout (B, S, H, D) attention. Reference surface:
    python/paddle/nn/functional/flash_attention.py (SURVEY.md §2.2)."""
    d = query.shape[-1]
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    drop = dropout_p if training else 0.0
    if attn_mask is None and drop == 0.0:
        return apply(lambda a, b, c: _sdpa_array(a, b, c, scale=sc, causal=is_causal),
                     query, key, value, op_name="flash_attention")
    dkey = _random.next_key()
    if attn_mask is not None:
        return apply(
            lambda a, b, c, m: _sdpa_masked(a, b, c, m, scale=sc, dropout_p=drop,
                                            dropout_key=dkey, causal=is_causal),
            query, key, value, attn_mask if isinstance(attn_mask, Tensor) else Tensor(attn_mask),
            op_name="attention_masked")
    return apply(
        lambda a, b, c: _sdpa_masked(a, b, c, None, scale=sc, dropout_p=drop,
                                     dropout_key=dkey, causal=is_causal),
        query, key, value, op_name="attention_dropout")
