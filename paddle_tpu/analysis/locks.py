"""Rule family 2: lock discipline in ``serving/`` and ``observability/``.

These are the only packages where scheduler watchdog threads, consumer
threads and the DiagServer scrape thread genuinely run concurrently.
Discipline is inferred per class, not configured:

* a class that assigns ``self.<x> = threading.Lock()/RLock()/Condition()``
  in ``__init__`` is *lock-owning*;
* an attribute is *lock-guarded* when any method touches it inside a
  ``with self.<lock>:`` block;
* ``lock-unguarded-write`` flags mutations of guarded attributes outside
  the lock (``__init__`` excluded — the object is not shared yet; methods
  whose name ends in ``_locked`` excluded — the repo-wide convention for
  "caller holds the lock", see ``TokenStream._close_locked``);
* ``lock-blocking-call`` flags blocking operations (sleep, thread joins,
  future ``.result()``, queue ``.get()``) while the lock is held —
  including inside ``*_locked`` helpers.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import dotted
from .engine import Finding, Project

SCOPE_PREFIXES = ("paddle_tpu/serving/", "paddle_tpu/observability/")

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

#: method calls that mutate their receiver in place
_MUTATORS = {"append", "appendleft", "extend", "insert", "pop", "popleft",
             "remove", "clear", "update", "add", "discard", "setdefault",
             "rotate", "sort", "reverse"}

_BLOCKING_SLEEP = {"time.sleep", "sleep", "self._sleep"}
_BLOCKING_ATTRS = {"join", "result", "acquire"}


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Names of threading.Lock/RLock/Condition attributes assigned in
    ``__init__``."""
    out: Set[str] = set()
    for item in cls.body:
        if isinstance(item, ast.FunctionDef) and item.name == "__init__":
            for node in ast.walk(item):
                if not isinstance(node, ast.Assign):
                    continue
                if not (isinstance(node.value, ast.Call)):
                    continue
                d = dotted(node.value.func)
                if d is None or d.split(".")[-1] not in _LOCK_FACTORIES:
                    continue
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        out.add(t.attr)
    return out


def _with_lock_blocks(fn: ast.FunctionDef, locks: Set[str]
                      ) -> List[Tuple[ast.With, str]]:
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                ce = item.context_expr
                if (isinstance(ce, ast.Attribute)
                        and isinstance(ce.value, ast.Name)
                        and ce.value.id == "self" and ce.attr in locks):
                    out.append((node, ce.attr))
    return out


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _span(node: ast.AST) -> Tuple[int, int]:
    return (node.lineno, getattr(node, "end_lineno", node.lineno))


def _inside_any(node: ast.AST, blocks: List[Tuple[ast.With, str]]) -> bool:
    ln = getattr(node, "lineno", None)
    if ln is None:
        return False
    for blk, _ in blocks:
        lo, hi = _span(blk)
        if lo <= ln <= hi:
            return True
    return False


class _ClassScan:
    """Shared per-class facts for both lock rules."""

    def __init__(self, mod_rel: str, cls: ast.ClassDef):
        self.rel = mod_rel
        self.cls = cls
        self.locks = _lock_attrs(cls)
        self.methods = [n for n in cls.body
                        if isinstance(n, ast.FunctionDef)]
        # attribute names touched (read OR written) under any lock block
        self.guarded: Set[str] = set()
        # keyed by node identity, NOT name: property getter/setter pairs
        # and if/else redefinitions share a name but not lock regions
        self._blocks: Dict[int, List[Tuple[ast.With, str]]] = {}
        for m in self.methods:
            blocks = _with_lock_blocks(m, self.locks)
            self._blocks[id(m)] = blocks
            for blk, _ in blocks:
                for sub in ast.walk(blk):
                    attr = _self_attr(sub)
                    if attr is not None and attr not in self.locks:
                        self.guarded.add(attr)

    def blocks(self, m: ast.FunctionDef) -> List[Tuple[ast.With, str]]:
        return self._blocks.get(id(m), [])


def _iter_lock_classes(project: Project) -> Iterable[Tuple[str, _ClassScan]]:
    for mod in project.iter_modules(SCOPE_PREFIXES):
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                scan = _ClassScan(mod.rel, node)
                if scan.locks:
                    yield mod.rel, scan


class LockUnguardedWriteRule:
    id = "lock-unguarded-write"
    protects = ("every mutation of a lock-guarded attribute of a "
                "lock-owning class in serving//observability/ happens "
                "under 'with self._lock' (or in a *_locked helper)")
    example = ("class C:  # has self._lock and reads self._buf under it\n"
               "    def add(self, x): self._buf.append(x)  # no lock")

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for rel, scan in _iter_lock_classes(project):
            for m in scan.methods:
                if m.name in ("__init__", "__new__", "__del__") \
                        or m.name.endswith("_locked"):
                    continue
                blocks = scan.blocks(m)
                for node in ast.walk(m):
                    attr = self._mutated_attr(node)
                    if attr is None or attr not in scan.guarded:
                        continue
                    if _inside_any(node, blocks):
                        continue
                    out.append(Finding(
                        rel, node.lineno, self.id,
                        f"{scan.cls.name}.{m.name} mutates lock-guarded "
                        f"'self.{attr}' outside 'with self."
                        f"{sorted(scan.locks)[0]}' — races every reader "
                        "that takes the lock",
                        symbol=f"{scan.cls.name}.{m.name}:{attr}"))
        return out

    @staticmethod
    def _mutated_attr(node: ast.AST) -> Optional[str]:
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    return attr
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        return attr
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    return attr
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        return attr
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr in _MUTATORS):
            return _self_attr(node.func.value)
        return None


class LockBlockingCallRule:
    id = "lock-blocking-call"
    protects = ("no blocking call (sleep, Thread.join, Future.result, "
                "queue get, second acquire) while holding a serving/"
                "observability lock — stalls every thread contending it")
    example = ("with self._lock:\n"
               "    time.sleep(backoff)  # scrape thread now stalls too")

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for rel, scan in _iter_lock_classes(project):
            for m in scan.methods:
                if m.name.endswith("_locked"):
                    # caller holds the lock: the whole body is a region
                    # (which covers any with-lock blocks inside it)
                    regions = [m]
                else:
                    regions = [blk for blk, _ in scan.blocks(m)]
                seen: Set[int] = set()      # nested with-lock blocks
                for region in regions:      # must not double-report
                    for node in ast.walk(region):
                        if id(node) in seen:
                            continue
                        seen.add(id(node))
                        tok = self._blocking_token(node, scan.locks)
                        if tok is None:
                            continue
                        out.append(Finding(
                            rel, node.lineno, self.id,
                            f"blocking call {tok} while "
                            f"{scan.cls.name}.{m.name} holds the lock "
                            "— every contending thread stalls behind it",
                            symbol=f"{scan.cls.name}.{m.name}:{tok}"))
        return out

    @staticmethod
    def _blocking_token(node: ast.AST, locks: Set[str]) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        d = dotted(node.func)
        if d in _BLOCKING_SLEEP:
            return f"{d}()"
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            # Condition.wait on the lock itself is the sanctioned way to
            # block; a *second* acquire of a self-lock is a deadlock
            if node.func.attr == "acquire":
                attr = _self_attr(recv)
                return (f"self.{attr}.acquire()"
                        if attr in locks else None)
            if node.func.attr == "join":
                # str.join is everywhere (",".join, os.path.join) — only
                # receivers that look like threads/workers block
                rname = (dotted(recv) or "").lower()
                if any(t in rname for t in ("thread", "worker", "proc")):
                    return f"{d}()"
                return None
            if node.func.attr == "result":
                return f"{d or node.func.attr}()"
            if node.func.attr == "get":
                rname = (dotted(recv) or "").lower()
                if "queue" in rname:
                    return f"{d}()"
        return None


LOCK_RULES = (LockUnguardedWriteRule(), LockBlockingCallRule())
