"""Rule family 1: trace purity / recompile hazards.

The unified ragged step's O(1)-recompile guarantee (PR 7) and the
engine's byte-identical replays hold only while code that runs *under
trace* stays pure: no wall-clock reads, no Python-side randomness, no
host synchronisation, no per-shape Python branching hiding inside a
jitted body. The runtime ``RecompileDetector`` catches the symptom
(cache misses); these rules catch the cause before it ships.

Reachability comes from :mod:`.callgraph`: roots are functions handed to
``jax.jit``/``pl.pallas_call`` (or ``@partial(jax.jit, ...)``-decorated),
and edges are conservatively resolved calls, so every flagged line is in
code that demonstrably CAN run under trace.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .callgraph import FunctionInfo, dotted
from .engine import Finding, Project

_WALL_CLOCK = {"time.time", "time.monotonic", "time.perf_counter",
               "time.perf_counter_ns", "time.time_ns",
               "time.monotonic_ns", "datetime.datetime.now"}

#: call prefixes that are host/Python randomness (jax.random is fine —
#: it is keyed and traceable)
_RANDOM_PREFIXES = ("random.", "np.random.", "numpy.random.")

_HOST_SYNC_ATTRS = {"item", "tolist"}
_HOST_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray",
                    "numpy.array", "onp.asarray", "onp.array"}


def _is_stdlib_random(mi, name: str) -> bool:
    """``random`` resolves to the stdlib module in this file (not a
    local variable that happens to share the name)."""
    return mi.import_aliases.get("random") == "random"


class TracedRuleBase:
    def _iter_traced(self, project: Project) -> Iterable[FunctionInfo]:
        return project.index.traced_functions()


class TraceWallClockRule(TracedRuleBase):
    id = "trace-wall-clock"
    protects = ("traced code never reads the wall clock — a clock read "
                "baked into a compiled program is a constant, not a "
                "measurement, and breaks byte-identical replays")
    example = "def step(x): t0 = time.time()  # inside a jax.jit body"

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for fi in self._iter_traced(project):
            for node in fi.own_nodes():
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if d in _WALL_CLOCK:
                    out.append(Finding(
                        fi.module.rel, node.lineno, self.id,
                        f"{d}() inside traced function "
                        f"'{fi.qualname}' — the value freezes at trace "
                        "time; hoist it to the host caller",
                        symbol=f"{fi.qualname}:{d}"))
        return out


#: jax.random draw functions whose first argument is the PRNG key
_JAX_DRAWS = {"uniform", "normal", "categorical", "bernoulli", "randint",
              "truncated_normal", "gumbel", "exponential", "laplace",
              "choice", "permutation", "bits", "gamma", "beta", "poisson"}

#: key constructors: a draw keyed by an INLINE literal-seeded constructor
#: is a constant, not a random variable
_JAX_KEY_CTORS = {"PRNGKey", "key"}


class TraceRandomRule(TracedRuleBase):
    id = "trace-random"
    protects = ("traced code never uses Python/NumPy RNG, and every "
                "jax.random draw threads its key in from outside — a "
                "host RNG call or a literal-seeded inline PRNGKey draws "
                "once at trace time and replays the same value forever")
    example = "def kernel(x): return x * random.random()  # under jit"

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for fi in self._iter_traced(project):
            mi = project.index.by_rel[fi.module.rel]
            for node in fi.own_nodes():
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if d is None:
                    continue
                if ((d.startswith("random.") and _is_stdlib_random(mi, d))
                        or d.startswith(_RANDOM_PREFIXES[1:])):
                    msg = (f"host RNG call {d}() inside traced function "
                           f"'{fi.qualname}' — traces once, replays "
                           "forever; use jax.random with a threaded key")
                elif self._constant_keyed_jax_draw(d, node):
                    # jax.random itself is keyed and traceable — the
                    # hazard is ONLY a key built inline from a literal
                    # seed: the "draw" is then one fixed constant baked
                    # into the program, identical across rows and steps.
                    # A threaded key (a Name, parameter, fold_in chain)
                    # is the sanctioned pattern and is not flagged.
                    msg = (f"constant-keyed draw {d}() inside traced "
                           f"function '{fi.qualname}' — its inline "
                           "literal-seeded PRNGKey makes it one fixed "
                           "value baked into the program, identical "
                           "across rows and steps; thread a per-call "
                           "key in as an argument")
                else:
                    continue
                out.append(Finding(
                    fi.module.rel, node.lineno, self.id, msg,
                    symbol=f"{fi.qualname}:{d}"))
        return out

    @staticmethod
    def _constant_keyed_jax_draw(d: str, node: ast.Call) -> bool:
        parts = d.split(".")
        if parts[-1] not in _JAX_DRAWS or "random" not in parts[:-1]:
            return False
        key = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "key"), None)
        if not isinstance(key, ast.Call):
            return False
        kd = dotted(key.func)
        if kd is None or kd.split(".")[-1] not in _JAX_KEY_CTORS:
            return False
        return all(isinstance(a, ast.Constant) for a in key.args)


class TraceHostSyncRule(TracedRuleBase):
    id = "trace-host-sync"
    protects = ("traced code never forces a host sync: .item()/.tolist()"
                "/np.asarray on a traced value aborts tracing or blocks "
                "the device pipeline")
    example = "def step(x): return x[0].item()  # under jit"

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for fi in self._iter_traced(project):
            params = fi.param_names()
            for node in fi.own_nodes():
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _HOST_SYNC_ATTRS
                        and not node.args):
                    out.append(Finding(
                        fi.module.rel, node.lineno, self.id,
                        f".{node.func.attr}() inside traced function "
                        f"'{fi.qualname}' forces a host sync (or "
                        "aborts tracing)",
                        symbol=f"{fi.qualname}:{node.func.attr}"))
                elif d in _HOST_SYNC_CALLS:
                    out.append(Finding(
                        fi.module.rel, node.lineno, self.id,
                        f"{d}() inside traced function '{fi.qualname}' "
                        "materialises a traced value on the host",
                        symbol=f"{fi.qualname}:{d}"))
                elif (isinstance(node.func, ast.Name)
                      and node.func.id in ("float", "int", "bool")
                      and node.args
                      and isinstance(node.args[0], ast.Name)
                      and node.args[0].id in params):
                    out.append(Finding(
                        fi.module.rel, node.lineno, self.id,
                        f"{node.func.id}({node.args[0].id}) on a "
                        f"parameter of traced function '{fi.qualname}' "
                        "— concretises a tracer",
                        symbol=f"{fi.qualname}:{node.func.id}"
                               f"({node.args[0].id})"))
        return out


class TraceShapeBranchRule(TracedRuleBase):
    """Shape-dependent Python branching inside traced bodies: each
    distinct shape takes a different branch at trace time, so every new
    shape is a new program — the recompile cliff the ragged unified step
    removed. Deliberate shape specialisation (kernel block-size pickers,
    pallas-vs-XLA selectors whose shapes an engine cache buckets) is
    recorded in the baseline with a justification instead of staying
    invisible."""

    id = "trace-shape-branch"
    protects = ("traced bodies never branch on .shape/.ndim/len() — "
                "every distinct shape is a distinct compiled program "
                "(the recompile cliff the ragged unified step removed); "
                "deliberate specialisation is baselined, not invisible")
    example = "def step(x):\n    if x.shape[0] > 8: ...  # under jit"

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for fi in self._iter_traced(project):
            params = fi.param_names()
            for node in fi.own_nodes():
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                tok = self._shape_token(node.test, params)
                if tok is not None:
                    out.append(Finding(
                        fi.module.rel, node.lineno, self.id,
                        f"Python branch on {tok} inside traced function "
                        f"'{fi.qualname}' — one compiled program per "
                        "distinct shape; pad/bucket (or baseline the "
                        "deliberate specialisation)",
                        symbol=f"{fi.qualname}:{tok}"))
        return out

    @staticmethod
    def _shape_token(test: ast.AST, params) -> str:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Attribute) \
                    and sub.attr in ("shape", "ndim"):
                d = dotted(sub)
                return d or f"<expr>.{sub.attr}"
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id == "len" and sub.args
                    and isinstance(sub.args[0], ast.Name)
                    and sub.args[0].id in params):
                return f"len({sub.args[0].id})"
        return None


class TraceHostStateRule(TracedRuleBase):
    """Mutable host state (the FLAGS registry, os.environ) read inside a
    traced body: the value is baked into the compiled program at trace
    time, so later ``set_flags``/env changes silently do NOTHING unless
    every compile cache that guards the program keys on the same state.
    The runtime ``RecompileDetector`` cannot see this — the program never
    recompiles, it just keeps stale behaviour. Reads that ARE keyed into
    the owning compile caches get a baseline entry saying so."""

    id = "trace-host-state"
    protects = ("traced code never reads mutable host state (flag_value,"
                " os.environ) unless the owning compile caches key on "
                "it — otherwise set_flags after first trace is a silent "
                "no-op the RecompileDetector cannot even see")
    example = ("def fwd(x):\n"
               "    if flag_value('serving_a8w8_prefill'): ...  # traced")

    _ENV = {"os.environ.get", "os.getenv"}

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for fi in self._iter_traced(project):
            for node in fi.own_nodes():
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if d is None:
                    continue
                if d.split(".")[-1] == "flag_value" or d in self._ENV:
                    out.append(Finding(
                        fi.module.rel, node.lineno, self.id,
                        f"mutable host state read {d}() inside traced "
                        f"function '{fi.qualname}' — baked in at trace "
                        "time; key the compile cache on it or hoist it "
                        "to the host caller",
                        symbol=f"{fi.qualname}:{d}"))
        return out


PURITY_RULES = (TraceWallClockRule(), TraceRandomRule(),
                TraceHostSyncRule(), TraceShapeBranchRule(),
                TraceHostStateRule())
