"""CLI: ``python -m paddle_tpu.analysis [--format json|text|sarif] ...``.

Exit code 0 when the tree is clean against the baseline; 1 when any
unbaselined finding or stale baseline entry exists. ``--write-baseline``
regenerates the checked-in baseline deterministically (sorted by
fingerprint; existing justifications are preserved).

``--changed-only [REF]`` scopes a run to the files ``git diff
--name-only REF`` names plus their reverse-dependency closure (computed
from a lightweight import scan, so a pre-commit run parses dozens of
files instead of the whole tree). Scoped semantics: findings outside the
closure are dropped, whole-tree-evidence findings (``unused:*`` catalog
rows) are skipped, and the stale-baseline check is disabled — the full
run remains the PR gate; this mode is the fast inner loop.
"""

from __future__ import annotations

import argparse
import re
import subprocess
import sys
from pathlib import Path
from typing import Optional, Sequence, Set

from . import BASELINE_PATH, REPO_ROOT, default_rules, run_repo
from .engine import Baseline, Project, Report

_ABS_IMPORT_RE = re.compile(r"^\s*(?:from|import)\s+([A-Za-z_][\w.]*)",
                            re.MULTILINE)
_FROM_IMPORT_RE = re.compile(r"^\s*from\s+([A-Za-z_][\w.]*)\s+import"
                             r"\s+([^\n#]+)", re.MULTILINE)
_REL_IMPORT_RE = re.compile(r"^\s*from\s+(\.+)([\w.]*)\s+import\s+([^\n#]+)",
                            re.MULTILINE)


def _imported_names(names: str):
    """Identifiers from an import-name list (``a, b as c, (d,``)."""
    for name in names.split(","):
        name = name.strip().strip("()").split(" ")[0].strip()
        if name.isidentifier():
            yield name

#: files some rules need regardless of the diff (contract tables)
_ALWAYS_PARSE = ("paddle_tpu/observability/catalog.py",
                 "paddle_tpu/serving/metrics.py")


def _modname(rel: str) -> str:
    mod = rel[:-3] if rel.endswith(".py") else rel
    if mod.endswith("/__init__"):
        mod = mod[:-len("/__init__")]
    return mod.replace("/", ".")


def changed_closure(root: Path, roots: Sequence[str],
                    ref: str) -> Optional[Set[str]]:
    """Repo-relative paths of files changed vs ``ref`` plus every file
    that (transitively) imports one of them. Returns None when git is
    unusable (caller falls back to a full run). The import scan is a
    line regex, not a parse — the whole point is a sub-second
    pre-commit loop."""
    try:
        # --relative keys the paths to cwd=root (ls-files already is),
        # not the git toplevel — they must match mod_of when --root sits
        # below the toplevel
        out = subprocess.run(
            ["git", "diff", "--name-only", "--relative", ref, "--"],
            cwd=root, capture_output=True, text=True, check=True).stdout
        # brand-new files are the primary pre-commit target and never
        # appear in a diff against REF until staged
        out += subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, check=True).stdout
    except (subprocess.CalledProcessError, FileNotFoundError,
            OSError) as e:
        print(f"--changed-only: git diff vs {ref!r} failed ({e}); "
              "falling back to a full run", file=sys.stderr)
        return None
    changed = {line.strip() for line in out.splitlines()
               if line.strip().endswith(".py")}
    imports: dict = {}
    mod_of: dict = {}
    for sub in roots:
        base = root / sub
        if not base.exists():
            continue
        for p in base.rglob("*.py"):
            rel = p.relative_to(root).as_posix()
            mod_of[rel] = _modname(rel)
            try:
                text = p.read_text()
            except (OSError, UnicodeDecodeError):
                text = ""
            imps = set(_ABS_IMPORT_RE.findall(text))
            for base_mod, names in _FROM_IMPORT_RE.findall(text):
                # ``from paddle_tpu.core import offload``: the
                # dependency may be the SUBMODULE — record the dotted
                # candidates too (name-not-a-module extras match no
                # file and are harmless)
                for name in _imported_names(names):
                    imps.add(base_mod + "." + name)
            # one leading dot = the containing package: for a plain
            # module that drops the module's own name, but a package
            # __init__'s modname IS the package already (_modname
            # stripped the /__init__), so nothing is dropped
            parts = _modname(rel).split(".")
            pkg = parts if rel.endswith("/__init__.py") else parts[:-1]
            for dots, tail, names in _REL_IMPORT_RE.findall(text):
                base_parts = pkg[:len(pkg) - (len(dots) - 1)]
                base_mod = ".".join(base_parts + ([tail] if tail else []))
                imps.add(base_mod)
                if not tail:
                    # ``from . import format as fmt``: the dependency is
                    # the submodule itself, which the package name alone
                    # misses (``pkg.format`` changing must pull this
                    # file into the closure)
                    for name in _imported_names(names):
                        imps.add(base_mod + "." + name)
            imports[rel] = imps
    closure = {rel for rel in changed if rel in mod_of}
    queue = list(closure)
    while queue:
        rel = queue.pop()
        mod = mod_of[rel]
        for other, imps in imports.items():
            if other in closure:
                continue
            if any(i == mod or i.startswith(mod + ".") for i in imps):
                closure.add(other)
                queue.append(other)
    return closure


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="tpu-lint: AST + dataflow invariant analyzer")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--root", type=Path, default=REPO_ROOT,
                    help="repo root to analyze (default: this checkout)")
    ap.add_argument("--baseline", type=Path, default=BASELINE_PATH,
                    help="baseline file (default: analysis/baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, grandfathered or not")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--changed-only", nargs="?", const="HEAD",
                    default=None, metavar="REF",
                    help="scope to files changed vs REF (default HEAD) "
                         "plus their reverse-dependency closure — the "
                         "sub-second pre-commit mode; the full run "
                         "stays the PR gate")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline to cover current findings "
                         "(sorted, deterministic; keeps justifications)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id:22s} {r.protects}")
        return 0
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]

    baseline_path = None if args.no_baseline else args.baseline
    roots = ("paddle_tpu", "tests", "benchmarks")

    only: Optional[Set[str]] = None
    scope: Set[str] = set()
    if args.changed_only is not None:
        closure = changed_closure(args.root, roots, args.changed_only)
        if closure is not None:
            scope = set(closure)            # findings reported from here
            only = set(closure)             # parsed: scope + contract tables
            for rel in _ALWAYS_PARSE:
                if (args.root / rel).exists():
                    only.add(rel)
            print(f"--changed-only {args.changed_only}: "
                  f"{len(closure)} file(s) in the dependency closure",
                  file=sys.stderr)

    if only is None:
        report = run_repo(root=args.root, rules=rules,
                          baseline_path=baseline_path)
    else:
        project = Project(args.root, roots=roots, only=only)
        baseline = (Baseline.load(baseline_path)
                    if baseline_path is not None else Baseline())
        from .engine import AnalysisEngine
        full = AnalysisEngine(rules, baseline).run(project)
        kept = [f for f in full.findings
                if f.file in scope
                and not f.symbol.startswith("unused:")]
        # scoped run: no stale-baseline verdict (absence proves nothing
        # when most of the tree was never parsed)
        report = Report(kept, baseline, full.elapsed_s, full.files,
                        ran_rules=set())

    if args.write_baseline:
        if only is not None:
            print("--write-baseline is incompatible with --changed-only "
                  "(a scoped run must not rewrite whole-tree "
                  "grandfathering)", file=sys.stderr)
            return 2
        old = Baseline.load(args.baseline)
        ran = {r.id for r in rules}
        # keep entries owned by rules that did NOT run (a --rules subset
        # regeneration must not delete the other rules' grandfathered
        # findings and their justifications), refresh the rest
        entries = {}
        for fp, why in old.entries.items():
            parts = fp.split(":")
            if (parts[1] if len(parts) > 1 else "") not in ran:
                entries[fp] = why
        entries.update({f.fingerprint: old.entries.get(f.fingerprint, "")
                        for f in report.findings})
        Baseline(entries).write(args.baseline)
        print(f"baseline written: {args.baseline} "
              f"({len(entries)} entries)")
        return 0

    if args.format == "json":
        print(report.to_json())
    elif args.format == "sarif":
        print(report.to_sarif(rules))
    else:
        print(report.to_text())
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
