"""CLI: ``python -m paddle_tpu.analysis [--format json|text] ...``.

Exit code 0 when the tree is clean against the baseline; 1 when any
unbaselined finding or stale baseline entry exists. ``--write-baseline``
regenerates the checked-in baseline deterministically (sorted by
fingerprint; existing justifications are preserved)."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from . import BASELINE_PATH, REPO_ROOT, default_rules, run_repo
from .engine import Baseline


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="tpu-lint: AST-based invariant analyzer")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--root", type=Path, default=REPO_ROOT,
                    help="repo root to analyze (default: this checkout)")
    ap.add_argument("--baseline", type=Path, default=BASELINE_PATH,
                    help="baseline file (default: analysis/baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, grandfathered or not")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline to cover current findings "
                         "(sorted, deterministic; keeps justifications)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id:22s} {r.protects}")
        return 0
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]

    baseline_path = None if args.no_baseline else args.baseline
    report = run_repo(root=args.root, rules=rules,
                      baseline_path=baseline_path)

    if args.write_baseline:
        old = Baseline.load(args.baseline)
        ran = {r.id for r in rules}
        # keep entries owned by rules that did NOT run (a --rules subset
        # regeneration must not delete the other rules' grandfathered
        # findings and their justifications), refresh the rest
        entries = {}
        for fp, why in old.entries.items():
            parts = fp.split(":")
            if (parts[1] if len(parts) > 1 else "") not in ran:
                entries[fp] = why
        entries.update({f.fingerprint: old.entries.get(f.fingerprint, "")
                        for f in report.findings})
        Baseline(entries).write(args.baseline)
        print(f"baseline written: {args.baseline} "
              f"({len(entries)} entries)")
        return 0

    print(report.to_json() if args.format == "json"
          else report.to_text())
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
