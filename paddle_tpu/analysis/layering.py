"""Rule family 4: layering / encapsulation contracts.

Declarative replacements for the five regex lints that used to be
scattered across ``tests/`` (each with its own ``_offenders()`` copy),
plus a module-dependency contract the regexes never could express. Every
contract is data at the top of this file — adding one is adding a row.

Ported contracts (rule id — what it subsumes):

* ``layer-http``        — tests/test_observability_lint.py http.server
* ``layer-socket``      — tests/test_observability_lint.py raw sockets
* ``layer-wall-clock``  — tests/test_observability_lint.py slo/goodput
* ``private-replica``   — tests/test_observability_lint.py ReplicaHandle
* ``private-kvcache``   — tests/test_kvcache.py ``._free``/``._pages_for``
* ``layer-shard-map``   — tests/test_serving.py direct jax shard_map
* ``layer-atomic-write``— tests/test_resilience.py unstaged checkpoint IO
* ``layer-prom-format`` — tests/test_observability.py ad-hoc formatters

New:

* ``layer-deps`` — module-level import direction between subsystems
  (e.g. resilience must never import the serving stack — PR 2 moved
  ``Histogram`` into core precisely to keep that edge out).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .callgraph import dotted
from .engine import Finding, Project

PKG = "paddle_tpu/"
ALL_ROOTS = ("paddle_tpu/", "tests/", "benchmarks/")


def _module_level_stmts(tree: ast.Module) -> Iterable[ast.stmt]:
    """Top-level statements, descending through top-level try/if bodies
    (conditional imports) but never into defs/classes."""
    stack: List[ast.stmt] = list(tree.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.Try, ast.If)):
            for part in ("body", "orelse", "finalbody", "handlers"):
                for sub in getattr(node, part, []):
                    if isinstance(sub, ast.ExceptHandler):
                        stack.extend(sub.body)
                    elif isinstance(sub, ast.stmt):
                        stack.append(sub)


def _abs_import_targets(mod_rel: str, node: ast.stmt) -> List[str]:
    """Absolute module names a module-level import statement binds."""
    out: List[str] = []
    if isinstance(node, ast.Import):
        out.extend(a.name for a in node.names)
    elif isinstance(node, ast.ImportFrom):
        if node.level:
            pkg_parts = mod_rel[:-3].split("/")[:-1]
            base = pkg_parts[:len(pkg_parts) - (node.level - 1)]
            src = ".".join(base + ([node.module] if node.module else []))
        else:
            src = node.module or ""
        out.append(src)
    return out


class ImportConfinementRule:
    """Generic "module X may only be imported inside these files"."""

    def __init__(self, rule_id: str, modules: Sequence[str],
                 allowed: Sequence[str], protects: str, example: str,
                 hint: str):
        self.id = rule_id
        self.modules = tuple(modules)       # top-level module names
        self.allowed = set(allowed)         # repo-relative files
        self.protects = protects
        self.example = example
        self.hint = hint

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for mod in project.iter_modules((PKG,)):
            if mod.rel in self.allowed:
                continue
            for node in mod.nodes_of(ast.Import, ast.ImportFrom):
                targets = _abs_import_targets(mod.rel, node)
                for t in targets:
                    top = t.split(".")[0]
                    if top in self.modules:
                        out.append(Finding(
                            mod.rel, node.lineno, self.id,
                            f"import of {t!r} outside "
                            f"{sorted(self.allowed)}; {self.hint}",
                            symbol=f"import:{top}"))
        return out


class WallClockFreeRule:
    """``time.time`` never referenced in the deterministic SLO/goodput/
    sensor-plane math (injected step-driven clocks only)."""

    id = "layer-wall-clock"
    protects = ("observability/slo.py + goodput.py + the sensor plane "
                "(timeseries.py, anomaly.py, signals.py) never read the "
                "wall clock — breach/recover transitions, goodput "
                "splits and anomaly events stay byte-reproducible in "
                "chaos replays")
    example = "self._clock = time.time  # in slo.py"
    FILES = ("paddle_tpu/observability/slo.py",
             "paddle_tpu/observability/goodput.py",
             "paddle_tpu/observability/timeseries.py",
             "paddle_tpu/observability/anomaly.py",
             "paddle_tpu/observability/signals.py")

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for rel in self.FILES:
            mod = project.module(rel)
            if mod is None:
                out.append(Finding(rel, 1, self.id,
                                   "expected module missing",
                                   symbol="missing"))
                continue
            for node in mod.nodes_of(ast.Attribute):
                if dotted(node) == "time.time":
                    out.append(Finding(
                        rel, node.lineno, self.id,
                        "wall-clock reference time.time in "
                        "deterministic SLO/goodput math — use the "
                        "injected step-driven clock",
                        symbol="time.time"))
        return out


class PrivateAccessRule:
    """Attribute access to named privates confined to owner packages."""

    def __init__(self, rule_id: str, attrs: Sequence[str],
                 allowed_prefixes: Sequence[str], protects: str,
                 example: str, hint: str,
                 roots: Sequence[str] = ALL_ROOTS):
        self.id = rule_id
        self.attrs = set(attrs)
        self.allowed_prefixes = tuple(allowed_prefixes)
        self.protects = protects
        self.example = example
        self.hint = hint
        self.roots = tuple(roots)

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for mod in project.iter_modules(self.roots):
            if mod.rel.startswith(self.allowed_prefixes):
                continue
            for node in mod.nodes_of(ast.Attribute):
                if node.attr in self.attrs \
                        and not (isinstance(node.value, ast.Name)
                                 and node.value.id in ("self", "cls")):
                    # a class touching its OWN private of the same name
                    # is not an encapsulation breach
                    out.append(Finding(
                        mod.rel, node.lineno, self.id,
                        f"access to private '.{node.attr}' outside "
                        f"{list(self.allowed_prefixes)}; {self.hint}",
                        symbol=f"attr:{node.attr}"))
        return out


class ShardMapRule:
    id = "layer-shard-map"
    protects = ("core/compat.py stays the single version-tolerant "
                "shard_map source (the seed broke on a bare jax import "
                "path; the resolver is the fix)")
    example = "from jax.experimental.shard_map import shard_map"
    ALLOWED = "paddle_tpu/core/compat.py"

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for mod in project.iter_modules(ALL_ROOTS):
            if mod.rel == self.ALLOWED:
                continue
            for node in mod.nodes_of(ast.ImportFrom, ast.Attribute):
                bad: Optional[str] = None
                if isinstance(node, ast.ImportFrom) and not node.level \
                        and (node.module or "").startswith("jax") \
                        and any(a.name == "shard_map"
                                for a in node.names):
                    bad = f"from {node.module} import shard_map"
                elif isinstance(node, ast.Attribute):
                    d = dotted(node)
                    if d in ("jax.shard_map",
                             "jax.experimental.shard_map.shard_map"):
                        bad = d
                if bad is not None:
                    out.append(Finding(
                        mod.rel, node.lineno, self.id,
                        f"direct jax shard_map use ({bad}); import it "
                        "from paddle_tpu.core.compat instead",
                        symbol="shard_map"))
        return out


class AtomicWriteRule:
    id = "layer-atomic-write"
    protects = ("every write under distributed/checkpoint/ goes through "
                "utils.atomic_write (stage + fsync + CRC32 + rename) — "
                "a crash can never leave a torn checkpoint file")
    example = 'open(path, "wb")  # in distributed/checkpoint/metadata.py'
    SCOPE = "paddle_tpu/distributed/checkpoint/"
    ALLOWED = "paddle_tpu/distributed/checkpoint/utils.py"
    _WRITE_MODE = re.compile(r"^(?:[wax]b?\+?|r\+b?)$")

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for mod in project.iter_modules((self.SCOPE,)):
            if mod.rel == self.ALLOWED:
                continue
            for node in mod.nodes_of(ast.Call):
                # bare open() AND attribute writers (gzip.open, io.open,
                # os.fdopen) — the regex this rule replaced caught all of
                # them, and a torn gzip'd checkpoint is just as torn
                is_open = (isinstance(node.func, ast.Name)
                           and node.func.id == "open") or \
                          (isinstance(node.func, ast.Attribute)
                           and node.func.attr in ("open", "fdopen"))
                if not is_open:
                    continue
                mode = None
                if len(node.args) >= 2:
                    mode = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "mode":
                        mode = kw.value
                if (isinstance(mode, ast.Constant)
                        and isinstance(mode.value, str)
                        and self._WRITE_MODE.match(mode.value)):
                    out.append(Finding(
                        mod.rel, node.lineno, self.id,
                        f"unstaged write-mode open(..., "
                        f"{mode.value!r}) in the checkpoint package; "
                        "use utils.atomic_write",
                        symbol=f"open:{mode.value}"))
        return out


class PromFormatRule:
    id = "layer-prom-format"
    protects = ("Prometheus exposition syntax is assembled ONLY in "
                "observability/format.py — one formatter means one "
                "valid /metrics document")
    example = "lines.append(f'{name}_bucket{{le=\"{b}\"}} {n}')"
    ALLOWED = ("paddle_tpu/observability/format.py",
               "paddle_tpu/analysis/")       # the contract's own table
    _TOKENS = ('_bucket{', '{le="', "# TYPE ", 'quantile="')

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for mod in project.iter_modules((PKG,)):
            if mod.rel.startswith(self.ALLOWED) \
                    or mod.rel in self.ALLOWED:
                continue
            for node in mod.nodes_of(ast.Constant):
                if not isinstance(node.value, str):
                    continue
                hit = next((t for t in self._TOKENS
                            if t in node.value), None)
                if hit is not None:
                    out.append(Finding(
                        mod.rel, node.lineno, self.id,
                        f"ad-hoc Prometheus formatting ({hit!r} in a "
                        "string literal); assemble exposition lines via "
                        "paddle_tpu.observability.format",
                        symbol=f"token:{hit}"))
        return out


class LayerDepsRule:
    """Module-level import direction between subsystems. Lazy (function
    -scope) imports are allowed — they are the sanctioned way to break
    cycles — so only top-level statements are checked."""

    id = "layer-deps"
    protects = ("subsystem import edges point downward only: core/"
                "observability are base layers; kvcache sits under the "
                "engine; resilience never pulls in the serving stack")
    example = "from ..serving.metrics import ServingMetrics  # in resilience/"

    #: package prefix -> forbidden paddle_tpu sub-packages
    CONTRACTS: Dict[str, Tuple[str, ...]] = {
        "paddle_tpu/core/": ("serving", "resilience", "inference",
                             "kvcache", "models"),
        "paddle_tpu/observability/": ("serving", "resilience",
                                      "inference", "kvcache", "models",
                                      "distributed"),
        "paddle_tpu/kvcache/": ("serving", "resilience", "inference",
                                "models", "distributed"),
        "paddle_tpu/resilience/": ("serving",),
        "paddle_tpu/analysis/": ("serving", "resilience", "inference",
                                 "kvcache", "models", "distributed",
                                 "observability", "core", "ops"),
    }

    #: file -> sub-packages it may not import AT ANY SCOPE (lazy
    #: function-scope imports included). The memory ledger is FED by the
    #: serving stack and never pulls from it — even a lazy import would
    #: let accounting reach back into the layers it measures.
    STRICT_CONTRACTS: Dict[str, Tuple[str, ...]] = {
        "paddle_tpu/observability/memory.py": (
            "serving", "inference", "kvcache", "models", "resilience",
            "distributed"),
        # the fusion pass consumes SYMBOLS (the hot-chain artifact +
        # ProjectIndex) and injected callables, never the serving stack
        # it optimizes — region installation is duck-typed, and the
        # decode-tail builders receive the model step as an argument
        "paddle_tpu/jit/fusion.py": (
            "serving", "inference", "kvcache", "models"),
    }

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for mod in project.iter_modules((PKG,)):
            strict = self.STRICT_CONTRACTS.get(mod.rel)
            if strict is not None:
                for node in mod.nodes_of(ast.Import, ast.ImportFrom):
                    for t in _abs_import_targets(mod.rel, node):
                        parts = t.split(".")
                        if parts[0] != "paddle_tpu" or len(parts) < 2:
                            continue
                        if parts[1] in strict:
                            out.append(Finding(
                                mod.rel, node.lineno, self.id,
                                f"import of paddle_tpu.{parts[1]} from "
                                f"{mod.rel} violates its STRICT layering "
                                "contract (the ledger is fed, never "
                                "pulls — lazy imports are banned here "
                                "too)", symbol=f"strict:{parts[1]}"))
            forbidden: Optional[Tuple[str, ...]] = None
            for prefix, banned in self.CONTRACTS.items():
                if mod.rel.startswith(prefix):
                    forbidden = banned
                    break
            if forbidden is None:
                continue
            for node in _module_level_stmts(mod.tree):
                if not isinstance(node, (ast.Import, ast.ImportFrom)):
                    continue
                for t in _abs_import_targets(mod.rel, node):
                    parts = t.split(".")
                    if parts[0] != "paddle_tpu" or len(parts) < 2:
                        continue
                    if parts[1] in forbidden:
                        out.append(Finding(
                            mod.rel, node.lineno, self.id,
                            f"module-level import of paddle_tpu."
                            f"{parts[1]} from {mod.rel} violates the "
                            "layering contract (lazy function-scope "
                            "imports are the sanctioned escape hatch)",
                            symbol=f"dep:{parts[1]}"))
        return out


LAYERING_RULES = (
    ImportConfinementRule(
        "layer-http", ("http",),
        ("paddle_tpu/observability/server.py",),
        protects=("http.server lives ONLY in observability/server.py — "
                  "the DiagServer is the ONE debug endpoint"),
        example="import http.server  # in serving/router.py",
        hint=("register a /statusz provider on the DiagServer instead "
              "of opening another listener")),
    ImportConfinementRule(
        "layer-socket", ("socket",),
        ("paddle_tpu/observability/server.py",
         "paddle_tpu/distributed/launch/context.py",
         "paddle_tpu/distributed/launch/master.py",
         "paddle_tpu/distributed/store.py"),
        protects=("raw sockets only in the DiagServer and the "
                  "grandfathered distributed rendezvous modules"),
        example="import socket  # in observability/flight.py",
        hint=("new listeners belong in observability/server.py or the "
              "sanctioned rendezvous modules")),
    WallClockFreeRule(),
    PrivateAccessRule(
        "private-replica", ("_scheduler", "_fault"),
        ("paddle_tpu/serving/",),
        protects=("nothing outside serving/ reaches into ReplicaHandle "
                  "privates — the breaker/drain state machine owns them"),
        example="router.replicas[0]._scheduler.step(params)  # in a bench",
        hint=("route through the public replica surface (submit/cancel/"
              "step/statusz/health) or the FleetRouter")),
    PrivateAccessRule(
        "private-kvcache", ("_free", "_pages_for"),
        ("paddle_tpu/ops/", "paddle_tpu/kvcache/"),
        protects=("pool internals stay behind the ops/kvcache boundary "
                  "— refcount/cached states make direct free-list "
                  "surgery unsound"),
        example="mgr._free.append(page)  # in serving/scheduler.py",
        hint="use pages_for()/usable_pages or paddle_tpu.kvcache"),
    PrivateAccessRule(
        "private-engine", ("_queue", "_slot_rid", "_pend", "_live"),
        ("paddle_tpu/inference/",),
        protects=("runtime code never reaches into the decoding "
                  "engine's slot/FIFO internals — admission math goes "
                  "through num_queued/num_free_slots (the engine's own "
                  "white-box tests are exempt)"),
        example="self.engine._queue  # in serving/scheduler.py",
        hint="use engine.num_queued / num_free_slots / submit()",
        roots=("paddle_tpu/", "benchmarks/")),
    ShardMapRule(),
    AtomicWriteRule(),
    PromFormatRule(),
    LayerDepsRule(),
)
