"""tpu-lint: AST-based invariant analyzer for the paddle_tpu tree.

One parse per file, pluggable visitor rules, line suppressions and a
checked-in baseline (see :mod:`.engine`). Seven rule families protect
the stack's hard-won guarantees:

* **trace purity / recompile hazards** (:mod:`.purity`) — a call graph
  from every ``jax.jit``/``pallas_call`` root; wall-clock reads, host
  RNG, host syncs and shape-branching flagged inside traced code;
* **lock discipline** (:mod:`.locks`) — unguarded mutation of lock-
  guarded state and blocking calls under a held lock in ``serving/`` and
  ``observability/``;
* **metrics/events contracts** (:mod:`.contracts`) — every metric name,
  label tuple and event kind checked against
  ``observability/catalog.py``, both directions;
* **layering/encapsulation** (:mod:`.layering`) — declarative import and
  private-access contracts (subsuming the five retired regex lints) plus
  subsystem dependency direction;
* **resource flow / dtype flow / cache-key completeness**
  (:mod:`.dataflow`, tpu-lint v2) — interprocedural dataflow over a
  per-function CFG (exception edges included): paged acquisitions must
  release on every path, traced bf16/int8 chains must not silently
  promote, and every trace-time flag read must be derivable from the
  guarding compile-cache key.

CLI::

    python -m paddle_tpu.analysis [--format text|json|sarif]
                                  [--rules a,b] [--changed-only [REF]]
                                  [--write-baseline]

exits 1 on any unbaselined finding or stale baseline entry. Tests use
:func:`cached_report` so the whole suite pays for ONE analysis run.
"""

from __future__ import annotations

import functools
from pathlib import Path
from typing import Optional, Sequence

from .contracts import CONTRACT_RULES
from .dataflow import DATAFLOW_RULES
from .engine import (AnalysisEngine, Baseline, Finding, Project,  # noqa: F401
                     Report, SourceModule)
from .layering import LAYERING_RULES
from .locks import LOCK_RULES
from .purity import PURITY_RULES

#: repo root (…/paddle_tpu/analysis/__init__.py -> two levels up)
REPO_ROOT = Path(__file__).resolve().parents[2]
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.txt"


def default_rules():
    return (*PURITY_RULES, *LOCK_RULES, *CONTRACT_RULES, *LAYERING_RULES,
            *DATAFLOW_RULES)


def run_repo(root: Optional[Path] = None,
             rules: Optional[Sequence] = None,
             baseline_path: Optional[Path] = BASELINE_PATH,
             roots: Optional[Sequence[str]] = None) -> Report:
    """One full analysis run over the repo (or any compatible tree)."""
    project = Project(root or REPO_ROOT,
                      roots=roots or ("paddle_tpu", "tests", "benchmarks"))
    baseline = (Baseline.load(baseline_path)
                if baseline_path is not None else Baseline())
    engine = AnalysisEngine(rules if rules is not None else default_rules(),
                            baseline)
    return engine.run(project)


@functools.lru_cache(maxsize=1)
def cached_report() -> Report:
    """The shared analysis run for the test suite: every ported lint
    test asserts over this ONE report instead of re-walking the tree."""
    return run_repo()
