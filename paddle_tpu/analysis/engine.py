"""tpu-lint core: parse-once AST engine, findings, suppressions, baseline.

The engine is deliberately tiny:

* every file under the configured roots is read and ``ast.parse``-d
  exactly ONCE (:class:`SourceModule`); rules share the trees (and the
  lazily built project-wide index, see :mod:`.callgraph`) instead of
  re-walking the filesystem per rule the way the five retired regex
  lints did;
* a rule is any object with an ``id``, a one-line ``protects`` string, an
  ``example`` violation (both feed the README catalog and the CLI) and a
  ``run(project) -> Iterable[Finding]``;
* ``# tpu-lint: disable=<rule>[,<rule>...]`` on the finding's line (or on
  a comment-only line directly above it) silences exactly those rules on
  exactly that line;
* a checked-in baseline grandfathers known findings by *fingerprint*
  (line-number-free, so unrelated edits don't invalidate it); a baseline
  entry whose finding disappeared is STALE and fails the run, keeping the
  file honest.

Nothing here imports jax/numpy — the analyzer stays importable and fast
in any environment that can parse the source.
"""

from __future__ import annotations

import ast
import json
import re
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_SUPPRESS_RE = re.compile(r"#\s*tpu-lint:\s*disable=([A-Za-z0-9_\-, ]+)")

#: sub-directories of the repo root the analyzer looks at by default
DEFAULT_ROOTS: Tuple[str, ...] = ("paddle_tpu", "tests", "benchmarks")


@dataclass(frozen=True)
class Finding:
    """One rule violation.

    ``symbol`` is the rule-chosen stable token (function qualname,
    attribute, metric name, ...) that makes the fingerprint survive line
    drift; it must not contain line numbers."""

    file: str           # repo-relative posix path
    line: int
    rule: str
    message: str
    symbol: str = ""

    @property
    def fingerprint(self) -> str:
        return f"{self.file}:{self.rule}:{self.symbol or self.message}"

    def text(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"

    def sort_key(self):
        return (self.file, self.line, self.rule, self.message)


class SourceModule:
    """One parsed file: path, source, AST, per-line suppressions."""

    def __init__(self, path: Path, rel: str, text: str):
        self.path = path
        self.rel = rel                      # posix, relative to repo root
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        self._nodes = None
        self._by_type = None
        # line -> set of rule ids disabled on that line
        self.suppressions: Dict[int, set] = {}
        for i, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self.suppressions[i] = rules

    @property
    def nodes(self):
        """Flat list of every AST node, computed once — rules iterate
        this instead of re-walking the tree (the walk, not the parse,
        dominated rule time)."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    def nodes_of(self, *types):
        """Nodes of the given AST types, from a per-type index built on
        first use — most rules only care about one node kind, and nine
        full-tree iterations per module blew the 5 s tier-1 budget."""
        if self._by_type is None:
            by_type: Dict[type, list] = {}
            for n in self.nodes:
                by_type.setdefault(type(n), []).append(n)
            self._by_type = by_type
        if len(types) == 1:
            return self._by_type.get(types[0], ())
        out = []
        for t in types:
            out.extend(self._by_type.get(t, ()))
        return out

    def suppressed(self, line: int, rule: str) -> bool:
        """True when ``rule`` is disabled on ``line`` — by a trailing
        comment on the line itself, or by a comment-only line directly
        above it (for statements whose line is already full)."""
        rules = self.suppressions.get(line)
        if rules and (rule in rules or "all" in rules):
            return True
        prev = self.suppressions.get(line - 1)
        if prev and (rule in prev or "all" in prev):
            text = self.lines[line - 2].strip() if line >= 2 else ""
            if text.startswith("#"):
                return True
        return False


class Project:
    """All modules under ``root``'s configured sub-roots, parsed once.

    ``only`` (an optional set of repo-relative posix paths) restricts
    parsing to those files — the ``--changed-only`` pre-commit mode,
    where the caller has already computed the reverse-dependency
    closure of a git diff."""

    def __init__(self, root: Path, roots: Sequence[str] = DEFAULT_ROOTS,
                 only: Optional[set] = None):
        self.root = Path(root)
        self.roots = tuple(roots)
        self.only = only
        self.modules: List[SourceModule] = []
        self.parse_errors: List[Finding] = []
        self.parse_count = 0
        for sub in self.roots:
            base = self.root / sub
            if not base.exists():
                continue
            for p in sorted(base.rglob("*.py")):
                rel = p.relative_to(self.root).as_posix()
                if only is not None and rel not in only:
                    continue
                try:
                    mod = SourceModule(p, rel, p.read_text())
                except SyntaxError as e:
                    self.parse_errors.append(Finding(
                        rel, e.lineno or 1, "parse-error",
                        f"syntax error: {e.msg}", symbol="syntax"))
                    continue
                except (UnicodeDecodeError, OSError, ValueError) as e:
                    # one undecodable/unreadable file must not kill the
                    # whole run — surface it as a finding like a syntax
                    # error
                    self.parse_errors.append(Finding(
                        rel, 1, "parse-error",
                        f"unreadable file: {e}", symbol="unreadable"))
                    continue
                self.parse_count += 1
                self.modules.append(mod)
        self._by_rel = {m.rel: m for m in self.modules}
        self._index = None

    def module(self, rel: str) -> Optional[SourceModule]:
        return self._by_rel.get(rel)

    def iter_modules(self, prefixes: Sequence[str] = ("",)
                     ) -> Iterable[SourceModule]:
        for m in self.modules:
            if any(m.rel.startswith(p) for p in prefixes):
                yield m

    @property
    def index(self):
        """Lazily built :class:`~paddle_tpu.analysis.callgraph.
        ProjectIndex` (imports, defs, traced reachability)."""
        if self._index is None:
            from .callgraph import ProjectIndex
            self._index = ProjectIndex(self)
        return self._index


class Baseline:
    """Grandfathered findings: ``fingerprint | justification`` lines."""

    def __init__(self, entries: Optional[Dict[str, str]] = None):
        self.entries: Dict[str, str] = dict(entries or {})

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        entries: Dict[str, str] = {}
        if Path(path).exists():
            for raw in Path(path).read_text().splitlines():
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                fp, _, why = line.partition(" | ")
                entries[fp.strip()] = why.strip()
        return cls(entries)

    def dumps(self) -> str:
        """Deterministic serialisation: sorted by fingerprint, one entry
        per line — re-writing an unchanged baseline is byte-identical."""
        lines = ["# tpu-lint baseline: grandfathered findings.",
                 "# format: <fingerprint> | <one-line justification>",
                 "# regenerate with: python -m paddle_tpu.analysis"
                 " --write-baseline", ""]
        for fp in sorted(self.entries):
            why = self.entries[fp] or "grandfathered"
            lines.append(f"{fp} | {why}")
        return "\n".join(lines) + "\n"

    def write(self, path: Path) -> None:
        Path(path).write_text(self.dumps())


class Report:
    """Outcome of one engine run: every finding, the unbaselined subset,
    and stale baseline entries.

    Staleness is judged only for baseline entries whose rule actually
    RAN (fingerprints are ``file:rule:symbol``; paths/rule ids contain
    no colons): a ``--rules`` subset run must not condemn every other
    rule's grandfathered findings as stale."""

    def __init__(self, findings: List[Finding], baseline: Baseline,
                 elapsed_s: float, files: int,
                 ran_rules: Optional[set] = None):
        self.findings = sorted(findings, key=Finding.sort_key)
        self.baseline = baseline
        self.elapsed_s = elapsed_s
        self.files = files
        found = {f.fingerprint for f in self.findings}
        self.new = [f for f in self.findings
                    if f.fingerprint not in baseline.entries]

        def _rule_of(fp: str) -> str:
            parts = fp.split(":")
            return parts[1] if len(parts) > 1 else ""

        self.stale = sorted(
            fp for fp in baseline.entries
            if fp not in found
            and (ran_rules is None or _rule_of(fp) in ran_rules))

    def new_for_rule(self, rule: str) -> List[Finding]:
        return [f for f in self.new if f.rule == rule]

    def for_rule(self, rule: str) -> List[Finding]:
        return [f for f in self.findings if f.rule == rule]

    @property
    def exit_code(self) -> int:
        return 1 if (self.new or self.stale) else 0

    def to_json(self) -> str:
        return json.dumps({
            "files": self.files,
            "elapsed_s": round(self.elapsed_s, 3),
            "findings": [{"file": f.file, "line": f.line, "rule": f.rule,
                          "message": f.message,
                          "fingerprint": f.fingerprint,
                          "baselined": f.fingerprint
                          in self.baseline.entries}
                         for f in self.findings],
            "stale_baseline": self.stale,
            "exit_code": self.exit_code,
        }, indent=1, sort_keys=True)

    def to_sarif(self, rules: Sequence = ()) -> str:
        """SARIF 2.1.0 document for CI annotation (GitHub code
        scanning et al.). New findings are ``error``, baselined ones
        ``note``; the line-number-free fingerprint rides along as a
        partial fingerprint so annotation dedup survives line drift."""
        rule_meta = [{"id": r.id,
                      "shortDescription": {"text": r.protects}}
                     for r in rules]
        results = []
        for f in self.findings:
            baselined = f.fingerprint in self.baseline.entries
            results.append({
                "ruleId": f.rule,
                "level": "note" if baselined else "error",
                "message": {"text": f.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f.file},
                    "region": {"startLine": f.line}}}],
                "partialFingerprints": {"tpuLint/v1": f.fingerprint},
            })
        return json.dumps({
            "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                        "sarif-spec/master/Schemata/sarif-schema-2.1.0"
                        ".json"),
            "version": "2.1.0",
            "runs": [{
                "tool": {"driver": {"name": "tpu-lint",
                                    "rules": rule_meta}},
                "results": results,
            }],
        }, indent=1, sort_keys=True)

    def to_text(self) -> str:
        out: List[str] = []
        for f in self.new:
            out.append(f.text())
        for fp in self.stale:
            out.append(f"stale baseline entry (finding no longer "
                       f"present): {fp}")
        n_base = len(self.findings) - len(self.new)
        out.append(f"tpu-lint: {self.files} files, "
                   f"{len(self.new)} finding(s), {n_base} baselined, "
                   f"{len(self.stale)} stale baseline entr(y/ies) "
                   f"[{self.elapsed_s:.2f}s]")
        return "\n".join(out)


class AnalysisEngine:
    """Run a rule list over a project; apply suppressions + baseline."""

    def __init__(self, rules: Sequence, baseline: Optional[Baseline] = None):
        self.rules = list(rules)
        ids = [r.id for r in self.rules]
        dupes = {i for i in ids if ids.count(i) > 1}
        if dupes:
            raise ValueError(f"duplicate rule ids: {sorted(dupes)}")
        self.baseline = baseline or Baseline()

    def run(self, project: Project) -> Report:
        t0 = time.perf_counter()
        findings: List[Finding] = list(project.parse_errors)
        for rule in self.rules:
            for f in rule.run(project):
                mod = project.module(f.file)
                if mod is not None and mod.suppressed(f.line, f.rule):
                    continue
                findings.append(f)
        return Report(findings, self.baseline,
                      time.perf_counter() - t0, project.parse_count,
                      ran_rules={r.id for r in self.rules})
