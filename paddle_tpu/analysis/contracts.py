"""Rule family 3: metrics / events contracts.

``observability/catalog.py`` declares every registry-owned metric family
(name, kind, label tuple) and every structured-event kind. These rules
hold the tree to it, statically and in both directions:

* ``metric-contract`` — every ``reg.counter/gauge/histogram("paddle_*")``
  registration must match the catalog (kind + exact label tuple); every
  use of a bound metric object (``self._c_x.inc(...)``) must pass exactly
  the declared label names; a catalog entry nothing registers is dead and
  fails too. Subsystem *sinks* are covered through their own declaration:
  string-keyed ``ServingMetrics`` calls (``inc("x")``, ``observe("x")``,
  ``set_gauge("x")``) in ``serving/`` must name a family declared in
  ``ServingMetrics.__init__`` — a typo there silently mints a new series,
  which is exactly the failure mode this family exists to stop.
* ``event-contract`` — every literal ``emit_event("kind", ...)`` /
  ``event_log.emit("kind", ...)`` must use a declared kind; declared
  kinds nothing emits fail.
* ``span-contract`` — every ``emit_span(...)`` / ``metrics.span(...)``
  name must be declared in the catalog's ``SPANS`` table (namespaced
  names — f-strings with a literal ``.suffix`` tail — resolve by that
  suffix), every literal ``args={...}`` dict may only carry declared
  fields, and declared span names nothing emits fail. The timeline
  collector's critical-path attribution keys on these names, so a typo
  silently drops a segment from every request breakdown.

The catalog is parsed from source (``ast.literal_eval``), never imported
— the analyzer stays runnable without jax.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import dotted
from .engine import Finding, Project

CATALOG_REL = "paddle_tpu/observability/catalog.py"
SINK_REL = "paddle_tpu/serving/metrics.py"

_REG_METHODS = {"counter", "gauge", "histogram"}
_USE_METHODS = {"inc", "set", "observe", "value", "hist"}
_SINK_METHODS = {"inc": "counters", "observe": "histograms",
                 "set_gauge": "gauges"}


def _top_level_literal(mod, name: str):
    """(value, Dict/Set node) for a top-level ``NAME = <literal>``."""
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    try:
                        return ast.literal_eval(node.value), node.value
                    except ValueError:
                        return None, node.value
    return None, None


def _str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            vals.append(e.value)
        return tuple(vals)
    return None


def _registration(node: ast.Call):
    """(name, kind, labels-or-None, lineno) when ``node`` registers an
    owned metric; labels is () when the kwarg is absent and None when it
    is present but not a string-literal sequence."""
    if not (isinstance(node.func, ast.Attribute)
            and node.func.attr in _REG_METHODS and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and node.args[0].value.startswith("paddle_")):
        return None
    labels: Optional[Tuple[str, ...]] = ()
    for kw in node.keywords:
        if kw.arg == "labels":
            labels = _str_tuple(kw.value)
    return (node.args[0].value, node.func.attr, labels, node.lineno)


class MetricContractRule:
    id = "metric-contract"
    protects = ("every registry metric registration matches the central "
                "catalog (name, kind, exact label tuple), every labeled "
                "use passes exactly the declared labels, every "
                "ServingMetrics string key names a declared family — "
                "typos can no longer mint phantom series")
    example = 'reg.counter("paddle_kvcache_hits_totl")  # typo: new series'

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        catalog_mod = project.module(CATALOG_REL)
        if catalog_mod is None:
            return [Finding(CATALOG_REL, 1, self.id,
                            "metrics catalog module missing",
                            symbol="catalog-missing")]
        metrics, metrics_node = _top_level_literal(catalog_mod, "METRICS")
        if not isinstance(metrics, dict):
            return [Finding(CATALOG_REL, 1, self.id,
                            "METRICS is not a literal dict",
                            symbol="catalog-unparsable")]
        key_lines = {k.value: k.lineno for k in metrics_node.keys
                     if isinstance(k, ast.Constant)}
        registered: Set[str] = set()
        for mod in project.iter_modules(("paddle_tpu/",)):
            for node in mod.nodes_of(ast.Call):
                reg = _registration(node)
                if reg is None:
                    continue
                name, kind, labels, line = reg
                registered.add(name)
                declared = metrics.get(name)
                if declared is None:
                    out.append(Finding(
                        mod.rel, line, self.id,
                        f"metric {name!r} is not declared in "
                        f"observability/catalog.py — typo, or add it to "
                        "METRICS", symbol=f"undeclared:{name}"))
                    continue
                dkind, dlabels = declared[0], tuple(declared[1])
                if kind != dkind:
                    out.append(Finding(
                        mod.rel, line, self.id,
                        f"metric {name!r} registered as {kind}, catalog "
                        f"declares {dkind}", symbol=f"kind:{name}"))
                if labels is not None and labels != dlabels:
                    out.append(Finding(
                        mod.rel, line, self.id,
                        f"metric {name!r} registered with labels "
                        f"{labels}, catalog declares {dlabels}",
                        symbol=f"labels:{name}"))
            # label-usage check: bound metric objects used with kwargs
            out.extend(self._check_usages(mod, metrics))
        for name in sorted(set(metrics) - registered):
            out.append(Finding(
                CATALOG_REL, key_lines.get(name, 1), self.id,
                f"catalog declares metric {name!r} but nothing in "
                "paddle_tpu/ registers it — remove the entry or wire "
                "the metric", symbol=f"unused:{name}"))
        out.extend(self._check_sink_keys(project))
        return out

    # -- bound-object label usage -------------------------------------------

    def _check_usages(self, mod, metrics) -> List[Finding]:
        out: List[Finding] = []
        bindings: Dict[str, str] = {}       # "self.X" / "X" -> metric name
        for node in mod.nodes_of(ast.Assign):
            if isinstance(node.value, ast.Call):
                reg = _registration(node.value)
                if reg is None:
                    continue
                for t in node.targets:
                    d = dotted(t)
                    if d is not None:
                        bindings[d] = reg[0]
        if not bindings:
            return out
        for node in mod.nodes_of(ast.Call):
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _USE_METHODS):
                continue
            recv = dotted(node.func.value)
            name = bindings.get(recv) if recv else None
            if name is None or name not in metrics:
                continue
            declared = set(metrics[name][1])
            given = {kw.arg for kw in node.keywords
                     if kw.arg is not None and kw.arg != "by"}
            if any(kw.arg is None for kw in node.keywords):
                continue                     # **labels — can't check
            if given != declared:
                out.append(Finding(
                    mod.rel, node.lineno, self.id,
                    f"{recv}.{node.func.attr}() on metric {name!r} "
                    f"passes labels {tuple(sorted(given))}, declared "
                    f"labels are {tuple(sorted(declared))}",
                    symbol=f"use:{name}:{node.func.attr}"))
        return out

    # -- ServingMetrics sink families ---------------------------------------

    def _sink_declared(self, project: Project) -> Dict[str, Set[str]]:
        """{'counters': {...}, 'histograms': {...}, 'gauges': {...}} from
        the dict literals in ServingMetrics.__init__."""
        mod = project.module(SINK_REL)
        decl: Dict[str, Set[str]] = {"counters": set(), "histograms": set(),
                                     "gauges": set()}
        if mod is None:
            return decl
        for node in mod.nodes_of(ast.Assign, ast.AnnAssign):
            if not isinstance(node.value, ast.Dict):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                attr = dotted(t)
                if attr in ("self.counters", "self.histograms",
                            "self.gauges"):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            decl[attr.split(".")[1]].add(k.value)
        return decl

    def _check_sink_keys(self, project: Project) -> List[Finding]:
        decl = self._sink_declared(project)
        if not any(decl.values()):
            return [Finding(SINK_REL, 1, self.id,
                            "could not parse ServingMetrics declared "
                            "families", symbol="sink-unparsable")]
        out: List[Finding] = []
        for mod in project.iter_modules(("paddle_tpu/serving/",)):
            for node in mod.nodes_of(ast.Call):
                if not (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SINK_METHODS
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                family = _SINK_METHODS[node.func.attr]
                name = node.args[0].value
                if name not in decl[family]:
                    out.append(Finding(
                        mod.rel, node.lineno, self.id,
                        f".{node.func.attr}({name!r}) names a "
                        f"{family[:-1]} family ServingMetrics.__init__ "
                        "never declares — it would be minted on first "
                        "use and missing from /metrics until then",
                        symbol=f"sink:{node.func.attr}:{name}"))
        return out


class EventContractRule:
    id = "event-contract"
    protects = ("every emit_event/event_log.emit kind is declared in "
                "observability/catalog.py EVENT_KINDS (and every "
                "declared kind is emitted somewhere) — a typo'd kind "
                "silently forks the event stream")
    example = 'emit_event("relpica_ejected", replica=3)  # typo'

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        catalog_mod = project.module(CATALOG_REL)
        if catalog_mod is None:
            return [Finding(CATALOG_REL, 1, self.id,
                            "event catalog module missing",
                            symbol="catalog-missing")]
        kinds, kinds_node = _top_level_literal(catalog_mod, "EVENT_KINDS")
        if not isinstance(kinds, (set, frozenset)):
            return [Finding(CATALOG_REL, 1, self.id,
                            "EVENT_KINDS is not a literal set",
                            symbol="catalog-unparsable")]
        kind_lines = {}
        if isinstance(kinds_node, ast.Set):
            kind_lines = {e.value: e.lineno for e in kinds_node.elts
                          if isinstance(e, ast.Constant)}
        emitted: Set[str] = set()
        for mod in project.iter_modules(("paddle_tpu/",)):
            for node in mod.nodes_of(ast.Call):
                f = node.func
                is_emit = (isinstance(f, ast.Name)
                           and f.id == "emit_event") or \
                          (isinstance(f, ast.Attribute) and f.attr == "emit"
                           and (dotted(f) or "").split(".")[-2:-1]
                           == ["event_log"])
                if not is_emit or not node.args:
                    continue
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    continue
                emitted.add(arg.value)
                if arg.value not in kinds:
                    out.append(Finding(
                        mod.rel, node.lineno, self.id,
                        f"event kind {arg.value!r} is not declared in "
                        "observability/catalog.py EVENT_KINDS — typo, "
                        "or declare it",
                        symbol=f"undeclared:{arg.value}"))
        for kind in sorted(set(kinds) - emitted):
            out.append(Finding(
                CATALOG_REL, kind_lines.get(kind, 1), self.id,
                f"EVENT_KINDS declares {kind!r} but nothing in "
                "paddle_tpu/ emits it — remove or wire the event",
                symbol=f"unused:{kind}"))
        return out


class SpanContractRule:
    id = "span-contract"
    protects = ("every emit_span/metrics.span name (and its literal args "
                "fields) is declared in observability/catalog.py SPANS, "
                "and every declared span is emitted somewhere — the "
                "timeline collector's segment attribution keys on these "
                "names, so a typo silently drops a critical-path segment")
    example = 'emit_span("engine.prefil", t0, t1)  # typo: lost segment'

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        catalog_mod = project.module(CATALOG_REL)
        if catalog_mod is None:
            return [Finding(CATALOG_REL, 1, self.id,
                            "span catalog module missing",
                            symbol="catalog-missing")]
        spans, spans_node = _top_level_literal(catalog_mod, "SPANS")
        if not isinstance(spans, dict):
            return [Finding(CATALOG_REL, 1, self.id,
                            "SPANS is not a literal dict",
                            symbol="catalog-unparsable")]
        key_lines = {k.value: k.lineno for k in spans_node.keys
                     if isinstance(k, ast.Constant)}
        emitted: Set[str] = set()
        for mod in project.iter_modules(("paddle_tpu/",)):
            for node in mod.nodes_of(ast.Call):
                f = node.func
                is_span = ((isinstance(f, ast.Name)
                            and f.id in ("emit_span", "make_span"))
                           or (isinstance(f, ast.Attribute)
                               and f.attr in ("emit_span", "make_span",
                                              "span")))
                if not is_span or not node.args:
                    continue
                name = self._span_name(node.args[0])
                if name is None:
                    continue        # dynamic name (metrics.mark relay)
                declared = spans.get(name) or spans.get(
                    name.rsplit(".", 1)[-1])
                if declared is None:
                    out.append(Finding(
                        mod.rel, node.lineno, self.id,
                        f"span {name!r} is not declared in "
                        "observability/catalog.py SPANS — typo, or "
                        "declare it (segment attribution keys on span "
                        "names)", symbol=f"undeclared:{name}"))
                    continue
                emitted.add(name if name in spans
                            else name.rsplit(".", 1)[-1])
                out.extend(self._check_fields(mod, node, name,
                                              tuple(declared)))
        for name in sorted(set(spans) - emitted):
            out.append(Finding(
                CATALOG_REL, key_lines.get(name, 1), self.id,
                f"SPANS declares {name!r} but nothing in paddle_tpu/ "
                "emits it — remove or wire the span",
                symbol=f"unused:{name}"))
        return out

    @staticmethod
    def _span_name(arg: ast.AST) -> Optional[str]:
        """Literal span name, resolving f-strings with a literal dotted
        tail (``f"{ns}.queue_wait"`` -> ``"queue_wait"``)."""
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
        if isinstance(arg, ast.JoinedStr) and arg.values:
            tail = arg.values[-1]
            if (isinstance(tail, ast.Constant)
                    and isinstance(tail.value, str)
                    and tail.value.startswith(".")):
                return tail.value[1:]
        return None

    def _check_fields(self, mod, node: ast.Call, name: str,
                      declared: Tuple[str, ...]) -> List[Finding]:
        for kw in node.keywords:
            if kw.arg != "args" or not isinstance(kw.value, ast.Dict):
                continue
            keys = []
            for k in kw.value.keys:
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    return []        # dynamic keys — can't check
                keys.append(k.value)
            extra = sorted(set(keys) - set(declared))
            if extra:
                return [Finding(
                    mod.rel, node.lineno, self.id,
                    f"span {name!r} emitted with undeclared args fields "
                    f"{tuple(extra)}; catalog allows {declared}",
                    symbol=f"fields:{name}")]
        return []


CONTRACT_RULES = (MetricContractRule(), EventContractRule(),
                  SpanContractRule())
