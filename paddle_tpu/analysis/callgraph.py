"""Project-wide symbol index + traced-code call graph.

Built once per :class:`~paddle_tpu.analysis.engine.Project` and shared by
every rule that needs more than single-file pattern matching. Three
layers:

* **imports** — per module: alias -> absolute module name (``import x.y
  as z``) and name -> (module, original) for ``from x import y``;
  relative imports are resolved against the importing module's package.
* **definitions** — every function/method with its scope-qualified name
  and owning class; every class with its method table.
* **traced reachability** — the call graph walked from *jit roots*:
  functions handed to ``jax.jit`` / ``pl.pallas_call`` (positionally or
  via ``functools.partial(jax.jit, ...)`` decorators), ``@jit``-style
  decorated functions, and lambdas jitted inline. Resolution is
  deliberately conservative (same-scope names, same-class ``self.``
  methods, explicitly imported module attributes) so the purity rules
  over-approximate reachable code only through edges that are certainly
  real — a missing edge costs recall, never a false positive.

This is the substrate ROADMAP item 2's telemetry-guided fusion pass
needs: a static view of which Python code runs under trace.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import Project, SourceModule

#: call targets that mark their function argument as traced
_JIT_NAMES = {"jit", "pallas_call"}


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_jit_expr(node: ast.AST) -> bool:
    """True when ``node`` names a tracing entry point (``jax.jit``,
    ``jit``, ``pl.pallas_call``, ``pallas_call``)."""
    d = dotted(node)
    return d is not None and d.split(".")[-1] in _JIT_NAMES


class FunctionInfo:
    """One def/lambda with enough context to resolve its calls."""

    __slots__ = ("module", "node", "qualname", "class_name", "scope")

    def __init__(self, module: SourceModule, node: ast.AST, qualname: str,
                 class_name: Optional[str],
                 scope: Dict[str, "FunctionInfo"]):
        self.module = module
        self.node = node
        self.qualname = qualname        # e.g. "Engine._build.<locals>.run"
        self.class_name = class_name
        #: names visible where this function is DEFINED (enclosing defs
        #: + module top level) — used to resolve bare-name calls
        self.scope = scope

    @property
    def name(self) -> str:
        return getattr(self.node, "name", "<lambda>")

    def own_nodes(self) -> Iterable[ast.AST]:
        """Walk this function's body WITHOUT descending into nested
        function/class definitions (those are separate graph nodes)."""
        body = (self.node.body if isinstance(self.node.body, list)
                else [self.node.body])
        stack: List[ast.AST] = list(body)
        while stack:
            n = stack.pop()
            yield n
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    continue
                stack.append(child)

    def param_names(self) -> Set[str]:
        a = self.node.args
        names = [p.arg for p in getattr(a, "posonlyargs", []) + a.args
                 + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return set(names)


class ModuleInfo:
    """Per-module symbol tables."""

    def __init__(self, module: SourceModule, modname: str):
        self.module = module
        self.modname = modname              # "paddle_tpu.serving.scheduler"
        self.import_aliases: Dict[str, str] = {}     # alias -> module
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.functions: List[FunctionInfo] = []      # every def, any depth
        self.top_level: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, Dict[str, FunctionInfo]] = {}
        self.lambdas: Dict[int, FunctionInfo] = {}   # id(node) -> info


def _modname(rel: str) -> str:
    return rel[:-3].replace("/", ".") if rel.endswith(".py") else \
        rel.replace("/", ".")


class ProjectIndex:
    def __init__(self, project: Project):
        self.project = project
        self.mods: Dict[str, ModuleInfo] = {}        # modname -> info
        self.by_rel: Dict[str, ModuleInfo] = {}
        for m in project.modules:
            mi = ModuleInfo(m, _modname(m.rel))
            self._index_imports(mi)
            self._index_defs(mi)
            self.mods[mi.modname] = mi
            self.by_rel[m.rel] = mi
        self._traced: Optional[Set[int]] = None      # id(FunctionInfo.node)
        self._traced_fns: List[FunctionInfo] = []
        self._roots: List[FunctionInfo] = []

    # -- construction -------------------------------------------------------

    def _index_imports(self, mi: ModuleInfo) -> None:
        pkg_parts = mi.modname.split(".")[:-1]
        for node in mi.module.nodes_of(ast.Import, ast.ImportFrom):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mi.import_aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
                    if a.asname:
                        mi.import_aliases[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                    src = ".".join(base + ([node.module]
                                           if node.module else []))
                else:
                    src = node.module or ""
                for a in node.names:
                    mi.from_imports[a.asname or a.name] = (src, a.name)

    @staticmethod
    def _level_stmts(body) -> List[ast.stmt]:
        """Statements at one scope level, descending through compound
        statements (if/try/with/for/while) but not into defs/classes —
        a def inside an ``if`` still binds in the enclosing scope."""
        out: List[ast.stmt] = []
        stack = list(body)
        while stack:
            node = stack.pop(0)
            out.append(node)
            if isinstance(node, (ast.If, ast.For, ast.While, ast.With,
                                 ast.Try)):
                for field in ("body", "orelse", "finalbody"):
                    stack.extend(getattr(node, field, []))
                for h in getattr(node, "handlers", []):
                    stack.extend(h.body)
        return out

    def _index_defs(self, mi: ModuleInfo) -> None:
        def visit(node, qual: List[str], class_name: Optional[str],
                  scope: Dict[str, FunctionInfo]):
            # two passes per level so sibling defs see each other
            local: Dict[str, FunctionInfo] = {}
            body = self._level_stmts(node.body
                                     if hasattr(node, "body") else [])
            infos = []
            for child in body:
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = ".".join(qual + [child.name]) if qual else child.name
                    fi = FunctionInfo(mi.module, child, q, class_name,
                                      scope)  # placeholder; fixed below
                    local[child.name] = fi
                    infos.append((child, fi))
            merged = {**scope, **local}
            for child, fi in infos:
                fi.scope = merged
                mi.functions.append(fi)
                if not qual:
                    mi.top_level[child.name] = fi
                if class_name is not None and len(qual) == 1:
                    mi.classes.setdefault(class_name, {})[child.name] = fi
                visit(child, qual + [child.name, "<locals>"], None, merged)
            for child in body:
                if isinstance(child, ast.ClassDef):
                    visit(child, qual + [child.name], child.name, merged)

        visit(mi.module.tree, [], None, {})
        # lambdas are indexed LAZILY (see _lambda_info): walking every
        # function subtree up front for them blew the tier-1 speed
        # budget, and only jitted lambdas are ever looked up

    def _lambda_info(self, mi: ModuleInfo, node: ast.Lambda
                     ) -> FunctionInfo:
        li = mi.lambdas.get(id(node))
        if li is None:
            owner = self._enclosing(mi, node)
            li = FunctionInfo(
                mi.module, node,
                (owner.qualname + ".<lambda>") if owner else "<lambda>",
                owner.class_name if owner else None,
                owner.scope if owner else mi.top_level)
            mi.functions.append(li)
            mi.lambdas[id(node)] = li
        return li

    # -- traced reachability ------------------------------------------------

    def traced_functions(self) -> List[FunctionInfo]:
        """Every function reachable from a jit/pallas root."""
        if self._traced is None:
            self._compute_traced()
        return self._traced_fns

    def traced_roots(self) -> List[FunctionInfo]:
        if self._traced is None:
            self._compute_traced()
        return self._roots

    def _compute_traced(self) -> None:
        roots: List[FunctionInfo] = []
        for mi in self.mods.values():
            if not mi.module.rel.startswith("paddle_tpu/"):
                continue
            for node in mi.module.nodes_of(ast.Call, ast.FunctionDef,
                                            ast.AsyncFunctionDef):
                # jax.jit(fn, ...) / pl.pallas_call(kernel, ...)
                if isinstance(node, ast.Call) and is_jit_expr(node.func):
                    for arg in node.args[:1]:
                        fi = self._fn_for_arg(mi, arg, node)
                        if fi is not None:
                            roots.append(fi)
                # decorators: @jax.jit / @jit / @partial(jax.jit, ...)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if is_jit_expr(dec) or (
                                isinstance(dec, ast.Call)
                                and (is_jit_expr(dec.func)
                                     or any(is_jit_expr(a)
                                            for a in dec.args))):
                            fi = self._info_for_def(mi, node)
                            if fi is not None:
                                roots.append(fi)
        self._roots = roots
        seen: Set[int] = set()
        queue = list(roots)
        ordered: List[FunctionInfo] = []
        while queue:
            fi = queue.pop()
            if id(fi.node) in seen:
                continue
            seen.add(id(fi.node))
            ordered.append(fi)
            queue.extend(self._callees(fi))
        self._traced = seen
        self._traced_fns = ordered

    def _fn_for_arg(self, mi: ModuleInfo, arg: ast.AST,
                    call: ast.Call) -> Optional[FunctionInfo]:
        if isinstance(arg, ast.Lambda):
            return self._lambda_info(mi, arg)
        if isinstance(arg, ast.Call):
            # transparent wrappers: the wrapped function still traces
            # (partial statics, shard_map bodies, vmap/grad/remat, the
            # compat shim's resolved shard_map)
            d = dotted(arg.func)
            wrappers = {"partial", "shard_map", "vmap", "grad",
                        "value_and_grad", "remat", "checkpoint"}
            if d is not None and d.split(".")[-1] in wrappers and arg.args:
                return self._fn_for_arg(mi, arg.args[0], call)
            return None
        if isinstance(arg, ast.Name):
            # resolve in the scope of the function containing the call:
            # its OWN local defs first (jax.jit(run, ...) at the end of a
            # builder method), then enclosing scopes, then module level
            owner = self._enclosing(mi, call)
            if owner is not None:
                child_qual = f"{owner.qualname}.<locals>.{arg.id}"
                for fi in mi.functions:
                    if fi.qualname == child_qual:
                        return fi
            scope = owner.scope if owner is not None else mi.top_level
            target = scope.get(arg.id) or mi.top_level.get(arg.id)
            if target is not None:
                return target
            imp = mi.from_imports.get(arg.id)
            if imp is not None:
                other = self.mods.get(imp[0])
                if other is not None:
                    return other.top_level.get(imp[1])
            # local rebinding: kernel = functools.partial(_kernel, ...)
            if owner is not None:
                for node in ast.walk(owner.node):
                    if (isinstance(node, ast.Assign)
                            and isinstance(node.value, ast.Call)
                            and any(isinstance(t, ast.Name)
                                    and t.id == arg.id
                                    for t in node.targets)):
                        return self._fn_for_arg(mi, node.value, call)
        return None

    def _info_for_def(self, mi: ModuleInfo, node) -> Optional[FunctionInfo]:
        for fi in mi.functions:
            if fi.node is node:
                return fi
        return None

    def _enclosing(self, mi: ModuleInfo, node: ast.AST
                   ) -> Optional[FunctionInfo]:
        """The innermost FunctionInfo whose body contains ``node`` (by
        line containment — cheap and adequate for call-site scoping)."""
        best: Optional[FunctionInfo] = None
        ln = getattr(node, "lineno", None)
        if ln is None:
            return None
        for fi in mi.functions:
            n = fi.node
            end = getattr(n, "end_lineno", None)
            if n.lineno <= ln and end is not None and ln <= end:
                if best is None or n.lineno >= best.node.lineno:
                    best = fi
        return best

    def resolve_call(self, fi: FunctionInfo,
                     node: ast.Call) -> Optional[FunctionInfo]:
        """Conservative single-call resolution — THE per-node convention
        shared by the call-graph edges and the dataflow rules (same-scope
        locals, self methods, from-imports, module-attribute calls
        through import aliases); ``None`` at resolution gaps."""
        mi = self.by_rel.get(fi.module.rel)
        if mi is None:
            return None
        f = node.func
        if isinstance(f, ast.Name):
            child_qual = f"{fi.qualname}.<locals>.{f.id}"
            child = next((c for c in mi.functions
                          if c.qualname == child_qual), None)
            if child is not None:
                return child
            target = fi.scope.get(f.id) or mi.top_level.get(f.id)
            if target is not None:
                return target
            imp = mi.from_imports.get(f.id)
            if imp is not None:
                other = self.mods.get(imp[0])
                if other is not None:
                    return other.top_level.get(imp[1])
            return None
        if isinstance(f, ast.Attribute):
            d = dotted(f)
            if d is None:
                return None
            parts = d.split(".")
            if parts[0] == "self" and len(parts) == 2 \
                    and fi.class_name is not None:
                return mi.classes.get(fi.class_name, {}).get(parts[1])
            # module-attribute call through an import alias
            if len(parts) == 2:
                target_mod = None
                if parts[0] in mi.import_aliases:
                    target_mod = self.mods.get(mi.import_aliases[parts[0]])
                elif parts[0] in mi.from_imports:
                    src, orig = mi.from_imports[parts[0]]
                    target_mod = self.mods.get(f"{src}.{orig}")
                if target_mod is not None:
                    return target_mod.top_level.get(parts[-1])
        return None

    def _callees(self, fi: FunctionInfo) -> List[FunctionInfo]:
        out: List[FunctionInfo] = []
        for node in fi.own_nodes():
            if isinstance(node, ast.Call):
                t = self.resolve_call(fi, node)
                if t is not None:
                    out.append(t)
        return out
