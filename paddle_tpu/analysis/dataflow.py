"""tpu-lint v2: interprocedural dataflow over a real CFG.

PR 8's rule families are per-file AST pattern matches; the invariants
this module protects are *path* properties no pattern can see:

* every paged acquisition must reach a release on EVERY path out of the
  acquiring function — exception edges included (the safety net the
  int8-page work multiplies the blast radius of);
* dtype facts flow through traced code, so silent f32 promotion in a
  bf16/int8 chain, a dequant that never meets its scale, or a
  mixed-dtype contraction are provable, not guessable;
* every trace-time external input (``flag_value``, ``os.environ``)
  reachable from a cached-compile body must be derivable from that
  cache's key expression — the generalisation of the PR 8 stale-program
  defect (FLAGS_serving_a8w8_prefill) into a standing rule.

Three layers:

1. **CFG** (:func:`build_cfg`) — one basic-block-per-statement control
   flow graph per function: branches, loops (back edges), try/except/
   finally (handler edges, duplicated finally instances per
   continuation), with-blocks, early returns, break/continue, and
   conservative *exception edges* from any statement that contains a
   call/raise/assert to the innermost matching handler chain (or the
   function's exceptional exit).
2. **Worklist solver** (:func:`solve_forward`) — generic forward
   abstract interpretation to fixpoint; transfer functions return a
   (normal, exceptional) out-state pair so exception edges carry the
   state *at the raise point*, which is what makes leak-on-exception
   findings real.
3. **Interprocedural summaries** (:class:`Summaries`) — layered on the
   existing :class:`~paddle_tpu.analysis.callgraph.ProjectIndex` call
   graph: per-function "releases pages", "flags read (transitively)"
   and "return dtype" facts, computed cycle-safely and used as
   call-site transfer functions. Resolution gaps are CONSERVATIVE in
   the no-false-positive direction: an unresolvable call neither
   releases, nor reads a flag, nor has a known dtype.

The three rule families (``page-leak``, ``dtype-flow``, ``cache-key``)
live at the bottom of this file and register in ``AnalysisEngine``
beside purity/locks/contracts/layering. Same contract as PR 8:
deterministic findings with line-number-free fingerprints,
``# tpu-lint: disable=`` suppressions, baselined-with-justification
entries, and the <5 s whole-package wall budget.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .callgraph import FunctionInfo, ProjectIndex, dotted
from .engine import Finding, Project

# ---------------------------------------------------------------------------
# Control-flow graph
# ---------------------------------------------------------------------------

class Block:
    """One CFG node. ``stmt`` is the owning AST statement (or the test
    expression for branch headers; None for synthetic entry/exit/join
    nodes). ``succ`` are normal-flow successors, ``esucc`` the targets
    an in-statement exception transfers to."""

    __slots__ = ("bid", "stmt", "kind", "succ", "esucc")

    def __init__(self, bid: int, stmt=None, kind: str = "stmt"):
        self.bid = bid
        self.stmt = stmt
        self.kind = kind            # stmt | test | entry | exit | exc | join
        self.succ: List["Block"] = []
        self.esucc: List["Block"] = []

    def __repr__(self):             # pragma: no cover - debugging aid
        ln = getattr(self.stmt, "lineno", "-")
        return f"<B{self.bid} {self.kind}@{ln}>"


class _Level:
    """One enclosing try-level for exception routing."""

    __slots__ = ("outer", "handler_entries", "catch_all", "finalbody",
                 "cfg", "_exc_entry", "_ret_entry")

    def __init__(self, cfg, outer, handler_entries=(), catch_all=False,
                 finalbody=None):
        self.cfg = cfg
        self.outer = outer
        self.handler_entries = list(handler_entries)
        self.catch_all = catch_all
        self.finalbody = finalbody          # list[stmt] or None
        self._exc_entry = None              # memoized finally instances
        self._ret_entry = None

    # -- duplicated finally instances ---------------------------------------

    def exc_entry(self) -> Block:
        """Entry of this level's finally instance on the EXCEPTION path
        (tail re-raises: continues routing at the outer level)."""
        if self._exc_entry is None:
            entry = self.cfg._join_block()
            self._exc_entry = entry
            tail = self.cfg._build_seq(self.finalbody, [entry], self.outer)
            for cont in self.cfg._exc_targets(self.outer):
                for b in tail:
                    b.succ.append(cont)
        return self._exc_entry

    def ret_entry(self) -> Block:
        """Entry of this level's finally instance on the RETURN path
        (tail continues returning through outer finallys to EXIT)."""
        if self._ret_entry is None:
            entry = self.cfg._join_block()
            self._ret_entry = entry
            tail = self.cfg._build_seq(self.finalbody, [entry], self.outer)
            cont = self.cfg._ret_continuation(self.outer)
            for b in tail:
                b.succ.append(cont)
        return self._ret_entry


#: handler types treated as catching EVERYTHING (propagation stops)
_CATCH_ALL = {"Exception", "BaseException"}

#: statements that can transfer control exceptionally (conservative: a
#: contained call/raise/assert; attribute/key errors are deliberately out
#: of scope to keep exception edges meaningful rather than total)
def _can_raise(stmt) -> bool:
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call):
            return True
    return False


class FunctionCFG:
    """CFG of one function body. Public surface: ``entry``, ``exit``
    (normal return), ``exc_exit`` (exception propagates out),
    ``blocks``."""

    def __init__(self, fn_node):
        self.fn_node = fn_node
        self.blocks: List[Block] = []
        self.entry = self._block(None, "entry")
        self.exit = self._block(None, "exit")
        self.exc_exit = self._block(None, "exc")
        self._loop_stack: List[Tuple[Block, Block, "_Level"]] = []
        body = fn_node.body if isinstance(fn_node.body, list) \
            else [ast.Return(value=fn_node.body)]
        tail = self._build_seq(body, [self.entry], None)
        for b in tail:
            b.succ.append(self.exit)

    # -- low-level helpers ---------------------------------------------------

    def _block(self, stmt, kind="stmt") -> Block:
        b = Block(len(self.blocks), stmt, kind)
        self.blocks.append(b)
        return b

    def _join_block(self) -> Block:
        return self._block(None, "join")

    def _exc_targets(self, level: Optional[_Level]) -> List[Block]:
        """Where an exception continuing past a finally goes next:
        every enclosing handler chain until a catch-all, else onward
        through the next finally instance, else exc_exit — the same
        routing :meth:`_route_exc` applies at a raise site. Walking only
        the finallys here (the original bug) skipped enclosing except
        handlers, so ``try: try: ... finally: ... except: release()``
        minted page-leak false positives."""
        targets: List[Block] = []
        while level is not None:
            targets.extend(level.handler_entries)
            if level.catch_all:
                return targets
            if level.finalbody:
                targets.append(level.exc_entry())
                return targets
            level = level.outer
        targets.append(self.exc_exit)
        return targets

    def _ret_continuation(self, level: Optional[_Level]) -> Block:
        while level is not None:
            if level.finalbody:
                return level.ret_entry()
            level = level.outer
        return self.exit

    def _jump_entry(self, level: Optional[_Level],
                    stop_level: Optional[_Level], target: Block) -> Block:
        """Where a break/continue at ``level`` lands first: every
        finalbody between the jump and the loop's own ``stop_level``
        (exclusive) runs, innermost first, before control reaches
        ``target`` (the loop's after/header block). Jumping straight to
        ``target`` (the original bug) made releases inside those
        finallys invisible to page-leak on break/continue paths."""
        chain: List[_Level] = []
        lv = level
        while lv is not None and lv is not stop_level:
            if lv.finalbody:
                chain.append(lv)
            lv = lv.outer
        for lv in reversed(chain):          # wire outermost-first so each
            entry = self._join_block()      # inner tail continues outward
            tail = self._build_seq(lv.finalbody, [entry], lv.outer)
            for b in tail:
                b.succ.append(target)
            target = entry
        return target

    def _route_exc(self, block: Block, level: Optional[_Level]) -> None:
        """Exception edges from ``block`` — one routing walk
        (:meth:`_exc_targets`) shared with finally-tail continuation so
        the two can never diverge."""
        block.esucc.extend(self._exc_targets(level))

    # -- recursive construction ----------------------------------------------

    def _build_seq(self, stmts, frontier: List[Block],
                   level: Optional[_Level]) -> List[Block]:
        """Wire ``stmts`` after ``frontier``; returns the new frontier
        (blocks whose normal successor is whatever comes next)."""
        for stmt in stmts or ():
            if not frontier:
                break                       # unreachable code after return
            frontier = self._build_stmt(stmt, frontier, level)
        return frontier

    def _build_stmt(self, stmt, frontier, level) -> List[Block]:
        if isinstance(stmt, ast.If):
            test = self._block(stmt.test, "test")
            self._connect(frontier, test)
            if _can_raise(stmt.test):
                self._route_exc(test, level)
            t_tail = self._build_seq(stmt.body, [test], level)
            e_tail = self._build_seq(stmt.orelse, [test], level) \
                if stmt.orelse else [test]
            return t_tail + e_tail

        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            header = self._block(
                stmt.test if isinstance(stmt, ast.While) else stmt.iter,
                "test")
            self._connect(frontier, header)
            if _can_raise_expr(header.stmt):
                self._route_exc(header, level)
            after = self._join_block()
            self._loop_stack.append((header, after, level))
            body_tail = self._build_seq(stmt.body, [header], level)
            for b in body_tail:
                b.succ.append(header)       # back edge
            self._loop_stack.pop()
            else_tail = self._build_seq(stmt.orelse, [header], level) \
                if stmt.orelse else [header]
            self._connect(else_tail, after)
            return [after]

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            header = self._block(stmt, "stmt")  # __enter__ calls can raise
            self._connect(frontier, header)
            if _can_raise(stmt):
                self._route_exc(header, level)
            return self._build_seq(stmt.body, [header], level)

        if isinstance(stmt, ast.Try):
            return self._build_try(stmt, frontier, level)

        if isinstance(stmt, ast.Return):
            b = self._block(stmt, "stmt")
            self._connect(frontier, b)
            if _can_raise(stmt):
                self._route_exc(b, level)
            b.succ.append(self._ret_continuation(level))
            return []

        if isinstance(stmt, ast.Raise):
            b = self._block(stmt, "stmt")
            self._connect(frontier, b)
            self._route_exc(b, level)
            return []

        if isinstance(stmt, ast.Break):
            b = self._block(stmt, "stmt")
            self._connect(frontier, b)
            if self._loop_stack:
                header, after, loop_level = self._loop_stack[-1]
                b.succ.append(self._jump_entry(level, loop_level, after))
            return []

        if isinstance(stmt, ast.Continue):
            b = self._block(stmt, "stmt")
            self._connect(frontier, b)
            if self._loop_stack:
                header, after, loop_level = self._loop_stack[-1]
                b.succ.append(self._jump_entry(level, loop_level, header))
            return []

        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # nested definitions are separate graph nodes (the call
            # graph owns them); the def statement itself cannot raise
            b = self._block(stmt, "stmt")
            self._connect(frontier, b)
            return [b]

        # simple statement
        b = self._block(stmt, "stmt")
        self._connect(frontier, b)
        if _can_raise(stmt):
            self._route_exc(b, level)
        return [b]

    def _build_try(self, stmt: ast.Try, frontier, level) -> List[Block]:
        finalbody = stmt.finalbody or None
        # exceptions raised INSIDE a handler (or the else block) skip
        # this try's handlers but still run its finally
        handler_level = _Level(self, level, finalbody=finalbody)
        handler_entries: List[Block] = []
        handler_tails: List[Block] = []
        catch_all = False
        for h in stmt.handlers:
            entry = self._join_block()
            handler_entries.append(entry)
            if h.type is None:
                catch_all = True
            else:
                names = [dotted(e) for e in
                         (h.type.elts if isinstance(h.type, ast.Tuple)
                          else [h.type])]
                if any((n or "").split(".")[-1] in _CATCH_ALL
                       for n in names):
                    catch_all = True
            handler_tails += self._build_seq(h.body, [entry],
                                             handler_level)
        body_level = _Level(self, level, handler_entries, catch_all,
                            finalbody)
        body_tail = self._build_seq(stmt.body, frontier, body_level)
        else_tail = self._build_seq(stmt.orelse, body_tail,
                                    handler_level) \
            if stmt.orelse else body_tail
        done = else_tail + handler_tails
        if finalbody:
            fin_entry = self._join_block()
            self._connect(done, fin_entry)
            return self._build_seq(finalbody, [fin_entry], level)
        return done

    @staticmethod
    def _connect(frontier: List[Block], target: Block) -> None:
        for b in frontier:
            b.succ.append(target)


def _can_raise_expr(expr) -> bool:
    if expr is None:
        return False
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            return True
    return False


def build_cfg(fn_node) -> FunctionCFG:
    """Public CFG constructor (memoize per node id if calling in bulk)."""
    return FunctionCFG(fn_node)


# ---------------------------------------------------------------------------
# Worklist fixpoint solver
# ---------------------------------------------------------------------------

#: hard cap on solver iterations — the lattices used here are finite
#: height so this never binds; it is a guard against a rule bug looping
MAX_ITERATIONS = 200_000


def solve_forward(cfg: FunctionCFG, analysis) -> Dict[int, object]:
    """Forward abstract interpretation to fixpoint.

    ``analysis`` provides ``initial()`` (entry state), ``join(a, b)``
    (``a`` may be None = unreached) and ``transfer(state, block) ->
    (normal_out, exc_out)``. Returns ``{block.bid: in_state}`` for every
    reached block (exit/exc_exit in-states are the rule's verdict)."""
    in_states: Dict[int, object] = {cfg.entry.bid: analysis.initial()}
    work: List[Block] = [cfg.entry]
    iters = 0
    while work:
        iters += 1
        if iters > MAX_ITERATIONS:          # pragma: no cover - guard
            break
        b = work.pop()
        state = in_states.get(b.bid)
        if state is None:
            continue
        n_out, e_out = analysis.transfer(state, b)
        for succ, out in [(s, n_out) for s in b.succ] + \
                         [(s, e_out) for s in b.esucc]:
            joined = analysis.join(in_states.get(succ.bid), out)
            if joined != in_states.get(succ.bid):
                in_states[succ.bid] = joined
                work.append(succ)
    return in_states


# ---------------------------------------------------------------------------
# Interprocedural summaries (layered on ProjectIndex)
# ---------------------------------------------------------------------------

_RELEASE_METHODS = {"free", "truncate_pages"}
_FLAG_READERS = {"flag_value"}
_ENV_READERS = {"os.environ.get", "os.getenv"}


class Summaries:
    """Cycle-safe per-function facts used as call-site transfer
    functions. A resolution gap contributes NOTHING (conservative in the
    direction that can only lose recall, never mint false positives)."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self._releases: Dict[int, bool] = {}
        self._flags: Dict[int, FrozenSet[str]] = {}
        self._ret_dtype: Dict[int, Optional[str]] = {}

    # -- releases ------------------------------------------------------------

    def releases(self, fi: FunctionInfo) -> bool:
        """True when ``fi`` (transitively) calls ``.free()`` /
        ``.truncate_pages()`` — a call to such a helper counts as a
        release at the call site."""
        return self._releases_walk(fi, set())[0]

    def _releases_walk(self, fi: FunctionInfo,
                       stack: Set[int]) -> Tuple[bool, bool]:
        """Returns ``(releases, final)``. A cycle cut under-approximates
        (False), so a False computed under one is PROVISIONAL — memoizing
        it would poison later queries in the false-positive direction
        (a mutually-recursive helper that does release would stay
        "no-release" forever). True is always final (a release call is a
        definite fact), and so is the walk ROOT's False: every node a
        cut edge points back to is on the current stack, so the root's
        traversal has accumulated the whole component's direct facts."""
        key = id(fi.node)
        if key in self._releases:
            return self._releases[key], True
        if key in stack:
            return False, False             # cycle cut: provisional
        is_root = not stack
        stack.add(key)
        out = False
        final = True
        for node in fi.own_nodes():
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _RELEASE_METHODS:
                out = True
                break
        if not out:
            for callee in self.index._callees(fi):
                v, f = self._releases_walk(callee, stack)
                final = final and f
                if v:
                    out = True
                    break
        stack.discard(key)
        if out or final or is_root:
            self._releases[key] = out
        return out, out or final or is_root

    # -- flags read ----------------------------------------------------------

    def flags_read(self, fi: FunctionInfo) -> FrozenSet[str]:
        """Names of every ``flag_value("<literal>")`` (plus the token
        ``os.environ`` for env reads) reachable from ``fi`` through
        resolvable call edges."""
        return self._flags_walk(fi, set())[0]

    def _flags_walk(self, fi: FunctionInfo,
                    stack: Set[int]) -> Tuple[FrozenSet[str], bool]:
        """Returns ``(flags, final)`` — same taint discipline as
        :meth:`_releases_walk`: a set accumulated under a cycle cut may
        be missing the cycle's flags, so only clean results and the walk
        root's (complete by the stack argument above) are memoized."""
        key = id(fi.node)
        if key in self._flags:
            return self._flags[key], True
        if key in stack:
            return frozenset(), False       # cycle cut: provisional
        is_root = not stack
        stack.add(key)
        out: Set[str] = set(direct_flag_reads(fi))
        final = True
        for callee in self.index._callees(fi):
            v, f = self._flags_walk(callee, stack)
            out |= v
            final = final and f
        stack.discard(key)
        result = frozenset(out)
        if final or is_root:
            self._flags[key] = result
        return result, final or is_root

    # -- return dtype ---------------------------------------------------------

    def return_dtype(self, fi: FunctionInfo) -> Optional[str]:
        """The dtype every return statement of ``fi`` provably yields
        (with parameters unknown), else None. Cycle-cut to None."""
        key = id(fi.node)
        if key in self._ret_dtype:
            return self._ret_dtype[key]
        self._ret_dtype[key] = None         # cycle cut
        dts: Set[Optional[str]] = set()
        for node in fi.own_nodes():
            if isinstance(node, ast.Return):
                if node.value is None:
                    dts.add(None)
                else:
                    dt, _, _ = _expr_dtype(node.value, {}, self, fi, None)
                    dts.add(dt)
        out = dts.pop() if len(dts) == 1 else None
        self._ret_dtype[key] = out
        return out


def direct_flag_reads(fi: FunctionInfo) -> Set[str]:
    """Literal flag/env reads in ``fi``'s own body."""
    out: Set[str] = set()
    for node in fi.own_nodes():
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d is None:
            continue
        if d.split(".")[-1] in _FLAG_READERS and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            out.add(node.args[0].value)
        elif d in _ENV_READERS:
            out.add("os.environ")
    return out


def _shared(project: Project):
    """One (Summaries, cfg-cache) pair per Project, shared by all three
    rule families so the whole run stays inside the 5 s budget."""
    state = getattr(project, "_dataflow_state", None)
    if state is None:
        state = (Summaries(project.index), {})
        project._dataflow_state = state
    return state


def _cfg_for(project: Project, fi: FunctionInfo) -> FunctionCFG:
    _, cache = _shared(project)
    key = id(fi.node)
    cfg = cache.get(key)
    if cfg is None:
        cfg = build_cfg(fi.node)
        cache[key] = cfg
    return cfg


# ---------------------------------------------------------------------------
# Rule family 5: resource flow (page-leak)
# ---------------------------------------------------------------------------

_ACQUIRE_METHODS = {"allocate", "grow_to"}
_ESCAPE_METHODS = {"append", "extend", "insert", "add", "setdefault",
                   "update", "put"}


class _LeakState:
    """Immutable may-held state: frozenset of acquisition ids + the
    variable bindings that let an escape discharge them."""

    __slots__ = ("held", "binds")

    def __init__(self, held: FrozenSet[int] = frozenset(),
                 binds: FrozenSet[Tuple[str, int]] = frozenset()):
        self.held = held
        self.binds = binds

    def __eq__(self, other):
        return isinstance(other, _LeakState) and self.held == other.held \
            and self.binds == other.binds

    def __hash__(self):
        return hash((self.held, self.binds))


class _LeakAnalysis:
    def __init__(self, acqs: Dict[int, Tuple[ast.Call, str, Optional[str]]]):
        #: id(call) -> (call node, receiver dotted, bound var name|None)
        self.acqs = acqs

    def initial(self):
        return _LeakState()

    def join(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        # both unions: held is MAY-held; binds union keeps every path's
        # binding so an escape can discharge whichever acquisition the
        # variable carries on the path actually taken (an acq held on a
        # sibling path is not held there, so discharging it is harmless)
        return _LeakState(a.held | b.held, a.binds | b.binds)

    # -- transfer ------------------------------------------------------------

    def transfer(self, state: _LeakState, block: Block):
        stmt = block.stmt
        if stmt is None:
            return state, state
        held, binds = set(state.held), set(state.binds)

        # releases apply on BOTH edges (a release that raises has at
        # least reached the pool; pool-internal errors are fatal anyway)
        released = self._released_receivers(stmt)
        if released is ALL_RECEIVERS:
            held.clear()
        elif released:
            held = {a for a in held
                    if self.acqs[a][1] not in released}
        binds = {(v, a) for (v, a) in binds if a in held}
        exc_state = _LeakState(frozenset(held), frozenset(binds))

        # ownership transfer: the bound result is STORED beyond the
        # frame (returned, yielded, or put into a container/attribute)
        escaped = self._escaped_vars(stmt, {v for v, _ in binds})
        if escaped:
            gone = {a for (v, a) in binds if v in escaped}
            held -= gone
            binds = {(v, a) for (v, a) in binds if a in held}
            exc_state = _LeakState(frozenset(held), frozenset(binds))

        # acquisitions take effect on the NORMAL edge only (the raising
        # acquisition never handed pages out); an acquisition sitting
        # DIRECTLY in an escaping position (``return mgr.allocate(...)``,
        # ``sink.append(mgr.allocate(...))``) transfers immediately
        immediate = self._immediately_escaping_calls(stmt)
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and id(node) in self.acqs \
                    and id(node) not in immediate:
                held.add(id(node))
                var = self.acqs[id(node)][2]
                if var is not None:
                    binds = {(v, a) for (v, a) in binds if v != var}
                    binds.add((var, id(node)))
        return _LeakState(frozenset(held), frozenset(binds)), exc_state

    def _immediately_escaping_calls(self, stmt) -> Set[int]:
        subtrees = []
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)) \
                    and getattr(node, "value", None) is not None:
                subtrees.append(node.value)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _ESCAPE_METHODS:
                subtrees.extend(node.args)
                subtrees.extend(kw.value for kw in node.keywords)
            elif isinstance(node, ast.Assign) \
                    and any(isinstance(t, (ast.Attribute, ast.Subscript))
                            for t in node.targets):
                subtrees.append(node.value)
        out: Set[int] = set()
        for sub in subtrees:
            for n in ast.walk(sub):
                if isinstance(n, ast.Call) and id(n) in self.acqs:
                    out.add(id(n))
        return out

    def _released_receivers(self, stmt):
        """Receivers freed by this statement; ALL_RECEIVERS when a
        resolved callee's summary says it releases (conservative: that
        helper may free any pool handed to it)."""
        out: Set[str] = set()
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _RELEASE_METHODS:
                recv = dotted(node.func.value)
                if recv is not None:
                    out.add(recv)
            elif id(node) in self._releasing_calls:
                return ALL_RECEIVERS
        return out

    def _escaped_vars(self, stmt, bound: Set[str]) -> Set[str]:
        if not bound:
            return set()
        out: Set[str] = set()

        def names_in(sub) -> Set[str]:
            return {n.id for n in ast.walk(sub)
                    if isinstance(n, ast.Name) and n.id in bound}

        for node in ast.walk(stmt):
            if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)) \
                    and getattr(node, "value", None) is not None:
                out |= names_in(node.value)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _ESCAPE_METHODS:
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    out |= names_in(arg)
            elif isinstance(node, ast.Assign):
                if any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in node.targets):
                    out |= names_in(node.value)
        return out

    _releasing_calls: FrozenSet[int] = frozenset()


ALL_RECEIVERS = object()


class PageLeakRule:
    """Every ``allocate``/``grow_to`` acquisition in ``kvcache/`` +
    ``inference/`` reaches ``free``/``truncate_pages``/ownership
    transfer on ALL paths out of the acquiring function, exception
    edges included."""

    id = "page-leak"
    protects = ("every paged acquisition (allocate/grow_to) in kvcache/"
                "+inference/ reaches free/truncate_pages or an ownership"
                " transfer on EVERY path out of the acquiring function "
                "— exception edges included (the int8-page safety net)")
    example = ("pages = self.mgr.allocate(rid, n)\n"
               "self.cache.record(rid)   # raises -> pages leak\n"
               "picked.append(pages)")

    SCOPE = ("paddle_tpu/kvcache/", "paddle_tpu/inference/")

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        index = project.index
        summaries, _ = _shared(project)
        for mod in project.iter_modules(self.SCOPE):
            mi = index.by_rel[mod.rel]
            for fi in mi.functions:
                out.extend(self._check_function(project, mi, fi,
                                                summaries))
        return out

    # -- per-function --------------------------------------------------------

    def _check_function(self, project, mi, fi, summaries) -> List[Finding]:
        local_pools = self._local_pools(fi)
        acqs: Dict[int, Tuple[ast.Call, str, Optional[str]]] = {}
        for node in fi.own_nodes():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _ACQUIRE_METHODS):
                continue
            recv = dotted(node.func.value)
            if recv is None or recv == "self" or recv == "cls":
                continue                    # the pool's own bookkeeping
            if recv.split(".")[0] in local_pools:
                continue                    # frame-local pool: dies here
            acqs[id(node)] = (node, recv, None)
        if not acqs:
            return []
        # bind acquisition results to their target variable
        for node in fi.own_nodes():
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and id(node.value) in acqs:
                targets = [t.id for t in node.targets
                           if isinstance(t, ast.Name)]
                if targets:
                    call, recv, _ = acqs[id(node.value)]
                    acqs[id(node.value)] = (call, recv, targets[0])
        analysis = _LeakAnalysis(acqs)
        analysis._releasing_calls = self._releasing_calls(fi, summaries)
        cfg = _cfg_for(project, fi)
        states = solve_forward(cfg, analysis)
        findings: List[Finding] = []
        seen: Set[int] = set()
        for exit_block, via in ((cfg.exc_exit, "an exception path"),
                                (cfg.exit, "a return path")):
            st = states.get(exit_block.bid)
            if st is None:
                continue
            for acq in sorted(st.held,
                              key=lambda a: acqs[a][0].lineno):
                if acq in seen:
                    continue
                seen.add(acq)
                call, recv, _ = acqs[acq]
                findings.append(Finding(
                    fi.module.rel, call.lineno, self.id,
                    f"pages acquired by {recv}.{call.func.attr}() in "
                    f"'{fi.qualname}' can leave the function on {via} "
                    "without free/truncate_pages or an ownership "
                    "transfer — a leaked page never returns to the "
                    "pool (exception edges count)",
                    symbol=f"{fi.qualname}:{recv}.{call.func.attr}"))
        return findings

    @staticmethod
    def _local_pools(fi) -> Set[str]:
        """Names bound to a pool CONSTRUCTED in this frame — its pages
        die with the object, so holding them is not a leak."""
        out: Set[str] = set()
        for node in fi.own_nodes():
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                d = dotted(node.value.func) or ""
                if d.split(".")[-1].endswith("Manager"):
                    out |= {t.id for t in node.targets
                            if isinstance(t, ast.Name)}
        return out

    def _releasing_calls(self, fi, summaries) -> FrozenSet[int]:
        """Call nodes in ``fi`` that resolve to a helper whose summary
        releases pages (the interprocedural call-site transfer)."""
        index = summaries.index
        mi = index.by_rel[fi.module.rel]
        out: Set[int] = set()
        for node in fi.own_nodes():
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _RELEASE_METHODS:
                continue                    # direct release, handled inline
            callee = index.resolve_call(fi, node)
            if callee is not None and summaries.releases(callee):
                out.add(id(node))
        return out


# ---------------------------------------------------------------------------
# Rule family 6: dtype flow
# ---------------------------------------------------------------------------

_DTYPE_TAILS = {
    "int8": "int8", "int16": "int16", "int32": "int32", "int64": "int64",
    "uint8": "uint8", "bfloat16": "bfloat16", "float16": "float16",
    "float32": "float32", "float64": "float64", "bool_": "bool",
}
_FLOATS = {"bfloat16": 16, "float16": 16, "float32": 32, "float64": 64}
_INTS = {"int8", "int16", "int32", "int64", "uint8"}
_CONTRACTIONS = {"einsum", "dot", "matmul", "tensordot", "dot_general"}
_DTYPE_FACTORIES = {"zeros", "ones", "full", "empty", "arange", "asarray",
                    "array", "zeros_like", "ones_like", "full_like",
                    "normal", "uniform"}

TOP = None          # unknown dtype
WEAK = "weak"       # python scalar literal: weak-typed, never flags


def _dtype_token(node) -> Optional[str]:
    """jnp.float32 / np.int8 / "float32" -> canonical dtype name."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return _DTYPE_TAILS.get(node.value)
    d = dotted(node)
    if d is not None:
        return _DTYPE_TAILS.get(d.split(".")[-1])
    return None


#: integer widths for promotion; equal-width signed/unsigned mixes
#: (int8 x uint8 really promotes to int16) fall to TOP — an unknown
#: dtype can only lose recall, a wrong one mints false findings
_INT_RANK = {"int8": 8, "uint8": 8, "int16": 16, "int32": 32,
             "int64": 64}


def _promote(a: str, b: str) -> Optional[str]:
    if a == b:
        return a
    if a in _FLOATS and b in _FLOATS:
        return a if _FLOATS[a] >= _FLOATS[b] else b
    if a in _FLOATS:
        return a
    if b in _FLOATS:
        return b
    ra, rb = _INT_RANK.get(a), _INT_RANK.get(b)
    if ra is None or rb is None or ra == rb:
        return TOP
    return a if ra > rb else b


def _is_narrowing_pair(a: str, b: str) -> bool:
    """True when mixing ``a``/``b`` silently widens a narrow value
    (bf16/f16/int8...) into f32/f64 — the promotion this family exists
    to flag."""
    wide = {"float32", "float64"}
    narrow = set(_INTS) | {"bfloat16", "float16"}
    return (a in wide and b in narrow) or (b in wide and a in narrow)


class _DtypeInfo:
    __slots__ = ("dt", "dequant", "explicit")

    def __init__(self, dt=TOP, dequant=False, explicit=False):
        self.dt = dt
        self.dequant = dequant
        self.explicit = explicit


def _expr_dtype(node, env: Dict[str, Tuple[Optional[str], bool]],
                summaries: Optional[Summaries], fi, sink: Optional[list]
                ) -> Tuple[Optional[str], bool, bool]:
    """(dtype, dequant-without-scale, explicit-cast) of ``node`` under
    ``env``. ``sink`` collects (node, kind, detail) findings when given
    (the post-fixpoint reporting pass); pass None while solving."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, (int, float, complex)) \
                and not isinstance(node.value, bool):
            return WEAK, False, False
        return TOP, False, False
    if isinstance(node, ast.Name):
        dt, deq = env.get(node.id, (TOP, False))
        return dt, deq, False
    if isinstance(node, ast.Call):
        return _call_dtype(node, env, summaries, fi, sink)
    if isinstance(node, ast.BinOp):
        l = _expr_dtype(node.left, env, summaries, fi, sink)
        r = _expr_dtype(node.right, env, summaries, fi, sink)
        if isinstance(node.op, ast.MatMult):
            _contraction_check(node, [(node.left, l), (node.right, r)],
                               False, sink, fi)
        elif isinstance(node.op, (ast.Add, ast.Sub, ast.Div, ast.Mod,
                                  ast.Pow)):
            _promotion_check(node, (node.left, l), (node.right, r),
                             sink, fi)
        dts = [x[0] for x in (l, r) if x[0] not in (TOP, WEAK)]
        dt = dts[0] if len(dts) == 1 else (
            _promote(dts[0], dts[1]) if len(dts) == 2 else TOP)
        dequant = (l[1] or r[1]) and not isinstance(node.op, ast.Mult)
        return dt, dequant, False
    if isinstance(node, ast.UnaryOp):
        return _expr_dtype(node.operand, env, summaries, fi, sink)
    if isinstance(node, (ast.IfExp,)):
        b = _expr_dtype(node.body, env, summaries, fi, sink)
        o = _expr_dtype(node.orelse, env, summaries, fi, sink)
        if b[0] == o[0]:
            return b[0], b[1] or o[1], False
        return TOP, False, False
    return TOP, False, False


def _call_dtype(node: ast.Call, env, summaries, fi, sink):
    func = node.func
    d = dotted(func)
    tail = d.split(".")[-1] if d else (
        func.attr if isinstance(func, ast.Attribute) else None)

    if isinstance(func, ast.Attribute) and func.attr == "astype" \
            and node.args:
        base = _expr_dtype(func.value, env, summaries, fi, sink)
        dt = _dtype_token(node.args[0])
        if dt is None:
            return TOP, False, True         # .astype(x.dtype): explicit
        dequant = base[0] in _INTS and dt in _FLOATS
        return dt, dequant, True

    if tail in _CONTRACTIONS:
        operands = node.args[1:] if tail == "einsum" else node.args[:2]
        infos = [(op, _expr_dtype(op, env, summaries, fi, sink))
                 for op in operands]
        has_pref = any(kw.arg == "preferred_element_type"
                       for kw in node.keywords)
        _contraction_check(node, infos, has_pref, sink, fi)
        dts = [i[1][0] for i in infos if i[1][0] not in (TOP, WEAK)]
        dt = dts[0] if dts and all(x == dts[0] for x in dts) else TOP
        return dt, False, False

    if tail in _DTYPE_FACTORIES:
        for kw in node.keywords:
            if kw.arg == "dtype":
                dt = _dtype_token(kw.value)
                if dt is not None:
                    return dt, False, True
        for arg in node.args:
            dt = _dtype_token(arg)
            if dt is not None:
                return dt, False, True
        if tail in ("asarray", "array", "zeros_like", "ones_like",
                    "full_like") and node.args:
            return _expr_dtype(node.args[0], env, summaries, fi, sink)
        return TOP, False, False

    # dtype constructor call: jnp.float32(x)
    dt = _dtype_token(func)
    if dt is not None:
        return dt, False, True

    # interprocedural: resolved callee with a provable return dtype
    if summaries is not None and fi is not None:
        callee = summaries.index.resolve_call(fi, node)
        if callee is not None:
            rdt = summaries.return_dtype(callee)
            if rdt is not None:
                return rdt, False, False
    return TOP, False, False


def _contraction_check(node, infos, has_pref, sink, fi):
    if sink is None or has_pref:
        return
    for op_node, (dt, dequant, _x) in infos:
        if dequant:
            sink.append((node, "dequant",
                         "an int8-origin value dequantized without a "
                         "scale multiply reaches this contraction"))
            break
    known = [(op_node, dt, expl) for op_node, (dt, _dq, expl) in infos
             if dt not in (TOP, WEAK)]
    if len(known) >= 2:
        dts = {dt for _, dt, _ in known}
        if len(dts) > 1 and not any(expl for _, _, expl in known):
            a, b = sorted(dts)[:2]
            sink.append((node, "mixed",
                         f"mixed-dtype contraction ({a} x {b}) — the "
                         "accumulator/output dtype is inherited, not "
                         "chosen; cast explicitly or pass "
                         "preferred_element_type"))


def _promotion_check(node, left, right, sink, fi):
    if sink is None:
        return
    (ln, (ldt, _ld, lex)), (rn, (rdt, _rd, rex)) = (left, right)
    if ldt in (TOP, WEAK) or rdt in (TOP, WEAK) or lex or rex:
        return
    if _is_narrowing_pair(ldt, rdt):
        sink.append((node, "promote",
                     f"silent promotion ({ldt} {type(node.op).__name__}"
                     f" {rdt}) widens a bf16/int8 chain to f32 — "
                     "2x activation bytes unless this is explicit"))


class _DtypeAnalysis:
    def __init__(self, summaries, fi):
        self.summaries = summaries
        self.fi = fi

    def initial(self):
        return frozenset()                   # env as frozenset of items

    def join(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        if a == b:
            return a
        da, db = dict(a), dict(b)
        out = {}
        for k in da.keys() & db.keys():
            va, vb = da[k], db[k]
            if va[0] == vb[0]:
                out[k] = (va[0], va[1] and vb[1])
        return frozenset(out.items())

    def transfer(self, state, block, sink=None):
        stmt = block.stmt
        if stmt is None:
            return state, state
        env = dict(state)
        if isinstance(stmt, ast.Assign):
            val = _expr_dtype(stmt.value, env, self.summaries, self.fi,
                              sink)
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    env[t.id] = (val[0], val[1])
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(t := stmt.target, ast.Name):
                cur = env.get(t.id, (TOP, False))
                synth = ast.BinOp(left=ast.Name(id=t.id, ctx=ast.Load()),
                                  op=stmt.op, right=stmt.value)
                ast.copy_location(synth, stmt)
                ast.fix_missing_locations(synth)
                env_l = dict(env)
                env_l[t.id] = cur
                val = _expr_dtype(synth, env_l, self.summaries, self.fi,
                                  sink)
                env[t.id] = (val[0], val[1])
        elif isinstance(stmt, (ast.Expr, ast.Return)):
            v = stmt.value
            if v is not None:
                _expr_dtype(v, env, self.summaries, self.fi, sink)
        elif isinstance(stmt, ast.expr):     # test blocks
            _expr_dtype(stmt, env, self.summaries, self.fi, sink)
        out = frozenset(env.items())
        return out, out


class DtypeFlowRule:
    """Propagate a dtype lattice through functions reachable from
    jit/pallas roots in ``ops/`` + ``models/``; flag silent f32
    promotion in bf16/int8 chains, dequant-without-scale, and
    mixed-dtype contractions."""

    id = "dtype-flow"
    protects = ("traced code in ops/+models/ never silently promotes a "
                "bf16/int8 chain to f32, never contracts mixed dtypes "
                "implicitly, and never feeds a dequantized int8 value "
                "to a contraction without its scale — dtype is a "
                "CHOICE, made with .astype/preferred_element_type")
    example = ("scores = jnp.einsum('ij,jk->ik', x_bf16, w_f32)"
               "  # mixed contraction")

    SCOPE = ("paddle_tpu/ops/", "paddle_tpu/models/")

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        summaries, _ = _shared(project)
        seen_nodes: Set[int] = set()
        for fi in project.index.traced_functions():
            if not fi.module.rel.startswith(self.SCOPE):
                continue
            if not isinstance(fi.node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.Lambda)):
                continue
            analysis = _DtypeAnalysis(summaries, fi)
            cfg = _cfg_for(project, fi)
            states = solve_forward(cfg, analysis)
            sink: List[Tuple[ast.AST, str, str]] = []
            for block in cfg.blocks:
                st = states.get(block.bid)
                if st is not None and block.stmt is not None:
                    analysis.transfer(st, block, sink=sink)
            for node, kind, detail in sink:
                if id(node) in seen_nodes:
                    continue
                seen_nodes.add(id(node))
                out.append(Finding(
                    fi.module.rel, node.lineno, self.id,
                    f"{detail} (inside traced function "
                    f"'{fi.qualname}')",
                    symbol=f"{fi.qualname}:{kind}"))
        return out


# ---------------------------------------------------------------------------
# Rule family 7: cache-key completeness
# ---------------------------------------------------------------------------

class CacheKeyRule:
    """Any trace-time external input (``flag_value``/``os.environ``)
    read by a program a compile cache stores must be derivable from the
    cache's key expression — generalizing PR 8's stale-program defect
    (a flag flip silently keeps serving the old program; the runtime
    RecompileDetector cannot even see it)."""

    id = "cache-key"
    protects = ("every trace-time external input (flag_value/os."
                "environ) read by a cached-compile body is derivable "
                "from that cache's key expression — a set_flags flip "
                "RETRACES as a counted recompile instead of silently "
                "serving the stale program (PR 8's defect, as a rule)")
    example = ("key = (bucket,)                    # no flag in the key\n"
               "self._compiled[key] = self._build()  # body reads "
               "flag_value('f')")

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        index = project.index
        summaries, _ = _shared(project)
        roots_by_fn = self._roots_by_enclosing(index)
        for mod in project.iter_modules(("paddle_tpu/",)):
            mi = index.by_rel[mod.rel]
            for fi in mi.functions:
                out.extend(self._check_function(index, summaries, mi, fi,
                                                roots_by_fn))
        return out

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _roots_by_enclosing(index) -> Dict[str, List[FunctionInfo]]:
        """jit roots grouped by the qualname prefix of the function that
        builds them (module-scoped)."""
        out: Dict[str, List[FunctionInfo]] = {}
        for root in index.traced_roots():
            out.setdefault(root.module.rel, []).append(root)
        return out

    def _check_function(self, index, summaries, mi, fi, roots_by_fn
                        ) -> List[Finding]:
        stores = self._cache_stores(fi)
        if not stores:
            return []
        out: List[Finding] = []
        for assign, target_name in stores:
            builder = index.resolve_call(fi, assign.value)
            if builder is None:
                continue                    # resolution gap: conservative
            traced = self._traced_flags(index, summaries, builder,
                                        roots_by_fn)
            if not traced:
                continue
            key_flags = self._key_flags(index, summaries, mi, fi, assign)
            for flag in sorted(traced - key_flags):
                out.append(Finding(
                    fi.module.rel, assign.lineno, self.id,
                    f"compile cache '{target_name}' in '{fi.qualname}' "
                    f"stores a traced program that reads "
                    f"flag_value({flag!r}) but the cache key never "
                    "derives from it — a set_flags flip keeps serving "
                    "the stale program (key it like _prefill_flags, or "
                    "baseline with the reason staleness is safe)",
                    symbol=f"{fi.qualname}:{target_name}:{flag}"))
        return out

    def _cache_stores(self, fi) -> List[Tuple[ast.Assign, str]]:
        """Assignments that store a BUILT program into cache state: a
        subscript store (dict cache) or an attribute store that the same
        function guards with an is-None/!=/not-in check (one-time
        unguarded builds are trace-host-state's problem, not a cache)."""
        guards: Set[str] = set()
        for node in fi.own_nodes():
            if isinstance(node, ast.Compare) and node.ops:
                if isinstance(node.ops[0], (ast.NotIn, ast.In)):
                    d = dotted(node.comparators[0])
                    if d is not None:
                        guards.add(d)
                elif isinstance(node.ops[0], (ast.Is, ast.IsNot, ast.Eq,
                                              ast.NotEq)):
                    for side in (node.left, node.comparators[0]):
                        d = dotted(side)
                        if d is not None:
                            guards.add(d)
        out: List[Tuple[ast.Assign, str]] = []
        for node in fi.own_nodes():
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and len(node.targets) == 1):
                continue
            t = node.targets[0]
            if isinstance(t, ast.Subscript):
                d = dotted(t.value)
                if d is not None and d in guards:
                    out.append((node, d))
            elif isinstance(t, ast.Attribute):
                d = dotted(t)
                if d is not None and d in guards:
                    out.append((node, d))
        return out

    def _traced_flags(self, index, summaries, builder, roots_by_fn
                      ) -> FrozenSet[str]:
        """Flags read under trace by the programs ``builder`` builds:
        roots enclosed in the builder (or in builders it calls),
        closed over the traced call graph."""
        visited: Set[int] = set()
        queue = [builder]
        building: List[FunctionInfo] = []
        while queue:
            fn = queue.pop()
            if id(fn.node) in visited or len(visited) > 200:
                continue
            visited.add(id(fn.node))
            building.append(fn)
            queue.extend(index._callees(fn))
        roots: List[FunctionInfo] = []
        for fn in building:
            for root in roots_by_fn.get(fn.module.rel, ()):
                if root.qualname.startswith(fn.qualname + ".<locals>") \
                        or root.node is fn.node:
                    roots.append(root)
        flags: Set[str] = set()
        for root in roots:
            flags |= summaries.flags_read(root)
        return frozenset(flags)

    def _key_flags(self, index, summaries, mi, fi, assign
                   ) -> FrozenSet[str]:
        """Flags derivable from the cache's key side: literal reads in
        the enclosing function plus the transitive reads of every
        helper it calls OUTSIDE the builder statement itself
        (e.g. ``_prefill_flags()`` in the key tuple or the freshness
        compare)."""
        skip = {id(n) for n in ast.walk(assign)}
        flags: Set[str] = set(direct_flag_reads(fi))
        for node in fi.own_nodes():
            if not isinstance(node, ast.Call) or id(node) in skip:
                continue
            callee = index.resolve_call(fi, node)
            if callee is not None:
                flags |= summaries.flags_read(callee)
        return frozenset(flags)


DATAFLOW_RULES = (PageLeakRule(), DtypeFlowRule(), CacheKeyRule())
