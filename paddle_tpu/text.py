"""``paddle_tpu.text`` — sequence labeling decode utilities.

Parity with python/paddle/text/ of the reference, whose live surface is
``ViterbiDecoder`` / ``viterbi_decode`` (the dataset wrappers there need
network downloads — scoped out under this environment's zero-egress
constraint, documented in SURVEY §8).

Viterbi max-sum decode as one ``lax.scan`` over time (forward scores +
backpointers) and a reversed scan for the backtrack — the same
compiled-loop shape as beam search's gather_tree (nn/decode.py), built
TPU-first instead of the reference's phi viterbi_decode CUDA kernel
(paddle/phi/kernels/gpu/viterbi_decode_kernel.cu:§0).

BOS/EOS convention with ``include_bos_eos_tag=True`` (reference
semantics): the tag set includes BOS = C-2 and EOS = C-1; step 0 adds
``transitions[BOS, :]`` and the final step adds ``transitions[:, EOS]``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .core.tensor import Tensor
from .nn import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _t(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """Max-sum decode of tag sequences.

    Args:
        potentials: (B, T, C) unary emission scores.
        transition_params: (C, C) transition scores [from, to].
        lengths: (B,) actual sequence lengths.
        include_bos_eos_tag: treat tags C-2/C-1 as BOS/EOS (see module
            docstring).

    Returns:
        (scores (B,), paths (B, T)) — static shape (T = potentials' time
        axis, jit-friendly); positions at or past a sequence's length
        hold 0. (The reference trims to max(lengths); a data-dependent
        width would force a host sync under jit.)
    """
    emis = _t(potentials).astype(jnp.float32)
    trans = _t(transition_params).astype(jnp.float32)
    lens = _t(lengths).astype(jnp.int32)
    B, T, C = emis.shape

    alpha = emis[:, 0, :]
    if include_bos_eos_tag:
        alpha = alpha + trans[C - 2, :][None, :]

    def step(carry, inp):
        alpha, t_idx = carry
        emis_t = inp                                   # (B, C)
        # scores[b, i, j] = alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)         # (B, C)
        best_score = jnp.max(scores, axis=1) + emis_t  # (B, C)
        # positions at or past each sequence's end keep alpha frozen
        active = (t_idx < lens)[:, None]
        new_alpha = jnp.where(active, best_score, alpha)
        bp = jnp.where(active, best_prev,
                       jnp.arange(C, dtype=best_prev.dtype)[None, :])
        return (new_alpha, t_idx + 1), bp

    (alpha, _), bps = jax.lax.scan(step, (alpha, jnp.asarray(1, jnp.int32)),
                                   jnp.moveaxis(emis[:, 1:, :], 1, 0))
    # bps: (T-1, B, C); bps[t][b, j] = best tag at time t for tag j at t+1

    final = alpha
    if include_bos_eos_tag:
        final = final + trans[:, C - 1][None, :]
    scores = jnp.max(final, axis=-1)
    last_tag = jnp.argmax(final, axis=-1).astype(jnp.int32)   # (B,)

    def back(carry, bp):
        tag, t_idx = carry
        # bp is for transition t_idx -> t_idx+1 (time index of bp row)
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        # only backtrack where t_idx+1 < len (the tag at len-1 is last_tag)
        use = (t_idx + 1) < lens
        new_tag = jnp.where(use, prev.astype(jnp.int32), tag)
        return (new_tag, t_idx - 1), new_tag

    t0 = jnp.asarray(T - 2, jnp.int32)
    (_, _), rev_tags = jax.lax.scan(back, (last_tag, t0), bps,
                                    reverse=True)
    # rev_tags[t] = tag at time t (t in [0, T-2]); append the last tag
    tags = jnp.concatenate([jnp.moveaxis(rev_tags, 0, 1),
                            last_tag[:, None]], axis=1)       # (B, T)
    # the tag at position len-1 must be last_tag, not the scan's carry at
    # that slot — splice it in, zero everything past the length
    pos = jnp.arange(T, dtype=jnp.int32)[None, :]
    tags = jnp.where(pos == (lens[:, None] - 1), last_tag[:, None], tags)
    tags = jnp.where(pos < lens[:, None], tags, 0)
    return Tensor(scores), Tensor(tags)


class ViterbiDecoder(Layer):
    """Layer form (reference paddle.text.ViterbiDecoder): holds the
    transition matrix; forward(potentials, lengths) -> (scores, paths)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
