"""Controllers: build the job, deploy the pod, watch, restart.

Reference: python/paddle/distributed/launch/controllers/{controller,
collective,watcher}.py + fleet/elastic/manager.py (SURVEY.md §2.6, §3.6).
Elastic recovery is restart-based: on a failed container, stop the local
pod, re-rendezvous (new generation), re-deploy — state continuity comes from
user checkpoints, exactly as in the reference.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Dict, List

from .context import Context, free_ports
from .job import Job, Status, build_trainer_env
from .master import make_master

logger = logging.getLogger("paddle_tpu.launch")


class CollectiveController:
    """One process per local device/host; PADDLE_* env injection."""

    def __init__(self, ctx: Context):
        self.ctx = ctx
        self.job = Job(job_id=ctx.args.job_id)
        # Elastic jobs use a short rendezvous timeout so a node stuck in a
        # stale generation re-reads the counter and retries promptly.
        timeout_s = (float(ctx.args.elastic_timeout)
                     if ctx.args.elastic_level >= 1 else 120.0)
        self.master = make_master(ctx.args.master, ctx.node_ip,
                                  ctx.args.rank, ctx.args.job_id,
                                  ctx.is_multi_node, timeout_s=timeout_s)
        self.node_rank = ctx.args.rank
        self.gen = self.master.get_gen()

    # -- job construction ---------------------------------------------------

    def build_job(self) -> None:
        ctx = self.ctx
        nproc = ctx.local_nproc
        ports = free_ports(nproc)
        local_eps = [f"{ctx.node_ip}:{p}" for p in ports]
        self.node_rank, peers = self.master.sync_peers(
            local_eps, ctx.args.rank, ctx.nnodes_min, ctx.nnodes_max,
            gen=self.gen)
        all_eps: List[str] = [ep for node in peers for ep in node]
        rank_offset = sum(len(peers[i]) for i in range(self.node_rank))
        world = len(all_eps)

        # Trainer rendezvous endpoint: worker 0's endpoint (its port is
        # free — reserved above — and on the master node for multi-node).
        master_ep = all_eps[0]

        script = ctx.args.training_script
        if script.endswith(".py"):
            entry_prefix = [sys.executable, "-u", script]
        else:
            entry_prefix = [script]

        devices = (ctx.args.devices.split(",")
                   if ctx.args.devices else [str(i) for i in range(nproc)])
        log_dir = ctx.args.log_dir
        self.job.pod.containers = []
        for i in range(nproc):
            rank = rank_offset + i
            env = build_trainer_env(
                rank, world, i, nproc, local_eps[i], all_eps, master_ep,
                node_rank=self.node_rank, job_id=ctx.args.job_id,
                restart_count=self.job.pod.restart_count,
                device=devices[i] if i < len(devices) else None)
            log_path = os.path.join(log_dir, f"workerlog.{rank}")
            self.job.pod.add_container(
                entry_prefix + ctx.args.training_script_args, env,
                log_path=log_path, rank=rank)

    # -- run loop -----------------------------------------------------------

    RESTART = "restart"

    def _fail(self, reason: str, **detail) -> Dict:
        """Record the structured reason the job is giving up: merged onto
        any container-level failure, logged as one JSON line, and written
        to ``<log_dir>/failure.json`` for supervisors to consume."""
        info: Dict = {"job_id": self.job.id, "node_rank": self.node_rank,
                      "gen": self.gen, "reason": reason}
        info.update(detail)
        if self.job.pod.failure:
            container = dict(self.job.pod.failure)
            container.pop("log_tail", None)  # keep the json line readable
            info["container"] = container
        self.job.failure = info
        logger.error("job failed: %s", json.dumps(info, default=str))
        log_dir = getattr(self.ctx.args, "log_dir", None)
        if log_dir:
            try:
                os.makedirs(log_dir, exist_ok=True)
                tmp = os.path.join(log_dir, "failure.json.tmp")
                with open(tmp, "w") as f:
                    json.dump(info, f, default=str, indent=2)
                os.replace(tmp, os.path.join(log_dir, "failure.json"))
            except OSError as e:
                logger.warning("could not write failure.json: %s", e)
        return info

    def _safe_get_gen(self) -> int:
        """Poll the generation counter; master loss reads as 'no change'
        (the hosting node may legitimately finish first)."""
        try:
            return self.master.get_gen()
        except Exception:
            return self.gen

    def run(self) -> int:
        ctx = self.ctx
        max_restart = ctx.args.max_restart if ctx.args.elastic_level >= 1 else 0
        restart_budget = max(max_restart, 1)
        while True:
            # Always rendezvous at the *latest* generation: concurrent bumps
            # from several failing nodes collapse to one namespace here.
            self.gen = max(self.gen, self._safe_get_gen())
            try:
                self.build_job()
            except (TimeoutError, RuntimeError, ConnectionError) as e:
                logger.error("rendezvous failed (gen %d): %s", self.gen, e)
                if max_restart == 0 or \
                        self.job.pod.restart_count >= restart_budget:
                    self._fail("rendezvous_failed", error=str(e))
                    self.master.close()
                    return 1
                # A failed rendezvous poisons its generation (half-written
                # counters/endpoints): bump so every node retries in a fresh
                # namespace — peers already deployed notice via their watch.
                try:
                    self.gen = self.master.bump_gen()
                except Exception:
                    pass
                self.job.pod.reset()
                time.sleep(1)
                continue
            logger.info("deploy pod: %d containers, node_rank=%d gen=%d",
                        len(self.job.pod.containers), self.node_rank, self.gen)
            self.job.pod.deploy()
            status = self.watch()
            if status == Status.COMPLETED:
                self.master.close()
                return 0
            if status == Status.FAILED:
                # local failure: report, and (elastic) tell peers via gen bump
                failed = [c for c in self.job.pod.containers
                          if c.status() == Status.FAILED]
                for c in failed:
                    logger.error("rank %d failed (exit %s); last log:\n%s",
                                 c.rank, c.exit_code, c.logs(tail=2048))
                # in-place peer restarts and full redeploys draw on the
                # same budget: --max_restart bounds total recovery attempts
                spent = (self.job.pod.restart_count +
                         self.job.pod.container_restarts)
                over_budget = spent >= max_restart
                if over_budget:
                    reason = (self.job.pod.failure or {}).get(
                        "reason", "container_failed")
                    self._fail(reason)
                if max_restart > 0:
                    try:
                        # signal peers even when leaving for good (scale-in)
                        self.master.bump_gen()
                    except Exception:
                        pass
                if over_budget:
                    self.job.pod.stop(force=True)
                    self.master.close()
                    return 1
            else:  # RESTART requested by a peer's gen bump
                if self.job.pod.restart_count >= restart_budget:
                    self._fail("pod_restart_budget_exhausted",
                               restart_budget=restart_budget)
                    self.job.pod.stop(force=True)
                    self.master.close()
                    return 1
            logger.warning("elastic restart %d/%d",
                           self.job.pod.restart_count + 1, restart_budget)
            self.job.pod.reset()     # bumps restart_count
            time.sleep(min(ctx.args.elastic_timeout, 3))

    def watch(self, poll_interval: float = 0.2) -> str:
        """Reference watcher loop: poll container liveness/exit codes.

        Elastic single-node: dead peers are restarted *in place* with
        exponential backoff (``Pod.restart_failed``) up to the
        ``max_restart`` budget — no re-rendezvous needed since endpoints
        are unchanged; past the budget the job fails with a structured
        reason. Multi-node elastic: also poll the store's generation
        counter — a peer node bumping it means the whole job is
        re-forming, so stop the local pod and re-rendezvous (reference:
        etcd membership watch, SURVEY §3.6).
        """
        ctx = self.ctx
        pod = self.job.pod
        elastic = ctx.args.elastic_level >= 1 and ctx.is_multi_node
        max_restart = (ctx.args.max_restart
                       if ctx.args.elastic_level >= 1 else 0)
        # In-place peer restart keeps every endpoint/env intact, so it is
        # only sound when there is no cross-node generation to re-form.
        in_place = max_restart > 0 and not ctx.is_multi_node
        last_gen_check = time.monotonic()
        while True:
            s = pod.status()
            if s == Status.COMPLETED:
                return s
            if s == Status.FAILED:
                if in_place and pod.restart_failed(max_restart):
                    logger.warning(
                        "restarted dead peers in place (%d/%d)",
                        pod.container_restarts, max_restart)
                    continue
                # budget spent (restart_failed recorded the reason) or
                # restarts disabled: tear down remaining live containers.
                # Only record here — run() may still recover via a full
                # elastic redeploy, and failure.json is a give-up artifact.
                if pod.failure is None:
                    pod.record_failure("container_failed")
                pod.stop(force=False)
                return s
            if elastic and time.monotonic() - last_gen_check >= 1.0:
                last_gen_check = time.monotonic()
                if self._safe_get_gen() != self.gen:
                    logger.warning("peer requested restart (gen changed)")
                    pod.stop(force=False)
                    return self.RESTART
            time.sleep(poll_interval)

    def stop(self):
        self.job.pod.stop(force=True)
        self.master.close()
