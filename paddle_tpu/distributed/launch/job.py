"""Job / Pod / Container process model.

Reference: python/paddle/distributed/launch/job/{job,pod,container,status}.py
(SURVEY.md §2.6, §3.1). A Container is one trainer subprocess with its
``PADDLE_*`` env and a per-rank log file (``workerlog.N`` — the primary
multi-process debugging surface, SURVEY §5.5).
"""

from __future__ import annotations

import os
import signal
import subprocess
import time
from typing import Dict, List, Optional


def build_trainer_env(rank: int, world: int, local_rank: int, local_size: int,
                      endpoint: str, all_endpoints: List[str], master: str,
                      node_rank: int = 0, job_id: str = "default",
                      restart_count: int = 0,
                      device: Optional[str] = None) -> Dict[str, str]:
    """The PADDLE_* env contract every trainer process receives — single
    source shared by the launch CLI and ``spawn`` so the two cannot drift."""
    return {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_LOCAL_SIZE": str(local_size),
        "PADDLE_NODE_RANK": str(node_rank),
        "PADDLE_CURRENT_ENDPOINT": endpoint,
        "PADDLE_TRAINER_ENDPOINTS": ",".join(all_endpoints),
        "PADDLE_MASTER": master,
        "PADDLE_JOB_ID": job_id,
        "PADDLE_RESTART_COUNT": str(restart_count),
        "FLAGS_selected_devices": device if device is not None else str(local_rank),
    }


class Status:
    UNINIT = "uninit"
    READY = "ready"
    RUNNING = "running"
    FAILED = "failed"
    TERMINATING = "terminating"
    COMPLETED = "completed"


class Container:
    """One trainer subprocess + env + log redirection."""

    def __init__(self, entrypoint: List[str], env: Dict[str, str],
                 log_path: Optional[str] = None, rank: int = -1):
        self.entrypoint = entrypoint
        self.env = env
        self.log_path = log_path
        self.rank = rank
        self.proc: Optional[subprocess.Popen] = None
        self._log_fh = None

    def start(self) -> None:
        env = dict(os.environ)
        env.update(self.env)
        stdout = stderr = None
        if self.log_path:
            os.makedirs(os.path.dirname(self.log_path) or ".", exist_ok=True)
            self._log_fh = open(self.log_path, "ab", buffering=0)
            stdout = stderr = self._log_fh
        self.proc = subprocess.Popen(self.entrypoint, env=env,
                                     stdout=stdout, stderr=stderr)

    def restart(self) -> None:
        """Relaunch this container in place (same entrypoint/endpoint; env
        may have been updated, e.g. PADDLE_RESTART_COUNT). The log file is
        reopened in append mode so both generations' output survives."""
        self.terminate(force=True)
        self.proc = None
        self.start()

    @property
    def exit_code(self) -> Optional[int]:
        return self.proc.poll() if self.proc else None

    def status(self) -> str:
        if self.proc is None:
            return Status.UNINIT
        code = self.proc.poll()
        if code is None:
            return Status.RUNNING
        return Status.COMPLETED if code == 0 else Status.FAILED

    def terminate(self, force: bool = False) -> None:
        if self.proc is None or self.proc.poll() is not None:
            self._close_log()
            return
        self.proc.send_signal(signal.SIGKILL if force else signal.SIGTERM)
        try:
            self.proc.wait(timeout=8)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
        self._close_log()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        if self.proc is None:
            return None
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    def _close_log(self):
        if self._log_fh is not None:
            try:
                self._log_fh.close()
            finally:
                self._log_fh = None

    def logs(self, tail: int = 4096) -> str:
        if not self.log_path or not os.path.exists(self.log_path):
            return ""
        with open(self.log_path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - tail))
            return f.read().decode(errors="replace")


class Pod:
    """The set of local containers on this node (reference Pod)."""

    def __init__(self, name: str = ""):
        self.name = name or f"pod-{os.getpid()}"
        self.containers: List[Container] = []
        self.restart_count = 0           # full pod re-deployments
        self.container_restarts = 0      # in-place dead-peer restarts
        self.failure: Optional[Dict] = None  # structured give-up reason

    def add_container(self, entrypoint, env, log_path=None, rank=-1):
        self.containers.append(Container(entrypoint, env, log_path, rank))

    def failed_containers(self) -> List[Container]:
        return [c for c in self.containers if c.status() == Status.FAILED]

    def record_failure(self, reason: str, **detail) -> Dict:
        """Build + store the structured reason the job is giving up."""
        info: Dict = {"reason": reason, "pod": self.name,
                      "pod_restarts": self.restart_count,
                      "container_restarts": self.container_restarts}
        info.update(detail)
        failed = self.failed_containers()
        if failed:
            c = failed[0]
            info.setdefault("rank", c.rank)
            info.setdefault("exit_code", c.exit_code)
            info.setdefault("log_tail", c.logs(tail=1024))
        self.failure = info
        return info

    def restart_failed(self, max_restarts: int, backoff_base: float = 0.5,
                       backoff_cap: float = 8.0, sleep=time.sleep) -> bool:
        """Restart dead containers in place with exponential backoff.

        Returns True when the dead peers were relaunched (budget left) and
        False when the restart budget is spent — in which case a structured
        failure reason is recorded on ``self.failure``. Restarted
        containers see a bumped ``PADDLE_RESTART_COUNT`` so trainers can
        tell generations apart (e.g. to resume from a checkpoint).
        """
        failed = self.failed_containers()
        if not failed:
            return True
        if self.container_restarts >= max_restarts:
            self.record_failure("restart_budget_exhausted",
                                max_restarts=max_restarts)
            return False
        delay = min(backoff_base * (2 ** self.container_restarts),
                    backoff_cap)
        sleep(delay)
        self.container_restarts += 1
        gen = self.restart_count + self.container_restarts
        for c in failed:
            c.env["PADDLE_RESTART_COUNT"] = str(gen)
            c.restart()
        return True

    def deploy(self) -> None:
        for c in self.containers:
            c.start()

    def status(self) -> str:
        stats = [c.status() for c in self.containers]
        if any(s == Status.FAILED for s in stats):
            return Status.FAILED
        if any(s == Status.RUNNING for s in stats):
            return Status.RUNNING
        if stats and all(s == Status.COMPLETED for s in stats):
            return Status.COMPLETED
        return Status.UNINIT

    def join(self, poll_interval: float = 0.2) -> str:
        """Block until every container exits or one fails."""
        while True:
            s = self.status()
            if s in (Status.FAILED, Status.COMPLETED):
                return s
            time.sleep(poll_interval)

    def stop(self, force: bool = False) -> None:
        for c in self.containers:
            c.terminate(force=force)

    def reset(self) -> None:
        """Drop dead containers so the pod can be rebuilt for a restart.
        The recorded failure is per-generation (a recovered job must not
        carry a stale reason forward), but ``container_restarts`` is
        cumulative — in-place and full-redeploy restarts share one
        ``max_restart`` budget, never multiply it."""
        self.stop(force=True)
        self.containers = []
        self.restart_count += 1
        self.failure = None


class Job:
    def __init__(self, job_id: str = "default"):
        self.id = job_id
        self.pod = Pod()
        self.failure: Optional[Dict] = None  # structured give-up reason
