"""Pipeline-parallel training wrapper.

Rebuild of python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py
(PipelineParallel.train_batch, 1F1B / interleave schedules — SURVEY.md §2.4
PP row, §3.2 call stack).

Two execution paths:

* **Generic path (this class)** — microbatch loop over the PipelineLayer's
  stages with gradient accumulation. Semantically identical to GPipe
  fill-drain (loss/grads match 1F1B exactly; schedules differ only in memory
  and overlap). In the single-controller world every stage's ops are issued
  from one host; XLA/async dispatch overlaps them across devices when stage
  parameters are sharded onto pp submeshes.
* **Compiled scan path** — for homogeneous decoder stacks the hybrid engine
  compiles the whole pipeline into one XLA program with ppermute rotation
  (parallel/pipeline.py); used by the transformer models and the benchmark
  (models/llama.py). Three schedules: fill-drain (pipeline_spmd),
  interleaved virtual-pipeline (pipeline_spmd_interleaved), and true
  memory-scheduled 1F1B (pipeline_1f1b — hand-scheduled forward+backward
  with O(S) in-flight activations; benchmarks/bench_pipeline.py measured
  ~30x lower temp memory and ~3x faster steps than fill-drain+AD on the
  8-device CPU mesh at M=32).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ...core.tensor import Tensor
from ...nn.layer import Layer
from .pp_layers import PipelineLayer


class PipelineParallel(Layer):
    def __init__(self, layers: PipelineLayer, hcg, strategy):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        h = strategy.hybrid_configs if strategy is not None else {}
        self.micro_batch_size = int(h.get("micro_batch_size", 1))
        self.accumulate_steps = int(h.get("accumulate_steps", 1))
        self.total_loss = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    @property
    def inner_model(self):
        return self._layers

    def _split_micro(self, data):
        """Split (inputs, labels) into accumulate_steps microbatches."""
        inputs, labels = data
        n = self.accumulate_steps
        def split(t):
            if isinstance(t, (list, tuple)):
                return [split(e) for e in t]
            b = t.shape[0]
            if b % n != 0:
                raise ValueError(
                    f"batch size {b} is not divisible by accumulate_steps {n}")
            mb = b // n
            return [t[i * mb:(i + 1) * mb] for i in range(n)]
        ins = split(inputs)
        labs = split(labels)
        if isinstance(inputs, (list, tuple)):
            ins = list(zip(*ins))
        if isinstance(labels, (list, tuple)):
            labs = list(zip(*labs))
        return list(zip(ins, labs))

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """GPipe-equivalent gradient accumulation over microbatches.
        Reference: forward_backward_pipeline + 1F1B (SURVEY.md §3.2)."""
        import os
        if int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1:
            # the eager microbatch loop depends on in-process activations;
            # across OS processes it would need the eager p2p mailbox, which
            # is single-process by design (communication/p2p.py). Fail HERE
            # with the route out instead of deep inside a send().
            raise RuntimeError(
                "PipelineParallel.train_batch is a single-process "
                "(single-controller) engine; under a multi-process launcher "
                "use the compiled pipeline instead — "
                "models.llama.build_hybrid_train_step(pipeline_schedule="
                "'fill_drain'|'1f1b') or parallel.pipeline.pipeline_spmd — "
                "which runs the whole pipeline as ONE XLA program with "
                "ppermute over ICI (SURVEY.md §2.4 PP row).")
        assert self._layers._loss_fn is not None, "PipelineLayer needs loss_fn"
        micro = self._split_micro(data)
        total = None
        for mb_in, mb_lab in micro:
            out = self._layers(mb_in)
            loss = self._layers._loss_fn(out, mb_lab)
            scaled = loss / len(micro)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = scaled.detach() if total is None else total + scaled.detach()
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        self.total_loss = total
        return total

    def eval_batch(self, data, compute_loss=True):
        micro = self._split_micro(data)
        total = None
        from ...core import autograd as _ag
        with _ag.no_grad():
            for mb_in, mb_lab in micro:
                out = self._layers(mb_in)
                if compute_loss:
                    loss = self._layers._loss_fn(out, mb_lab) / len(micro)
                    total = loss if total is None else total + loss
        return total


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved virtual-pipeline schedule (reference: same class name).

    Eager path: numerics identical to the base schedule (gradient
    accumulation commutes), so train_batch is inherited. The *compiled*
    interleave — the systolic one-chunk-per-tick scan with the v-fold
    bubble reduction — is parallel/pipeline.py::pipeline_spmd_interleaved;
    homogeneous decoder stacks should route through it with chunk params
    pre-permuted by interleave_chunk_order."""
    pass
