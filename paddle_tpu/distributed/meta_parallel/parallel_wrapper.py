"""Model wrappers returned by fleet.distributed_model.

Rebuild of the reference's TensorParallel / ShardingParallel wrappers
(python/paddle/distributed/fleet/meta_parallel/{tensor_parallel,
sharding_parallel}.py — SURVEY.md §2.4). Forward stays imperative; the
compiled path is obtained with ``compile_train_step`` which returns the
GSPMD HybridTrainStep over the fleet mesh.
"""

from __future__ import annotations

from typing import Callable, Optional

from ...nn.layer import Layer


class HybridParallelModel(Layer):
    def __init__(self, model: Layer, hcg, strategy):
        super().__init__()
        self._layers = model
        self._hcg = hcg
        self._strategy = strategy
        self._train_step = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    @property
    def inner_model(self):
        return self._layers

    def compile_train_step(self, loss_fn: Callable, optimizer):
        """loss_fn(model, *batch) -> scalar. Returns the compiled hybrid step
        (cached)."""
        from ..fleet.hybrid_engine import HybridTrainStep
        if self._train_step is None:
            inner_opt = getattr(optimizer, "_inner_opt", optimizer)
            stage = 1
            if self._strategy is not None and self._strategy.sharding:
                stage = int(self._strategy.sharding_configs.get("stage", 1))
            self._train_step = HybridTrainStep(
                self._layers, loss_fn, inner_opt,
                mesh=self._hcg.mesh if self._hcg else None,
                zero_stage=stage)
        return self._train_step

    def train_batch(self, batch, optimizer, lr_scheduler=None, loss_fn=None):
        if self._train_step is None:
            if loss_fn is None:
                raise ValueError("first train_batch call needs loss_fn")
            self.compile_train_step(loss_fn, optimizer)
        loss = self._train_step(*batch)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss
