"""Tensor-parallel (Megatron-style) layers.

Rebuild of python/paddle/distributed/fleet/meta_parallel/parallel_layers/
mp_layers.py (VocabParallelEmbedding / ColumnParallelLinear /
RowParallelLinear / ParallelCrossEntropy — SURVEY.md §2.4 TP row).

Dual execution modes with ONE weight layout (global shapes + PartitionSpec):

* **GSPMD mode** (default, pp==1 path): weights carry NamedSharding specs;
  forwards are plain math; XLA inserts the mp collectives (this replaces the
  reference's c_identity/mp_allreduce_sum ops).
* **Manual mode** (inside shard_map, pcontext.manual_parallel active): the
  engine hands each device its weight shard; forwards issue explicit
  lax collectives over the mp axis — exactly the reference's comm pattern,
  lowered to ICI.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...core.dispatch import apply
from ...core.tensor import Tensor
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer import Layer
from ...parallel import pcontext, mesh as _mesh
from ..topology import get_hybrid_communicate_group
from ...core.compat import axis_size


def _mp_degree() -> int:
    hcg = get_hybrid_communicate_group()
    if hcg is not None:
        return hcg.get_model_parallel_world_size()
    return _mesh.axis_degree("mp")


class ColumnParallelLinear(Layer):
    """Weight [in, out] sharded on out (P(None, 'mp'))."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.world_size = _mp_degree()
        assert out_features % max(self.world_size, 1) == 0, (
            f"out_features {out_features} not divisible by mp degree "
            f"{self.world_size}")
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.weight._sharding_spec = P(None, "mp")
        self.weight.is_distributed_param = True
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias._sharding_spec = P("mp")
            self.bias.is_distributed_param = True
        else:
            self.bias = None

    def forward(self, x):
        ax = pcontext.manual_axis("mp")
        if pcontext.in_manual_mode() and ax is not None:
            def fn(xv, wv, *rest):
                y = jnp.matmul(xv, wv)
                if rest:
                    y = y + rest[0]
                if self.gather_output:
                    y = lax.all_gather(y, ax, axis=y.ndim - 1, tiled=True)
                return y
            args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
            return apply(fn, *args, op_name="col_parallel_linear")
        return F.linear(x, self.weight, self.bias)


class RowParallelLinear(Layer):
    """Weight [in, out] sharded on in (P('mp', None)); input expected sharded
    on the feature dim when input_is_parallel."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.world_size = _mp_degree()
        assert in_features % max(self.world_size, 1) == 0
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.weight._sharding_spec = P("mp", None)
        self.weight.is_distributed_param = True
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias._sharding_spec = P()
        else:
            self.bias = None

    def forward(self, x):
        ax = pcontext.manual_axis("mp")
        if pcontext.in_manual_mode() and ax is not None:
            def fn(xv, wv, *rest):
                if not self.input_is_parallel:
                    # split the full activation to this rank's slice
                    n = axis_size(ax)
                    idx = lax.axis_index(ax)
                    size = xv.shape[-1] // n
                    xv = lax.dynamic_slice_in_dim(xv, idx * size, size, xv.ndim - 1)
                y = jnp.matmul(xv, wv)
                y = lax.psum(y, ax)
                if rest:
                    y = y + rest[0]
                return y
            args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
            return apply(fn, *args, op_name="row_parallel_linear")
        return F.linear(x, self.weight, self.bias)


class VocabParallelEmbedding(Layer):
    """Weight [vocab, emb] sharded on vocab (P('mp', None))."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.world_size = _mp_degree()
        assert num_embeddings % max(self.world_size, 1) == 0
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        self.weight._sharding_spec = P("mp", None)
        self.weight.is_distributed_param = True

    def forward(self, x):
        ax = pcontext.manual_axis("mp")
        if pcontext.in_manual_mode() and ax is not None:
            def fn(ids, wv):
                n = axis_size(ax)
                idx = lax.axis_index(ax)
                per = wv.shape[0]  # local vocab size
                start = idx * per
                ids32 = ids.astype(jnp.int32)
                local = ids32 - start
                in_range = (local >= 0) & (local < per)
                safe = jnp.where(in_range, local, 0)
                emb = jnp.take(wv, safe, axis=0)
                emb = jnp.where(in_range[..., None], emb, 0.0)
                return lax.psum(emb, ax)
            return apply(fn, x, self.weight, op_name="vocab_parallel_embedding")
        return F.embedding(x, self.weight)


def vocab_parallel_ce_array(lg, lab, axis: str, ignore_index: Optional[int] = None):
    """Array-level CE over vocab-sharded logits inside shard_map (shared by
    ParallelCrossEntropy and the llama hybrid step). lg: (..., V_local) fp32;
    lab: (...) int. Returns per-token loss; ignored positions get 0."""
    lg = lg.astype(jnp.float32)
    idx = lax.axis_index(axis)
    per = lg.shape[-1]
    start = idx * per
    # stability shift; input detached because pmax has no AD rule and the
    # shift's gradient contributions cancel exactly
    gmax = lax.pmax(lax.stop_gradient(jnp.max(lg, axis=-1)), axis)
    ex = jnp.exp(lg - gmax[..., None])
    denom = lax.psum(jnp.sum(ex, axis=-1), axis)
    li = lab.astype(jnp.int32)
    local = li - start
    ok = (local >= 0) & (local < per)
    picked = jnp.take_along_axis(lg, jnp.where(ok, local, 0)[..., None],
                                 axis=-1)[..., 0]
    target = lax.psum(jnp.where(ok, picked, 0.0), axis)
    loss = jnp.log(denom) + gmax - target
    if ignore_index is not None:
        loss = jnp.where(li != ignore_index, loss, 0.0)
    return loss


class ParallelCrossEntropy(Layer):
    """CE over vocab-sharded logits.

    Manual mode mirrors the reference's c_softmax_with_cross_entropy: pmax for
    the global max, psum for the denominator, masked pick + psum for the
    target logit — no all_gather of the [.., vocab] logits.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        ax = pcontext.manual_axis("mp")
        if pcontext.in_manual_mode() and ax is not None:
            ignore = self.ignore_index

            def fn(logits, lab):
                li = lab
                if li.ndim == logits.ndim:
                    li = li[..., 0]
                return vocab_parallel_ce_array(logits, li, ax,
                                               ignore_index=ignore)

            return apply(fn, input, label, op_name="parallel_cross_entropy")
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


def mark_as_sequence_parallel_parameter(param):
    param.is_sequence_parallel = True


# functional helpers used inside manual-mode model code -----------------------
def mp_all_gather_last_dim(x: Tensor) -> Tensor:
    ax = pcontext.manual_axis("mp")
    if ax is None:
        return x
    return apply(lambda v: lax.all_gather(v, ax, axis=v.ndim - 1, tiled=True),
                 x, op_name="mp_all_gather")


def mp_all_reduce(x: Tensor) -> Tensor:
    ax = pcontext.manual_axis("mp")
    if ax is None:
        return x
    return apply(lambda v: lax.psum(v, ax), x, op_name="mp_allreduce_sum")
