"""Auto-parallel (semi-automatic SPMD) API.

Rebuild of python/paddle/distributed/auto_parallel/{process_mesh,api}.py
(ProcessMesh / shard_tensor / placements — SURVEY.md §2.4 auto-parallel row).
This is the layer where the reference converges with jax's native model:
ProcessMesh ≈ jax Mesh, Shard(i)/Replicate/Partial ≈ PartitionSpec entries,
and completion/partition/reshard are what GSPMD does inside jit. So this
module is a thin, honest bridge — not a reimplementation of the static
pipeline.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...parallel import mesh as _mesh


class Placement:
    pass


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = int(dim)

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("Shard", self.dim))


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("Replicate")


class Partial(Placement):
    """Pending-reduction placement. GSPMD materialises partial values only
    inside programs; at the API level we treat Partial as Replicate after an
    eager psum."""

    def __repr__(self):
        return "Partial()"


class ProcessMesh:
    """Parity with paddle.distributed.ProcessMesh; wraps a jax Mesh."""

    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None,
                 process_ids=None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.process_ids = arr.flatten().tolist()
        self.dim_names = dim_names or [f"d{i}" for i in range(arr.ndim)]
        devs = np.asarray(jax.devices())
        if devs.size >= arr.size:
            sel = devs.flatten()[: arr.size].reshape(arr.shape)
            self._jax_mesh = Mesh(sel, tuple(self.dim_names))
        else:
            self._jax_mesh = None

    @property
    def mesh(self):
        return self.process_ids

    def jax_mesh(self) -> Optional[Mesh]:
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and self.shape == other.shape
                and self.process_ids == other.process_ids)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def _placements_to_spec(placements, ndim: int, mesh: ProcessMesh) -> P:
    dims = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            name = mesh.dim_names[mesh_dim]
            if dims[pl.dim] is None:
                dims[pl.dim] = name
            elif isinstance(dims[pl.dim], tuple):
                dims[pl.dim] = dims[pl.dim] + (name,)
            else:
                dims[pl.dim] = (dims[pl.dim], name)
    return P(*dims)


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None,
                 stop_gradient=True) -> Tensor:
    """Create a distributed Tensor with the given placements — the dygraph
    entry of the reference's auto-parallel (api.py::shard_tensor)."""
    t = data if isinstance(data, Tensor) else Tensor(data)
    jm = mesh.jax_mesh()
    if jm is None:
        return t
    spec = _placements_to_spec(placements, t._value.ndim, mesh)
    sharded = jax.device_put(t._value, NamedSharding(jm, spec))
    out = Tensor(sharded, stop_gradient=stop_gradient, name=t.name)
    out._sharding_spec = spec
    out.is_distributed = True
    return out


def reshard(tensor: Tensor, mesh: ProcessMesh, placements) -> Tensor:
    jm = mesh.jax_mesh()
    if jm is None:
        return tensor
    spec = _placements_to_spec(placements, tensor._value.ndim, mesh)
    out = Tensor(jax.device_put(tensor._value, NamedSharding(jm, spec)),
                 stop_gradient=tensor.stop_gradient)
    out._sharding_spec = spec
    out.is_distributed = True
    return out


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs) -> Tensor:
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """Shard a Layer's parameters over ``process_mesh`` in place
    (reference api.py::shard_layer). ``shard_fn(sublayer_name, sublayer,
    process_mesh)`` assigns placements per sublayer; the default
    replicates every parameter onto the mesh. ``input_fn``/``output_fn``
    are registered as forward pre/post hooks like the reference."""
    from ...nn import Layer

    if not isinstance(layer, Layer):
        raise TypeError(f"expected a Layer, got {type(layer).__name__}")

    def default_shard(name, sub, mesh):
        for _, p in sub.named_parameters(include_sublayers=False):
            sharded = shard_tensor(p, mesh,
                                   [Replicate()] * max(1, p.ndim))
            p._value = sharded._value

    fn = shard_fn or default_shard
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda lyr, inp: input_fn(inp, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda lyr, inp, out: output_fn(out, process_mesh))
    return layer


class _StrategyConfig:
    """Attribute bag with defaults (enable=False style, reference
    paddle.distributed.Strategy sub-configs)."""

    def __init__(self, **defaults):
        self.__dict__.update(defaults)


class Strategy:
    """Reference paddle.distributed.Strategy (auto-parallel-to-static
    config, python/paddle/distributed/auto_parallel/strategy.py:§0):
    sub-configs for sharding / amp / pipeline / fused passes. Consumed
    by auto_parallel.Engine; the GSPMD partitioner makes most knobs
    advisory here — stage/degree feed mesh construction, amp maps to
    paddle_tpu.amp levels."""

    def __init__(self, config=None):
        cfg = dict(config or {})

        def sub(name, **defaults):
            defaults.update(cfg.get(name, {}))
            return _StrategyConfig(**defaults)

        self.sharding = sub("sharding", enable=False, degree=1, stage=1)
        self.amp = sub("amp", enable=False, dtype="float16", level="O1")
        self.pipeline = sub("pipeline", enable=False,
                            schedule_mode="1F1B", micro_batch_size=1,
                            accumulate_steps=1)
        self.fused_passes = sub("fused_passes", enable=False,
                                fused_passes_list=[])

    def __repr__(self):
        parts = []
        for k in ("sharding", "amp", "pipeline", "fused_passes"):
            parts.append(f"{k}={getattr(self, k).__dict__}")
        return f"Strategy({', '.join(parts)})"
