from .api import ProcessMesh, Shard, Replicate, Partial, shard_tensor, reshard, dtensor_from_fn  # noqa: F401
