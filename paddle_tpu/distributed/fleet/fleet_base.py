"""Fleet facade.

Rebuild of python/paddle/distributed/fleet/fleet.py (fleet.init /
distributed_model / distributed_optimizer — SURVEY.md §2.4 hybrid row, §3.2
call stack).
"""

from __future__ import annotations

from typing import Optional

from .. import env as _env
from ..topology import (CommunicateTopology, HybridCommunicateGroup,
                        set_hybrid_communicate_group,
                        get_hybrid_communicate_group)
from .distributed_strategy import DistributedStrategy
from ...parallel import mesh as _mesh

_state = {"strategy": None, "initialized": False}


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None, log_level=None):
    """Parity with fleet.init: parse strategy, build topology + mesh, create
    axis groups."""
    strategy = strategy or DistributedStrategy()
    _state["strategy"] = strategy
    _env.init_parallel_env()
    degrees = strategy.degrees()
    order = strategy.hybrid_configs.get("order", list(_mesh.HYBRID_ORDER))
    # build the global mesh (folds leftover devices into dp) honouring the
    # configured axis order
    mesh = _mesh.build_mesh(degrees, order=order)
    _mesh.set_global_mesh(mesh)
    actual = {ax: mesh.shape[ax] for ax in mesh.axis_names}
    dims = [actual.get(ax, 1) for ax in _mesh.HYBRID_ORDER]
    topo = CommunicateTopology(list(_mesh.HYBRID_ORDER), dims)
    hcg = HybridCommunicateGroup(topo)
    set_hybrid_communicate_group(hcg)
    _state["initialized"] = True
    return None


def fleet_initialized() -> bool:
    return _state["initialized"]


def get_strategy() -> Optional[DistributedStrategy]:
    return _state["strategy"]


def distributed_model(model):
    """Wrap per active parallelism (reference dispatch in fleet.py →
    PipelineParallel / TensorParallel / ShardingParallel wrappers)."""
    from ..meta_parallel.pipeline_parallel import PipelineParallel
    from ..meta_parallel.pp_layers import PipelineLayer
    from ..meta_parallel.parallel_wrapper import HybridParallelModel

    hcg = get_hybrid_communicate_group()
    strategy = _state["strategy"] or DistributedStrategy()
    if hcg is not None and hcg.get_pipe_parallel_world_size() > 1:
        if not isinstance(model, PipelineLayer):
            raise TypeError(
                "pp_degree > 1 requires the model to be a PipelineLayer "
                "(parity with the reference)")
        return PipelineParallel(model, hcg, strategy)
    return HybridParallelModel(model, hcg, strategy)


def distributed_optimizer(optimizer, strategy=None):
    from .hybrid_optimizer import HybridParallelOptimizer
    hcg = get_hybrid_communicate_group()
    return HybridParallelOptimizer(optimizer, hcg,
                                   strategy or _state["strategy"])


def get_hybrid_communicate_group_():
    return get_hybrid_communicate_group()


# re-export with the fleet.* names
def worker_index() -> int:
    return _env.get_rank()


def worker_num() -> int:
    return _env.get_world_size()


def is_first_worker() -> bool:
    return worker_index() == 0


def barrier_worker():
    import jax
    jax.effects_barrier()
