"""Fleet utility modules (reference: python/paddle/distributed/fleet/utils/)."""

from . import sequence_parallel_utils  # noqa: F401
from . import mix_precision_utils  # noqa: F401
from . import tensor_fusion_helper  # noqa: F401
from . import hybrid_parallel_util  # noqa: F401

# reference import path: paddle.distributed.fleet.utils.recompute
from ..recompute import recompute, recompute_sequential  # noqa: E402,F401
