"""Megatron sequence parallelism (SP, tied to TP).

Rebuild of python/paddle/distributed/fleet/utils/sequence_parallel_utils.py
(SURVEY.md §2.4 SP row, §5.7): LN/dropout activations are sharded along the
*sequence* dimension over the mp group; at TP-region boundaries the
activations are re-partitioned with all_gather / reduce_scatter.

TPU-first note: in GSPMD mode this whole file is unnecessary — annotating
activations with a seq-axis NamedSharding makes XLA insert exactly these
collectives (SURVEY §2.4: "GSPMD does this automatically"). These ops are
the *manual* (shard_map) execution path, where the reference's comm pattern
is written explicitly over the mp mesh axis, riding ICI. Outside manual
mode every op is the identity.

Gradient rules follow the reference's autograd functions exactly:

=================  =======================  =========================
op                 forward                  backward
=================  =======================  =========================
ScatterOp          local seq slice          all_gather over seq
GatherOp           all_gather over seq      local seq slice
AllGatherOp        all_gather over seq      reduce_scatter (psum_scatter)
ReduceScatterOp    reduce_scatter           all_gather
=================  =======================  =========================
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ....core.dispatch import apply
from ....nn import functional as F
from ....nn import initializer as I
from ....nn.layer import Layer
from ....parallel import pcontext, mesh as _mesh
from ...topology import get_hybrid_communicate_group
from ...meta_parallel.mp_layers import (  # noqa: F401  (re-export parity)
    mark_as_sequence_parallel_parameter,
)
from ....core.compat import axis_size

SEQ_AXIS = 0  # [s, b, h] layout, as in the reference


def _mp_degree() -> int:
    hcg = get_hybrid_communicate_group()
    if hcg is not None:
        return hcg.get_model_parallel_world_size()
    return _mesh.axis_degree("mp")


# ---------------------------------------------------------------------------
# Array-level ops with the reference's custom gradients (jax.custom_vjp).
# ``axis`` is the mesh axis name; these are only valid inside shard_map.
# ---------------------------------------------------------------------------

def _slice_to_rank(v, axis_name: str, dim: int):
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    size = v.shape[dim] // n
    return lax.dynamic_slice_in_dim(v, idx * size, size, dim)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def scatter_array(v, axis_name: str, dim: int = SEQ_AXIS):
    return _slice_to_rank(v, axis_name, dim)


def _scatter_fwd(v, axis_name, dim):
    return scatter_array(v, axis_name, dim), None


def _scatter_bwd(axis_name, dim, _res, g):
    return (lax.all_gather(g, axis_name, axis=dim, tiled=True),)


scatter_array.defvjp(_scatter_fwd, _scatter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_array(v, axis_name: str, dim: int = SEQ_AXIS):
    return lax.all_gather(v, axis_name, axis=dim, tiled=True)


def _gather_fwd(v, axis_name, dim):
    return gather_array(v, axis_name, dim), None


def _gather_bwd(axis_name, dim, _res, g):
    return (_slice_to_rank(g, axis_name, dim),)


gather_array.defvjp(_gather_fwd, _gather_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def all_gather_array(v, axis_name: str, dim: int = SEQ_AXIS):
    return lax.all_gather(v, axis_name, axis=dim, tiled=True)


def _all_gather_fwd(v, axis_name, dim):
    return all_gather_array(v, axis_name, dim), None


def _all_gather_bwd(axis_name, dim, _res, g):
    return (lax.psum_scatter(g, axis_name, scatter_dimension=dim, tiled=True),)


all_gather_array.defvjp(_all_gather_fwd, _all_gather_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def reduce_scatter_array(v, axis_name: str, dim: int = SEQ_AXIS):
    return lax.psum_scatter(v, axis_name, scatter_dimension=dim, tiled=True)


def _reduce_scatter_fwd(v, axis_name, dim):
    return reduce_scatter_array(v, axis_name, dim), None


def _reduce_scatter_bwd(axis_name, dim, _res, g):
    return (lax.all_gather(g, axis_name, axis=dim, tiled=True),)


reduce_scatter_array.defvjp(_reduce_scatter_fwd, _reduce_scatter_bwd)


# ---------------------------------------------------------------------------
# Tensor-level ops (the reference's ScatterOp/GatherOp/... public surface)
# ---------------------------------------------------------------------------

def _tensor_op(array_op, x, op_name: str, dim: int = SEQ_AXIS):
    ax = pcontext.manual_axis("mp")
    if not pcontext.in_manual_mode() or ax is None:
        return x  # GSPMD/eager mode: sharding annotations do the job
    return apply(lambda v: array_op(v, ax, dim), x, op_name=op_name)


class ScatterOp:
    """Split the sequence dim onto mp ranks. bwd: all_gather."""

    @staticmethod
    def apply(x, axis: int = SEQ_AXIS):
        return _tensor_op(scatter_array, x, "sp_scatter", axis)


class GatherOp:
    """Assemble the full sequence from mp ranks. bwd: slice."""

    @staticmethod
    def apply(x, axis: int = SEQ_AXIS):
        return _tensor_op(gather_array, x, "sp_gather", axis)


class AllGatherOp:
    """all_gather entering a TP region. bwd: reduce_scatter."""

    @staticmethod
    def apply(x, axis: int = SEQ_AXIS):
        return _tensor_op(all_gather_array, x, "sp_all_gather", axis)


class ReduceScatterOp:
    """reduce_scatter leaving a TP region. bwd: all_gather."""

    @staticmethod
    def apply(x, axis: int = SEQ_AXIS):
        return _tensor_op(reduce_scatter_array, x, "sp_reduce_scatter", axis)


# ---------------------------------------------------------------------------
# Sequence-parallel linear layers
# ---------------------------------------------------------------------------

class ColumnSequenceParallelLinear(Layer):
    """Column-parallel linear whose input is sequence-sharded.

    forward: all_gather(x) over seq → matmul with out-sharded weight.
    The all_gather's bwd (reduce_scatter) returns the grad seq-sharded.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, mp_group=None, name=None):
        super().__init__()
        self.world_size = _mp_degree()
        self.gather_output = gather_output
        assert out_features % max(self.world_size, 1) == 0
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.weight._sharding_spec = P(None, "mp")
        self.weight.is_distributed_param = True
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias._sharding_spec = P("mp")
            self.bias.is_distributed_param = True
        else:
            self.bias = None

    def forward(self, x):
        ax = pcontext.manual_axis("mp")
        if pcontext.in_manual_mode() and ax is not None:
            def fn(xv, wv, *rest):
                full = all_gather_array(xv, ax, SEQ_AXIS)
                y = jnp.matmul(full, wv)
                if rest:
                    y = y + rest[0]
                if self.gather_output:
                    y = lax.all_gather(y, ax, axis=y.ndim - 1, tiled=True)
                return y
            args = [x, self.weight] + (
                [self.bias] if self.bias is not None else [])
            return apply(fn, *args, op_name="col_sp_linear")
        return F.linear(x, self.weight, self.bias)


class RowSequenceParallelLinear(Layer):
    """Row-parallel linear returning a sequence-sharded output.

    forward: matmul with in-sharded weight → reduce_scatter over seq (the
    psum of RowParallelLinear fused with the SP re-partition).
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, mp_group=None,
                 name=None):
        super().__init__()
        self.world_size = _mp_degree()
        self.input_is_parallel = input_is_parallel
        assert in_features % max(self.world_size, 1) == 0
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.weight._sharding_spec = P("mp", None)
        self.weight.is_distributed_param = True
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias._sharding_spec = P()
        else:
            self.bias = None

    def forward(self, x):
        ax = pcontext.manual_axis("mp")
        if pcontext.in_manual_mode() and ax is not None:
            def fn(xv, wv, *rest):
                if not self.input_is_parallel:
                    xv = _slice_to_rank(xv, ax, xv.ndim - 1)
                y = jnp.matmul(xv, wv)
                y = reduce_scatter_array(y, ax, SEQ_AXIS)
                if rest:
                    y = y + rest[0]
                return y
            args = [x, self.weight] + (
                [self.bias] if self.bias is not None else [])
            return apply(fn, *args, op_name="row_sp_linear")
        return F.linear(x, self.weight, self.bias)


# ---------------------------------------------------------------------------
# Gradient-sync hooks for SP parameters (LN weights etc.)
# ---------------------------------------------------------------------------

def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """Parity shim. In the reference, params marked with
    ``mark_as_sequence_parallel_parameter`` get a backward hook allreducing
    their grad over the mp group (their grads are computed from seq shards).

    Here the same sync is applied by :func:`sequence_parallel_sync_gradients`
    after backward in eager mode; inside the compiled hybrid step, marked
    params are psum'd over mp by the engine. This function records the
    marking so both paths find it.
    """
    marked = [p for p in model.parameters()
              if getattr(p, "is_sequence_parallel", False)]
    model._sequence_parallel_params = marked
    return marked


def sequence_parallel_sync_gradients(model, group=None):
    """Eager-mode grad allreduce over the mp group for marked params."""
    from ... import collective
    params = getattr(model, "_sequence_parallel_params", None)
    if params is None:
        params = [p for p in model.parameters()
                  if getattr(p, "is_sequence_parallel", False)]
    for p in params:
        if p.grad is not None:
            collective.all_reduce(p.grad, group=group)
