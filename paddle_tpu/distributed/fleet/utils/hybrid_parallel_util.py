"""Gradient-sync helpers for hybrid parallelism.

Rebuild of python/paddle/distributed/fleet/utils/hybrid_parallel_util.py
(SURVEY.md §2.4 hybrid row): fused allreduce of grads over the dp/sharding
group after backward, and parameter broadcast at init so replicas agree.

Single-controller note: under one controller, parameters are global arrays —
replicas agree by construction, so the broadcast_* functions are cheap
parity shims; fused_allreduce_gradients is real work whenever a dp group
spans a mesh axis (multi-slice DCN sync in particular).
"""

from __future__ import annotations

from typing import Optional, Sequence

from .tensor_fusion_helper import fused_parameters


def fused_allreduce_gradients(parameter_list: Sequence, hcg=None,
                              group=None, scale=None,
                              use_main_grad: bool = False) -> None:
    """Bucketed allreduce of every param's grad over the dp group.

    Reference behaviour: called at the end of backward for params not
    covered by the sharding reducer; expert params (MoE) are excluded.
    """
    params = [p for p in parameter_list
              if not getattr(p, "expert", False)]
    grads_attr = "main_grad" if use_main_grad else "grad"
    params = [p for p in params if getattr(p, grads_attr) is not None]
    if not params:
        return
    if group is None and hcg is not None:
        group = hcg.get_data_parallel_group()
    if group is None or getattr(group, "nranks", 1) <= 1:
        # single controller, no multi-process dp group: grads are already
        # globally reduced (they were computed from the global batch)
        return
    if scale is None:
        # comm() all-reduces replicated copies (nranks * grad under one
        # controller), so the dp average requires dividing by the group
        # size. Default it so reference-convention callers
        # fused_allreduce_gradients(params, hcg) can't get inflated grads;
        # pass scale=1.0 explicitly to opt out.
        scale = float(group.nranks)
    for buf in fused_parameters(params, comm_group=group,
                                use_main_grad=use_main_grad):
        for p in buf._params:
            buf.add_grad(p)
        buf.comm()
        if scale != 1.0:
            # dp averaging (reference divides the reduced grads by the dp
            # degree); done on the flat buffer before scatter so each param
            # slice is written back exactly once.
            buf.buffer = buf.buffer / scale
        buf.scatter_grads()


def broadcast_input_data(hcg, *inputs, **kwargs):
    """Parity shim: inputs are global arrays under one controller."""
    if kwargs:
        return list(inputs) + [kwargs]
    return inputs if len(inputs) != 1 else inputs[0]


def broadcast_mp_parameters(model, hcg) -> None:
    """No-op under single controller: mp replicas share the global array."""


def broadcast_dp_parameters(model, hcg) -> None:
    """No-op under single controller (reference: dp-group broadcast)."""


def broadcast_sharding_parameters(model, hcg) -> None:
    """No-op under single controller (reference: sharding-group broadcast)."""


def sharding_reduce_gradients(parameter_list: Sequence, hcg) -> None:
    """Reduce grads over the sharding group (stage-1 path). Under one
    controller the grads are already global sums; kept for API parity and
    used when a sharding axis maps to a real multi-process group."""
    group = hcg.get_sharding_parallel_group() if hcg is not None else None
    if group is None or getattr(group, "nranks", 1) <= 1:
        return
    # comm() psums replicated copies (nranks * g under one controller);
    # scale by the group size so the written-back grads stay the dp average.
    fused_allreduce_gradients(parameter_list, group=group,
                              scale=float(group.nranks))
