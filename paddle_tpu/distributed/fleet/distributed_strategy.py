"""DistributedStrategy.

Rebuild of python/paddle/distributed/fleet/base/distributed_strategy.py
(protobuf-backed in the reference, paddle/fluid/framework/
distributed_strategy.proto — SURVEY.md §5.6). Plain dataclass-style config
with the same key names: hybrid_configs (dp/mp/pp/sharding/sep degrees +
micro-batch settings), amp_configs, recompute_configs, sharding_configs,
tensor_parallel_configs.
"""

from __future__ import annotations

from typing import Any, Dict


_HYBRID_DEFAULTS = {
    "dp_degree": 1,
    "mp_degree": 1,
    "pp_degree": 1,
    "sharding_degree": 1,
    "sep_degree": 1,
    "ep_degree": 1,
    "micro_batch_size": 1,
    "accumulate_steps": 1,
    "order": ["dp", "pp", "sharding", "sep", "mp"],
    # reference pp_configs carries schedule options (schedule_mode in the
    # reference proto); here it selects the compiled pipeline program:
    # "fill_drain" (interleaved when virtual_pp > 1) or "1f1b"
    "pp_configs": {"schedule": "fill_drain", "virtual_pp": 1},
}

_AMP_DEFAULTS = {
    "init_loss_scaling": 2.0 ** 15,
    "incr_every_n_steps": 1000,
    "decr_every_n_nan_or_inf": 2,
    "incr_ratio": 2.0,
    "decr_ratio": 0.5,
    "use_dynamic_loss_scaling": True,
    "custom_white_list": [],
    "custom_black_list": [],
    "level": "O1",
    "dtype": "bfloat16",
    "use_fp16_guard": False,
}

_RECOMPUTE_DEFAULTS = {
    "checkpoints": [],
    "enable_offload": False,
    "checkpoint_shape": [],
}

_SHARDING_DEFAULTS = {
    "sharding_degree": 1,
    "stage": 1,
    "split_param": False,
    "comm_overlap": True,
    "offload": False,
}

_TP_DEFAULTS = {
    "tensor_parallel_degree": 1,
    "tensor_init_seed": -1,
    "sequence_parallel": False,
}


class DistributedStrategy:
    def __init__(self):
        self._hybrid_configs = dict(_HYBRID_DEFAULTS)
        self._hybrid_configs["pp_configs"] = dict(
            _HYBRID_DEFAULTS["pp_configs"])
        self._amp = False
        self._amp_configs = dict(_AMP_DEFAULTS)
        self._recompute = False
        self._recompute_configs = dict(_RECOMPUTE_DEFAULTS)
        self._sharding = False
        self._sharding_configs = dict(_SHARDING_DEFAULTS)
        self._tensor_parallel_configs = dict(_TP_DEFAULTS)
        self.find_unused_parameters = False
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.lamb_configs = {"lamb_weight_decay": 0.01,
                             "exclude_from_weight_decay": []}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1, "begin_step": 1}
        self.heter_ccl_mode = False
        self.without_graph_optimization = True

    # hybrid ---------------------------------------------------------------
    @property
    def hybrid_configs(self) -> Dict[str, Any]:
        return self._hybrid_configs

    @hybrid_configs.setter
    def hybrid_configs(self, configs: Dict[str, Any]):
        for k, v in configs.items():
            if k not in _HYBRID_DEFAULTS:
                raise ValueError(f"unknown hybrid config key {k!r}")
            if k == "pp_configs":
                unknown = set(v) - set(_HYBRID_DEFAULTS["pp_configs"])
                if unknown:
                    raise ValueError(
                        f"unknown pp_configs key(s) {sorted(unknown)}")
                # partial update against the INSTANCE's current value
                merged = dict(self._hybrid_configs["pp_configs"])
                merged.update(v)
                if merged["schedule"] not in ("fill_drain", "1f1b"):
                    raise ValueError(
                        f"pp_configs.schedule must be 'fill_drain' or "
                        f"'1f1b', got {merged['schedule']!r}")
                v = merged
            self._hybrid_configs[k] = v

    def pipeline_schedule(self) -> str:
        """Compiled pipeline schedule for the hybrid train step; consumed by
        model builders as build_hybrid_train_step(pipeline_schedule=...)."""
        return self._hybrid_configs["pp_configs"]["schedule"]

    def virtual_pp_degree(self) -> int:
        return int(self._hybrid_configs["pp_configs"]["virtual_pp"])

    def degrees(self) -> Dict[str, int]:
        h = self._hybrid_configs
        return {
            "dp": int(h["dp_degree"]),
            "pp": int(h["pp_degree"]),
            "sharding": int(h["sharding_degree"]),
            "sep": int(h["sep_degree"]),
            "mp": int(h["mp_degree"]),
        }

    # amp ------------------------------------------------------------------
    @property
    def amp(self) -> bool:
        return self._amp

    @amp.setter
    def amp(self, flag: bool):
        self._amp = bool(flag)

    @property
    def amp_configs(self):
        return self._amp_configs

    @amp_configs.setter
    def amp_configs(self, configs):
        self._amp_configs.update(configs)

    # recompute ------------------------------------------------------------
    @property
    def recompute(self) -> bool:
        return self._recompute

    @recompute.setter
    def recompute(self, flag: bool):
        self._recompute = bool(flag)

    @property
    def recompute_configs(self):
        return self._recompute_configs

    @recompute_configs.setter
    def recompute_configs(self, configs):
        self._recompute_configs.update(configs)

    # sharding -------------------------------------------------------------
    @property
    def sharding(self) -> bool:
        return self._sharding

    @sharding.setter
    def sharding(self, flag: bool):
        self._sharding = bool(flag)

    @property
    def sharding_configs(self):
        return self._sharding_configs

    @sharding_configs.setter
    def sharding_configs(self, configs):
        self._sharding_configs.update(configs)

    # tp -------------------------------------------------------------------
    @property
    def tensor_parallel_configs(self):
        return self._tensor_parallel_configs

    @tensor_parallel_configs.setter
    def tensor_parallel_configs(self, configs):
        self._tensor_parallel_configs.update(configs)

    def __repr__(self):
        return (f"DistributedStrategy(hybrid={self._hybrid_configs}, "
                f"amp={self._amp}, recompute={self._recompute}, "
                f"sharding={self._sharding})")
