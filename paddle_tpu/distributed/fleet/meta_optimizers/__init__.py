"""Strategy-driven meta-optimizers — parity shims.

Reference: python/paddle/distributed/fleet/meta_optimizers/ — static-graph
rewrite passes (AMP, recompute, sharding, pipeline, ...) selected by
DistributedStrategy flags (SURVEY.md §2.5, marked design-level for the
rebuild: jax has no separate static graph to rewrite — the same strategy
flags configure *transform composition* instead).

Each class here keeps the reference's name and constructor and delegates
to the dygraph/TPU-native mechanism, so strategy-driven code paths
(fleet.distributed_optimizer dispatch) resolve the same way.
"""

from __future__ import annotations


class _DelegatingMetaOptimizer:
    """Wraps an inner optimizer; subclasses attach their transform."""

    def __init__(self, optimizer):
        self.inner_opt = optimizer

    def __getattr__(self, item):
        if item == "inner_opt":  # not yet set (unpickling) → no recursion
            raise AttributeError(item)
        return getattr(self.inner_opt, item)


class AMPOptimizer(_DelegatingMetaOptimizer):
    """amp strategy → paddle_tpu.amp.decorate / auto_cast (bf16-first)."""


class RecomputeOptimizer(_DelegatingMetaOptimizer):
    """recompute strategy → fleet.recompute (jax.checkpoint policies)."""


class ShardingOptimizer(_DelegatingMetaOptimizer):
    """sharding strategy → DygraphShardingOptimizer / group_sharded APIs."""


class PipelineOptimizer(_DelegatingMetaOptimizer):
    """pipeline strategy → meta_parallel.PipelineParallel engines."""


class GradientMergeOptimizer(_DelegatingMetaOptimizer):
    """gradient merge → microbatch accumulation in PipelineParallel /
    MixPrecisionLayer main_grad accumulation."""


class LambOptimizer(_DelegatingMetaOptimizer):
    """lamb strategy → paddle_tpu.optimizer.Lamb."""


class LocalSGDOptimizer(_DelegatingMetaOptimizer):
    """localsgd: periodic parameter averaging over dp — host-side loop
    calling distributed.all_reduce on params every k steps."""


class DGCOptimizer(_DelegatingMetaOptimizer):
    """deep gradient compression: not applicable on ICI (collectives are
    compiler-scheduled); kept for strategy-surface parity."""
