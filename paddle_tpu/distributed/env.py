"""Distributed environment bootstrap.

Rebuild of init_parallel_env / ParallelEnv (python/paddle/distributed/
parallel.py) + TCPStore rendezvous (paddle/fluid/distributed/store/
tcp_store.cc) — SURVEY.md §2.3. On TPU the coordination service of
``jax.distributed`` replaces TCPStore+NCCL-id exchange; env vars keep the
reference's names (PADDLE_TRAINER_ID etc.) with JAX equivalents honoured too.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
from ..core.compat import distributed_is_initialized

_initialized = [False]


def coordinator_address() -> str:
    """Resolve the coordination-service address the way the reference
    resolves its TCPStore master (SURVEY §3.1): explicit PADDLE_MASTER wins;
    else the FIRST entry of PADDLE_TRAINER_ENDPOINTS (the launcher deploys
    rank 0 there — reference launch env contract); else MASTER_ADDR/PORT."""
    master = os.environ.get("PADDLE_MASTER")
    if master:
        return master
    endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    if endpoints:
        first = endpoints.split(",")[0].strip()
        if first:
            return first
    return (os.environ.get("MASTER_ADDR", "127.0.0.1") + ":" +
            os.environ.get("MASTER_PORT", "8639"))


def init_parallel_env(strategy=None, timeout_s: Optional[int] = None
                      ) -> "ParallelEnv":
    """Parity with paddle.distributed.init_parallel_env.

    Single-host: no-op beyond device discovery. Multi-host (launcher sets
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS or
    PADDLE_MASTER): initialises the jax coordination service — the
    TCPStore + NCCL-id rendezvous of the reference collapsed into one
    barrier'd bring-up. ``jax.distributed.initialize`` blocks until all
    ``nprocs`` processes connect, so returning means the mesh of every
    host's devices is visible via jax.devices().
    """
    if _initialized[0]:
        return ParallelEnv()
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    # IMPORTANT: do not probe jax.process_count() here — it initialises the
    # XLA backend, after which jax.distributed.initialize() refuses to run
    # (found by the round-3 two-process rehearsal, tests/test_launch.py).
    # is_initialized() only checks the coordination-service client handle.
    if nprocs > 1 and not distributed_is_initialized():
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        master = coordinator_address()
        kwargs = {}
        if timeout_s is not None:
            kwargs["initialization_timeout"] = timeout_s
        local = os.environ.get("PADDLE_LOCAL_DEVICE_IDS")
        if local:
            kwargs["local_device_ids"] = [int(x) for x in local.split(",")
                                          if x]
        try:
            jax.distributed.initialize(coordinator_address=master,
                                       num_processes=nprocs,
                                       process_id=rank, **kwargs)
        except Exception as e:
            raise RuntimeError(
                f"multi-host bring-up failed: rank {rank}/{nprocs} could "
                f"not reach coordinator {master!r} "
                f"(PADDLE_MASTER/PADDLE_TRAINER_ENDPOINTS). "
                f"Original error: {type(e).__name__}: {e}") from e
    _initialized[0] = True
    return ParallelEnv()


def is_initialized() -> bool:
    return _initialized[0]


def get_rank() -> int:
    """Process rank (reference: trainer id)."""
    return int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))


def get_world_size() -> int:
    """Number of processes (reference: trainer count)."""
    return int(os.environ.get("PADDLE_TRAINERS_NUM", jax.process_count()))


def device_count() -> int:
    return len(jax.devices())


class ParallelEnv:
    @property
    def rank(self) -> int:
        return get_rank()

    @property
    def world_size(self) -> int:
        return get_world_size()

    @property
    def local_rank(self) -> int:
        return int(os.environ.get("PADDLE_LOCAL_RANK", "0"))

    @property
    def nranks(self) -> int:
        return get_world_size()

    @property
    def dev_id(self) -> int:
        return self.local_rank
