"""Collective communication API — ProcessGroupICI.

Rebuild of the reference's ProcessGroup/ProcessGroupNCCL + Python functional
collectives (paddle/fluid/distributed/collective/process_group_nccl.cc,
python/paddle/distributed/communication/* — SURVEY.md §2.3).

TPU-native semantics: a Group is a handle onto a mesh axis. Collectives are
*program-level* — tiny jitted shard_map programs over the global mesh whose
ops lower to XLA ICI collectives (psum / all_gather / reduce_scatter /
ppermute / all_to_all). They operate on GLOBAL arrays (sharded or replicated
jax values), which is the single-controller analog of the reference's
per-rank eager tensors. Inside a compiled hybrid step the same axis names are
used directly via jax.lax collectives.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from ..core.compat import distributed_is_initialized, shard_map

from ..core.tensor import Tensor
from ..parallel import mesh as _mesh


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group = (mesh, axis name). Parity surface of the
    reference's ``Group`` (python/paddle/distributed/communication/group.py)."""

    def __init__(self, axis: str, mesh=None, ranks: Optional[List[int]] = None):
        self.axis = axis
        self.mesh = mesh if mesh is not None else _mesh.get_global_mesh()
        self._ranks = ranks

    @property
    def nranks(self) -> int:
        if self.mesh is None or self.axis not in self.mesh.shape:
            return 1
        return self.mesh.shape[self.axis]

    @property
    def world_size(self) -> int:
        return self.nranks

    @property
    def rank(self) -> int:
        return 0  # single-controller; per-device rank exists only in-program

    def get_group_rank(self, rank):
        return rank

    def __repr__(self):
        return f"Group(axis={self.axis!r}, nranks={self.nranks})"


_WORLD: List[Optional[Group]] = [None]


def _world_group() -> Group:
    m = _mesh.ensure_mesh()
    if _WORLD[0] is None or _WORLD[0].mesh is not m:
        _WORLD[0] = Group("dp", m)  # rebuilt whenever the global mesh changes
    return _WORLD[0]


def get_group(group: Optional[Group]) -> Group:
    return group if group is not None else _world_group()


def new_group(ranks=None, backend=None, axis: Optional[str] = None,
              timeout=None) -> Group:
    """Reference creates an NCCL ring per group; here groups alias mesh axes.
    ``axis`` selects the mesh dimension; default 'dp'."""
    return Group(axis or "dp", _mesh.ensure_mesh(), ranks)


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


@functools.lru_cache(maxsize=256)
def _cached_program(mesh, axis: str, kind: str, in_sharded: bool,
                    out_sharded: bool, op: str = "sum"):
    """One compiled shard_map program per (mesh, axis, collective) — eager
    collectives in a loop must not recompile per call."""

    def make(fn):
        return jax.jit(shard_map(
            fn, mesh=mesh,
            in_specs=(P(axis) if in_sharded else P(),),
            out_specs=P(axis) if out_sharded else P(),
            check_vma=False))

    if kind == "all_reduce":
        red = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin,
               "avg": lax.pmean}[op]
        return make(lambda x: red(x, axis))
    if kind == "all_gather_tiled":
        return make(lambda x: lax.all_gather(x, axis, tiled=True))
    if kind == "all_gather_stacked":
        return make(lambda x: lax.all_gather(x, axis, tiled=False))
    if kind == "reduce_scatter":
        return make(lambda x: lax.psum_scatter(x, axis, scatter_dimension=0,
                                               tiled=True))
    if kind == "alltoall":
        n = mesh.shape[axis]

        def fn(x):
            xs = x.reshape((n, x.shape[0] // n) + x.shape[1:])
            return lax.all_to_all(xs, axis, split_axis=0, concat_axis=0,
                                  tiled=False).reshape(x.shape)
        return make(fn)
    raise ValueError(kind)


# ---------------------------------------------------------------------------
# collectives on global arrays
# ---------------------------------------------------------------------------
def all_reduce(tensor, op=ReduceOp.SUM, group: Optional[Group] = None,
               sync_op=True):
    """All-reduce a *replicated-per-rank view*: the global tensor is treated
    as stacked per-rank slabs along dim 0 (reference semantics: every rank
    holds one tensor). For a tensor NOT stacked per-rank, this reduces the
    dim-0 shards. Result replaces the tensor in-place (paddle semantics)."""
    g = get_group(group)
    v = _unwrap(tensor)
    if g.nranks == 1:
        return tensor
    if op not in ("sum", "max", "min", "avg"):
        raise ValueError(f"unsupported reduce op {op}")
    out = _cached_program(g.mesh, g.axis, "all_reduce", True, True, op)(v)
    if isinstance(tensor, Tensor):
        tensor._value = out
        return tensor
    return out


def all_reduce_replicated(value, op=ReduceOp.SUM,
                          group: Optional[Group] = None):
    """Reduce a REPLICATED array over the group: every device contributes
    its (identical, under one controller) copy — result = nranks * value for
    sum. This is the per-rank-tensor all_reduce without the dim-0 slab view;
    flat fused-grad buffers need it because their dim 0 packs many params
    and must not be sharded."""
    g = get_group(group)
    v = _unwrap(value)
    if g.nranks == 1:
        return v
    return _cached_program(g.mesh, g.axis, "all_reduce", False, False, op)(v)


def all_gather(tensor_list, tensor=None, group: Optional[Group] = None,
               sync_op=True):
    """Two calling conventions (paddle): all_gather(list, tensor) fills the
    list; or all_gather(tensor, group=g) returns the gathered tensor when the
    first arg is a Tensor."""
    g = get_group(group)
    if tensor is None or isinstance(tensor_list, Tensor):
        src = tensor_list if isinstance(tensor_list, Tensor) else tensor
        v = _unwrap(src)
        out = _cached_program(g.mesh, g.axis, "all_gather_tiled", True, False)(v)
        return Tensor(out)

    v = _unwrap(tensor)
    gathered = _cached_program(g.mesh, g.axis, "all_gather_stacked", False, False)(v)
    tensor_list.clear()
    for i in range(g.nranks):
        tensor_list.append(Tensor(gathered[i]))
    return tensor_list


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM,
                   group: Optional[Group] = None, sync_op=True):
    """Each rank holds the (replicated) input tensor; the output is the
    summed tensor scattered along dim 0 — returned as a global array sharded
    over the group axis (rank i's slab = sum slice i)."""
    g = get_group(group)
    v = _unwrap(tensor)
    if g.nranks == 1:
        return Tensor(v) if not isinstance(tensor, Tensor) else tensor
    return Tensor(_cached_program(g.mesh, g.axis, "reduce_scatter",
                                  False, True)(v))


def broadcast(tensor, src=0, group: Optional[Group] = None, sync_op=True):
    """Single-controller: global arrays are already consistent; broadcast is
    the identity (kept for API parity)."""
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group: Optional[Group] = None,
           sync_op=True):
    return all_reduce(tensor, op=op, group=group)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        from ..core.math_ops import concat
        return concat([t for t in tensor_list], axis=0)
    return tensor


def alltoall(out_tensor_list, in_tensor_list=None, group: Optional[Group] = None,
             sync_op=True):
    """paddle alltoall: rank i sends in_tensor_list[j] to rank j. Global-array
    semantics: input stacked (nranks*..., ...) along dim 0, returns the
    transposed exchange."""
    g = get_group(group)
    if isinstance(out_tensor_list, Tensor):
        v = _unwrap(out_tensor_list)
        out = _cached_program(g.mesh, g.axis, "alltoall", True, True)(v)
        return Tensor(out)
    raise NotImplementedError("list-form alltoall: pass a stacked Tensor")


def all_to_all(*args, **kwargs):
    return alltoall(*args, **kwargs)


def send(tensor, dst=0, group=None, sync_op=True):
    from .communication.p2p import send as _send
    return _send(tensor, dst=dst, group=group, sync_op=sync_op)


def recv(tensor, src=0, group=None, sync_op=True):
    from .communication.p2p import recv as _recv
    return _recv(tensor, src=src, group=group, sync_op=sync_op)


def barrier(group=None):
    jax.effects_barrier()


def get_world_size(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.nranks
    from . import env
    return max(env.get_world_size(), len(jax.devices()))


def get_rank(group: Optional[Group] = None) -> int:
    from . import env
    return env.get_rank()


# in-program helpers (used by model code inside shard_map)
def psum(x, axis: str):
    return lax.psum(x, axis)


def ppermute(x, axis: str, perm):
    return lax.ppermute(x, axis, perm)


# --------------------------------------------------------------------------
# Long-tail parity surface (round 5): gather, wait, backend queries, object
# collectives (reference python/paddle/distributed/communication/{gather,
# all_gather,broadcast,scatter}.py object variants:§0)
# --------------------------------------------------------------------------

def gather(tensor, gather_list=None, dst=0, group: Optional[Group] = None,
           sync_op=True):
    """Gather shards to rank ``dst``. Single-controller semantics: the
    gathered list materializes on the (one) host for every dst, so this
    is all_gather with the reference's call shape (gather_list filled
    in-place)."""
    if gather_list is None:
        gather_list = []
    all_gather(gather_list, tensor, group=group, sync_op=sync_op)
    return gather_list


def wait(tensor, group=None, use_calc_stream=True):
    """Reference stream-sync. XLA programs order collectives by data
    dependency, so this only forces materialization."""
    v = tensor._value if isinstance(tensor, Tensor) else tensor
    jax.block_until_ready(v)
    return tensor


def get_backend(group: Optional[Group] = None) -> str:
    """Backend name (reference: 'NCCL'/'GLOO'). ICI collectives compiled
    by XLA; 'XLA' keeps code that just checks truthiness/logs happy."""
    return "XLA"


def destroy_process_group(group: Optional[Group] = None):
    """Tear down comm state (reference parity): destroying the WORLD
    group (or passing no group) clears the cached collective programs
    and the env init flag; destroying a subgroup is a no-op beyond
    dropping the handle (groups alias mesh axes — there is no per-group
    state to free)."""
    if group is None or group is _WORLD[0]:
        _cached_program.cache_clear()
        _WORLD[0] = None
        from . import env as _env
        _env._initialized[0] = False


def _store_exchange(obj, op: str):
    """Serialize ``obj`` and exchange across processes over the jax
    coordination service (the reference runs tensor collectives on
    pickled bytes; ``process_allgather`` on a padded uint8 buffer is the
    same wire shape on the single-controller runtime). Requires
    ``init_parallel_env()`` in multi-process jobs, like the reference
    requires its process group init. Single-process worlds
    short-circuit."""
    import pickle

    import numpy as np

    from . import env as _env

    world = _env.get_world_size()
    if world <= 1:
        return [obj]
    if not distributed_is_initialized():
        raise RuntimeError(
            "object collectives need the coordination service; call "
            "paddle.distributed.init_parallel_env() first")
    from jax.experimental import multihost_utils as mhu

    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    # round 1: lengths (ragged pickles), round 2: padded bytes
    sizes = mhu.process_allgather(np.asarray([payload.size], np.int64))
    max_len = int(sizes.max())
    buf = np.zeros((max_len,), np.uint8)
    buf[:payload.size] = payload
    data = np.asarray(mhu.process_allgather(buf))
    return [pickle.loads(data[r, :int(sizes[r, 0])].tobytes())
            for r in range(world)]


def all_gather_object(object_list, obj, group: Optional[Group] = None):
    """Gather arbitrary picklable objects from every process
    (reference all_gather_object)."""
    object_list[:] = _store_exchange(obj, "ag")
    return object_list


def broadcast_object_list(object_list, src=0, group: Optional[Group] = None):
    """Broadcast the list of objects from process ``src`` in place."""
    from . import env as _env

    world = _env.get_world_size()
    if world <= 1:
        return object_list
    gathered = _store_exchange(list(object_list), "bc")
    object_list[:] = gathered[src]
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group: Optional[Group] = None):
    """Each process receives its slice of ``in_object_list`` from
    ``src``."""
    from . import env as _env

    world = _env.get_world_size()
    if world <= 1:
        out_object_list[:] = [in_object_list[0] if in_object_list else None]
        return out_object_list
    gathered = _store_exchange(in_object_list, "sc")
    rank = _env.get_rank()
    out_object_list[:] = [gathered[src][rank]]
    return out_object_list
