"""Sharded checkpoint load with reshard-on-load.

Rebuild of python/paddle/distributed/checkpoint/load_state_dict.py:§0
(SURVEY.md §5.4): the saved shard set (from ``.metadata``) is matched against
the *target* state dict's current shapes/shardings; every saved piece is
copied into its slice of the target tensor ("ReadItems" in the reference),
then placed with the target's NamedSharding — so checkpoints written under
one TP×PP×sharding topology load under any other.
"""

from __future__ import annotations

import glob
import os
import pickle
from typing import Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from .metadata import Metadata
from .save_state_dict import _BF16
from .utils import CheckpointCorruptError, flatten_state_dict, verify_crc32


def _read_metadata(path: str, unique_id=None) -> Metadata:
    files = sorted(glob.glob(os.path.join(path, "*.metadata")))
    if not files:
        raise FileNotFoundError(f"no .metadata file under {path!r}")

    def uid_of(f):
        stem = os.path.basename(f)[: -len(".metadata")]
        # "{rank}_{uid}" (current) or bare "{uid}" (coordinator-style)
        return int(stem.rsplit("_", 1)[-1])

    if unique_id is None:
        unique_id = max(uid_of(f) for f in files)  # latest checkpoint wins
    files = [f for f in files if uid_of(f) == unique_id]
    if not files:
        raise FileNotFoundError(
            f"no .metadata for unique_id={unique_id} under {path!r}")
    merged = Metadata()
    for f in files:
        try:
            with open(f, "rb") as fh:
                m = pickle.load(fh)
        except Exception as e:  # truncated/torn metadata = corrupt checkpoint
            raise CheckpointCorruptError(
                f"unreadable checkpoint metadata {f!r}: {e}") from e
        if not isinstance(m, Metadata):
            raise CheckpointCorruptError(
                f"{f!r} does not contain checkpoint Metadata "
                f"(got {type(m).__name__})")
        # shard lists must EXTEND across ranks (each rank records only the
        # shards it owns), deduped by offset
        for key, shards in m.state_dict_metadata.items():
            have = merged.state_dict_metadata.setdefault(key, [])
            seen = {s.global_offset for s in have}
            have.extend(s for s in shards if s.global_offset not in seen)
        merged.storage_metadata.update(m.storage_metadata)
        merged.flat_mapping.update(m.flat_mapping)
        merged.aux.update(getattr(m, "aux", {}))
        merged.checksums.update(getattr(m, "checksums", {}))
    return merged


def read_metadata(path: str, unique_id=None) -> Metadata:
    """Public merged-metadata reader (the resilience layer uses it to build
    a full-coverage load target from the checkpoint's own key set)."""
    return _read_metadata(path, unique_id)


class _DataFiles:
    """Lazy npz readers, one per data file; each file's recorded CRC32 is
    verified once, on first open, before any shard from it is trusted."""

    def __init__(self, path: str, checksums: Optional[Dict[str, int]] = None):
        self.path = path
        self.checksums = checksums or {}
        self._files: Dict[str, "np.lib.npyio.NpzFile"] = {}
        self._dtypes: Dict[str, Dict[str, str]] = {}

    def _verify(self, name: str) -> None:
        if name in self.checksums:  # pre-checksum checkpoints: nothing to check
            verify_crc32(os.path.join(self.path, name), self.checksums[name])

    def read(self, ref: str) -> np.ndarray:
        fname, name = ref.split("::", 1)
        if fname not in self._files:
            self._verify(fname + ".npz")
            self._verify(fname + ".dtypes")
            try:
                self._files[fname] = np.load(
                    os.path.join(self.path, fname + ".npz"))
                dt_path = os.path.join(self.path, fname + ".dtypes")
                with open(dt_path, "rb") as f:
                    self._dtypes[fname] = pickle.load(f)
            except CheckpointCorruptError:
                raise
            except FileNotFoundError:
                raise
            except Exception as e:  # undecodable zip/pickle = corrupt shard
                raise CheckpointCorruptError(
                    f"unreadable shard file {fname!r} under "
                    f"{self.path!r}: {e}") from e
        try:
            arr = self._files[fname][name]
        except Exception as e:
            raise CheckpointCorruptError(
                f"shard {name!r} missing/undecodable in {fname!r}: {e}") from e
        if self._dtypes[fname].get(name) == _BF16:
            arr = arr.view(jnp.bfloat16)
        return arr


def load_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, unique_id=None,
                    offload: bool = False) -> None:
    """Fill ``state_dict``'s tensors in place from the checkpoint at
    ``path``, resharding saved pieces into each target tensor's current
    global shape and sharding."""
    meta = _read_metadata(path, unique_id)
    data = _DataFiles(path, getattr(meta, "checksums", {}))
    flat, mapping = flatten_state_dict(state_dict)
    storage = {(i.tensor_key, i.global_offset): ref
               for i, ref in meta.storage_metadata.items()}

    def _assign_nested(key, value):
        path_keys = mapping.get(key, (key,))
        d = state_dict
        for p in path_keys[:-1]:
            d = d[p]
        d[path_keys[-1]] = value

    for key, target in flat.items():
        if not isinstance(target, Tensor) and not hasattr(target, "shape"):
            # non-tensor state rides in metadata aux (step counters, lr state)
            if key in meta.aux:
                _assign_nested(key, meta.aux[key])
                continue
            raise KeyError(f"non-tensor key {key!r} not in checkpoint aux")
        shards = meta.state_dict_metadata.get(key)
        if shards is None:
            raise KeyError(
                f"{key!r} not found in checkpoint {path!r} "
                f"(available: {sorted(meta.state_dict_metadata)[:8]}...)")
        is_tensor = isinstance(target, Tensor)
        if not is_tensor:
            # fail fast before any shard IO: in-place fill needs a Tensor
            raise TypeError(
                f"load_state_dict target {key!r} must be a Tensor "
                f"(got {type(target).__name__})")
        tv = target._value
        # global shape = max over shards of offset+local_shape
        ndim = len(shards[0].local_shape)
        gshape = [0] * ndim
        for s in shards:
            for d in range(ndim):
                gshape[d] = max(gshape[d], s.global_offset[d] + s.local_shape[d])
        gshape = tuple(gshape)
        if tuple(tv.shape) != gshape:
            raise ValueError(
                f"shape mismatch for {key!r}: checkpoint {gshape}, "
                f"target {tuple(tv.shape)}")
        # assemble the global array from saved pieces (reshard-on-load:
        # pieces may come from any source topology)
        first = data.read(storage[(key, shards[0].global_offset)])
        out = np.empty(gshape, dtype=first.dtype)
        for s in shards:
            piece = data.read(storage[(key, s.global_offset)])
            idx = tuple(slice(o, o + l)
                        for o, l in zip(s.global_offset, s.local_shape))
            out[idx] = piece.reshape(s.local_shape)
        # place with the target's sharding (this is where the new topology's
        # partitioning happens — XLA scatters slices to devices). Targets
        # without an explicit mesh placement stay uncommitted so they keep
        # composing with any mesh downstream.
        sharding = getattr(tv, "sharding", None)
        arr = jnp.asarray(out)
        if arr.dtype != tv.dtype:
            arr = arr.astype(tv.dtype)
        if isinstance(sharding, jax.sharding.NamedSharding) and not offload:
            arr = jax.device_put(arr, sharding)
        target._value = arr
