"""Checkpoint metadata schema.

Rebuild of python/paddle/distributed/checkpoint/metadata.py:§0
(SURVEY.md §5.4 tier 3): a global ``Metadata`` maps every tensor key to the
list of saved shards (offset + shape + dtype) and each shard to the data file
holding it — the information load-time resharding needs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class LocalTensorMetadata:
    """One saved shard of a tensor: where it sits in the global tensor."""
    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str


@dataclass(frozen=True)
class LocalTensorIndex:
    """Key of a saved shard (tensor name + its offset)."""
    tensor_key: str
    global_offset: Tuple[int, ...]


@dataclass
class Metadata:
    # tensor key -> all shards saved for it
    state_dict_metadata: Dict[str, List[LocalTensorMetadata]] = field(
        default_factory=dict)
    # shard -> file name (relative to checkpoint dir) and array name inside it
    storage_metadata: Dict[LocalTensorIndex, str] = field(default_factory=dict)
    # original (possibly nested) key -> flat key mapping
    flat_mapping: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    # non-tensor state (step counters, lr-scheduler scalars, …), stored
    # directly in the metadata pickle
    aux: Dict[str, object] = field(default_factory=dict)
    # data-file name (as on disk, relative to the checkpoint dir) -> CRC32
    # of its bytes; load verifies before trusting a shard. Absent on
    # pre-checksum checkpoints — read with ``getattr(meta, "checksums", {})``
    # since old pickles restore without the field.
    checksums: Dict[str, int] = field(default_factory=dict)
