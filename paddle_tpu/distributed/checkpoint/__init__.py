"""``paddle_tpu.distributed.checkpoint`` — sharded save/load with
reshard-on-load (reference: python/paddle/distributed/checkpoint/ —
SURVEY.md §5.4 tier 3)."""

from .save_state_dict import save_state_dict  # noqa: F401
from .load_state_dict import load_state_dict, read_metadata  # noqa: F401
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata  # noqa: F401
from .utils import (flatten_state_dict, unflatten_state_dict,  # noqa: F401
                    CheckpointCorruptError)
from .async_save import async_save_state_dict, AsyncSaveFuture, TrainState  # noqa: F401
