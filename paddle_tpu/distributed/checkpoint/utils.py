"""Checkpoint helpers (reference:
python/paddle/distributed/checkpoint/utils.py:§0).

Also the single place checkpoint files are allowed to be written:
``atomic_write`` stages to ``<path>.tmp``, fsyncs, CRC32s the bytes and
renames into place, so a crash at any point leaves either the old file or
nothing — never a torn write (`tests/test_resilience.py` lints that no
other write-mode ``open`` exists under this package)."""

from __future__ import annotations

import os
import zlib
from typing import Any, Callable, Dict, Tuple

import numpy as np

from ...core.tensor import Tensor


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed checksum verification or cannot be decoded
    (truncated shard, torn metadata, bad pickle)."""


def file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename into it is durable (POSIX; no-op where
    directories cannot be opened)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, writer: Callable, do_fsync: bool = True) -> int:
    """Durably write ``path`` via stage-then-rename; returns the CRC32.

    ``writer(fileobj)`` produces the bytes into a ``<path>.tmp`` handle;
    the data is fsynced, checksummed from disk, then renamed over ``path``
    (atomic on POSIX) — readers never observe a partial file.
    """
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        writer(f)
        f.flush()
        if do_fsync:
            os.fsync(f.fileno())
    crc = file_crc32(tmp)
    os.replace(tmp, path)
    return crc


def verify_crc32(path: str, expected: int) -> None:
    actual = file_crc32(path)
    if actual != int(expected):
        raise CheckpointCorruptError(
            f"checksum mismatch for {path!r}: recorded {int(expected)}, "
            f"on-disk {actual} (truncated or corrupted shard)")


def flatten_state_dict(state_dict: Dict) -> Tuple[Dict[str, Any], Dict[str, Tuple[str, ...]]]:
    """Flatten a nested state dict into {joined_key: value}; returns the flat
    dict and the mapping flat_key -> original key path."""
    flat: Dict[str, Any] = {}
    mapping: Dict[str, Tuple[str, ...]] = {}

    def rec(prefix: Tuple[str, ...], d):
        if isinstance(d, dict):
            for k, v in d.items():
                rec(prefix + (str(k),), v)
        else:
            key = ".".join(prefix)
            if key in flat:
                raise ValueError(f"duplicate flat key {key!r}")
            flat[key] = d
            mapping[key] = prefix
    rec((), state_dict)
    return flat, mapping


def unflatten_state_dict(flat: Dict[str, Any],
                         mapping: Dict[str, Tuple[str, ...]]) -> Dict:
    out: Dict = {}
    for key, value in flat.items():
        path = mapping.get(key, (key,))
        d = out
        for p in path[:-1]:
            d = d.setdefault(p, {})
        d[path[-1]] = value
    return out


def to_array(value):
    """numpy view of a Tensor / jax array / scalar (bf16-safe)."""
    if isinstance(value, Tensor):
        value = value._value
    return np.asarray(value)


def offsets_from_index(index, shape) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """(global_offset, local_shape) from a jax shard ``index`` (tuple of
    slices over the global shape)."""
    if not shape:
        return (), ()
    offs, lshape = [], []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        offs.append(start)
        lshape.append(stop - start)
    return tuple(offs), tuple(lshape)
