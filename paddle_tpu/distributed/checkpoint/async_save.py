"""Async checkpointing + full train-state capture.

SURVEY.md §5.4 calls for the TPU equivalent to go beyond the reference:
"async, multi-host GDA checkpoint with reshard-on-load, plus
optimizer-state + dataloader-position capture". This module adds:

* ``async_save_state_dict`` — snapshot device arrays to host (blocking
  only for the device→host copy), then write shard files on a background
  thread; ``AsyncSaveFuture.result()`` joins. Training resumes while IO
  runs — the orbax-style async pattern.
* ``TrainState`` capture/restore — model params, optimizer state, LR
  scheduler, global step and dataloader position in one state_dict, so an
  elastic restart (launch controller, SURVEY §3.6) resumes mid-epoch.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import numpy as np

from .save_state_dict import save_state_dict


class AsyncSaveFuture:
    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        self._ok = False  # set by the writer only after a successful commit
        self.path: Optional[str] = None

    def result(self, timeout: Optional[float] = None) -> str:
        """Join the writer. Raises ``TimeoutError`` if it is still running
        after ``timeout`` seconds, re-raises the writer's exception if it
        failed, and only returns ``path`` once the write actually
        completed — never a path to bytes that were not written."""
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError(
                    f"async checkpoint to {self.path!r} still writing "
                    f"after {timeout}s")
        if self._exc is not None:
            raise self._exc
        if not self._ok:
            raise RuntimeError(
                f"async checkpoint to {self.path!r} never ran to completion")
        return self.path

    def exception(self, timeout: Optional[float] = None):
        """Join and return the writer's exception (None on success);
        TimeoutError still raises — 'no result yet' is not 'no error'."""
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError(
                    f"async checkpoint to {self.path!r} still writing "
                    f"after {timeout}s")
        return self._exc

    def done(self) -> bool:
        return self._thread is None or not self._thread.is_alive()


_last_save = [None]  # serialize overlapping async saves


def host_snapshot(state_dict):
    """Materialise a nested state dict to host numpy arrays NOW (the
    blocking device→host copy of the async-save pattern)."""
    if isinstance(state_dict, dict):
        return {k: host_snapshot(v) for k, v in state_dict.items()}
    v = state_dict
    if hasattr(v, "_value"):
        v = v._value
    return np.asarray(v)


def spawn_async_writer(fut: AsyncSaveFuture, write) -> AsyncSaveFuture:
    """Run ``write()`` on a daemon thread, serialized after any in-flight
    async save (ordering must be preserved for resume correctness). A
    previous save's error is NOT re-raised here — it belongs to the caller
    holding that future, and a failed save must not wedge subsequent ones.
    """
    prev = _last_save[0]
    if prev is not None and prev._thread is not None:
        prev._thread.join()

    def runner():
        try:
            write()
            fut._ok = True
        except BaseException as e:  # surfaced at result()
            fut._exc = e

    fut._thread = threading.Thread(target=runner, daemon=True)
    fut._thread.start()
    _last_save[0] = fut
    return fut


def async_save_state_dict(state_dict: Dict[str, Any], path: str,
                          process_group=None, coordinator_rank: int = 0
                          ) -> AsyncSaveFuture:
    """Device→host snapshot now; file writes on a background thread."""
    snapshot = host_snapshot(state_dict)
    fut = AsyncSaveFuture()
    fut.path = path

    def write():
        save_state_dict(snapshot, path, process_group=process_group,
                        coordinator_rank=coordinator_rank)

    return spawn_async_writer(fut, write)


def _wrap_leaves(tree):
    """Checkpoint IO wants Tensor leaves; wrap scalars/arrays (e.g. the
    optimizer's python-int @step, LR-scheduler floats)."""
    from ...core.tensor import Tensor
    if isinstance(tree, dict):
        return {k: _wrap_leaves(v) for k, v in tree.items()}
    if isinstance(tree, Tensor):
        return tree
    return Tensor(np.asarray(tree))


def _unwrap_leaves(tree):
    """Back to python/numpy scalars for consumers like optimizer
    set_state_dict (0-d arrays become python scalars)."""
    from ...core.tensor import Tensor
    if isinstance(tree, dict):
        return {k: _unwrap_leaves(v) for k, v in tree.items()}
    if isinstance(tree, Tensor):
        v = np.asarray(tree._value)
        return v.item() if v.ndim == 0 else v
    return tree


class TrainState:
    """One-call capture/restore of everything resume needs."""

    def __init__(self, model=None, optimizer=None, lr_scheduler=None):
        self.model = model
        self.optimizer = optimizer
        self.lr_scheduler = lr_scheduler
        self.global_step = 0
        self.epoch = 0
        self.batch_in_epoch = 0  # dataloader position

    def step(self, batches: int = 1) -> None:
        self.global_step += batches
        self.batch_in_epoch += batches

    def next_epoch(self) -> None:
        self.epoch += 1
        self.batch_in_epoch = 0

    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "progress": {
                "global_step": np.asarray(self.global_step, np.int64),
                "epoch": np.asarray(self.epoch, np.int64),
                "batch_in_epoch": np.asarray(self.batch_in_epoch, np.int64),
            }
        }
        if self.model is not None:
            out["model"] = self.model.state_dict()
        if self.optimizer is not None:
            out["optimizer"] = self.optimizer.state_dict()
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler,
                                                     "state_dict"):
            out["lr_scheduler"] = self.lr_scheduler.state_dict()
        return _wrap_leaves(out)

    def set_state_dict(self, state: Dict[str, Any]) -> None:
        state = _unwrap_leaves(state)
        prog = state.get("progress", {})
        self.global_step = int(prog.get("global_step", 0))
        self.epoch = int(prog.get("epoch", 0))
        self.batch_in_epoch = int(prog.get("batch_in_epoch", 0))
        if self.model is not None and "model" in state:
            self.model.set_state_dict(state["model"])
        if self.optimizer is not None and "optimizer" in state:
            self.optimizer.set_state_dict(state["optimizer"])
        if self.lr_scheduler is not None and "lr_scheduler" in state and \
                hasattr(self.lr_scheduler, "set_state_dict"):
            self.lr_scheduler.set_state_dict(state["lr_scheduler"])

    def skip_batches(self, loader):
        """Fast-forward a dataloader to the captured mid-epoch position.

        Correct under shuffle only when the sampler's order is a pure
        function of the epoch: the loader's batch_sampler is pinned to the
        captured epoch first (RandomSampler/DistributedBatchSampler both
        derive their permutation from (seed, epoch))."""
        bs = getattr(loader, "batch_sampler", None)
        if bs is not None and hasattr(bs, "set_epoch"):
            bs.set_epoch(self.epoch)
        it = iter(loader)
        for _ in range(self.batch_in_epoch):
            next(it)
        return it
