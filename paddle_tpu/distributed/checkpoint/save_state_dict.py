"""Sharded checkpoint save.

Rebuild of python/paddle/distributed/checkpoint/save_state_dict.py:§0
(SURVEY.md §5.4): each rank writes the shards it owns into its own data file
plus a global ``.metadata`` describing every shard — load can then reshard to
any topology. Single-controller jax: "this process" owns every addressable
shard; replicas are deduped by shard index so a fully-replicated tensor is
written exactly once. On multi-host deployments each host writes only the
shards whose first replica it holds (same dedup rule keyed by process index).
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, Optional

import numpy as np
import jax

from ...core.tensor import Tensor
from .metadata import LocalTensorIndex, LocalTensorMetadata, Metadata
from .utils import (atomic_write, flatten_state_dict, fsync_dir,
                    offsets_from_index, to_array)

_BF16 = "bfloat16"


def _np_payload(arr: np.ndarray):
    """bf16 arrays round-trip as uint16 views (npz has no bf16)."""
    if arr.dtype == jax.numpy.bfloat16:
        return arr.view(np.uint16), _BF16
    return arr, str(arr.dtype)


def save_state_dict(state_dict: Dict, path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id: Optional[int] = None) -> None:
    """Write ``state_dict`` (possibly nested; values Tensor/jax arrays) as a
    sharded checkpoint directory: ``<rank>_<id>.distcp`` data files +
    ``<id>.metadata``."""
    from .. import env as _env
    os.makedirs(path, exist_ok=True)
    rank = _env.get_rank()
    uid = 0 if unique_id is None else int(unique_id)

    flat, mapping = flatten_state_dict(state_dict)
    meta = Metadata(flat_mapping=mapping)
    data_file = f"{rank}_{uid}.distcp"
    payload: Dict[str, np.ndarray] = {}
    dtypes: Dict[str, str] = {}

    for key, value in flat.items():
        v = value._value if isinstance(value, Tensor) else value
        if not isinstance(value, Tensor) and not hasattr(v, "shape"):
            # non-tensor state (ints, floats, strings): rides in metadata
            meta.aux[key] = v
            continue
        shards_meta = []
        if hasattr(v, "addressable_shards") and v.addressable_shards:
            seen = set()
            gshape = tuple(v.shape)
            for shard in v.addressable_shards:
                # multi-host dedup: a shard is written by the process holding
                # its first replica; single-controller sees replica 0 of
                # every shard, so the offset set below also dedups locally
                if getattr(shard, "replica_id", 0) != 0:
                    continue
                off, lshape = offsets_from_index(shard.index, gshape)
                if off in seen:
                    continue  # replica of an already-recorded shard
                seen.add(off)
                arr = np.asarray(shard.data)
                name = f"{key}|{'_'.join(map(str, off)) or 'scalar'}"
                arr2, dt = _np_payload(arr)
                payload[name] = arr2
                dtypes[name] = dt
                lm = LocalTensorMetadata(off, tuple(lshape) or gshape, dt)
                shards_meta.append(lm)
                meta.storage_metadata[LocalTensorIndex(key, off)] = \
                    f"{data_file}::{name}"
        else:
            arr = to_array(v)
            off = tuple([0] * arr.ndim)
            name = f"{key}|{'_'.join(map(str, off)) or 'scalar'}"
            arr2, dt = _np_payload(arr)
            payload[name] = arr2
            dtypes[name] = dt
            shards_meta.append(LocalTensorMetadata(off, tuple(arr.shape), dt))
            meta.storage_metadata[LocalTensorIndex(key, off)] = \
                f"{data_file}::{name}"
        meta.state_dict_metadata[key] = shards_meta

    # Every file goes through atomic_write (stage + fsync + rename): a crash
    # mid-save leaves only *.tmp litter, never a torn file the loader could
    # half-read. Data files land first, metadata LAST — its presence is the
    # rank-local commit point — and the recorded CRC32s let load verify each
    # shard file before trusting it.
    npz_name = data_file + ".npz"  # np.savez appends .npz to str paths; we
    # pass a handle, so name the staged file explicitly
    meta.checksums[npz_name] = atomic_write(
        os.path.join(path, npz_name), lambda f: np.savez(f, **payload))
    meta.checksums[f"{data_file}.dtypes"] = atomic_write(
        os.path.join(path, f"{data_file}.dtypes"),
        lambda f: pickle.dump(dtypes, f))
    # every rank writes its own metadata covering the shards it owns; the
    # loader merges all *.metadata files, so multi-host checkpoints stay
    # complete without a gather step
    atomic_write(os.path.join(path, f"{rank}_{uid}.metadata"),
                 lambda f: pickle.dump(meta, f))
    fsync_dir(path)
