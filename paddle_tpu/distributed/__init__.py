"""``paddle_tpu.distributed`` — collective API, fleet, parallel engines.

Parity with python/paddle/distributed/ of the reference (SURVEY.md §2.3/§2.4).
"""

from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, ParallelEnv,
    is_initialized,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, all_reduce, all_gather, reduce_scatter,
    broadcast, reduce, scatter, alltoall, all_to_all, send, recv, barrier,
    gather, wait, get_backend, destroy_process_group, all_gather_object,
    broadcast_object_list, scatter_object_list,
)
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup,
    get_hybrid_communicate_group, set_hybrid_communicate_group,
)
from . import fleet  # noqa: F401
from . import meta_parallel  # noqa: F401
from . import sharding  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import save_state_dict, load_state_dict  # noqa: F401
from . import env  # noqa: F401
from .auto_parallel.api import (  # noqa: F401
    shard_tensor, ProcessMesh, Shard, Replicate, Partial, reshard,
    shard_layer, dtensor_from_fn, Strategy,
)
from .spawn import spawn  # noqa: F401
from . import launch  # noqa: F401
from . import communication  # noqa: F401
from .communication.p2p import (  # noqa: F401
    P2POp, batch_isend_irecv, isend, irecv,
)
from .communication import stream  # noqa: F401
