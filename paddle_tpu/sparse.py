"""``paddle_tpu.sparse`` — COO/CSR sparse tensors.

Rebuild of paddle's sparse surface (python/paddle/sparse/, phi
SparseCooTensor/SparseCsrTensor — paddle/phi/core/sparse_coo_tensor.cc,
SURVEY.md §2.1 DenseTensor row; flagged absent in VERDICT round 1).

TPU-first design: a sparse tensor is (indices, values) with a STATIC nnz —
XLA needs static shapes, so operations preserve nnz (coalescing with a
fixed output budget) and compute lowers to gather/segment ops on the MXU
rather than dynamic sparse kernels. This mirrors how the reference's
SelectedRows (rows + dense chunk) represents embedding gradients.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .core.tensor import Tensor
from .core.dispatch import apply


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor: ``indices`` (ndim, nnz) int32, ``values`` (nnz,
    *dense_dims), dense ``shape``. Duplicate coordinates are allowed and sum
    on densification (paddle semantics before coalesce)."""

    def __init__(self, indices, values, shape):
        self.indices = _unwrap(indices).astype(jnp.int32)
        self.values = values if isinstance(values, Tensor) else Tensor(values)
        self.shape = tuple(int(s) for s in shape)
        if self.indices.ndim != 2:
            raise ValueError("indices must be (sparse_ndim, nnz)")

    # -- introspection ------------------------------------------------------
    def nnz(self) -> int:
        return int(self.indices.shape[1])

    @property
    def dtype(self):
        return self.values.dtype

    def is_sparse_coo(self) -> bool:
        return True

    def is_sparse_csr(self) -> bool:
        return False

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    # -- conversions --------------------------------------------------------
    def to_dense(self) -> Tensor:
        shape = self.shape
        sd = self.indices.shape[0]

        def fn(idx, vals):
            flat_shape = (int(np.prod(shape[:sd])),) + tuple(shape[sd:])
            strides = np.cumprod((1,) + shape[:sd][::-1])[::-1][1:]
            strides = jnp.asarray(np.ascontiguousarray(strides), jnp.int32)
            flat_idx = jnp.sum(idx * strides[:, None], axis=0)
            dense = jnp.zeros(flat_shape, vals.dtype).at[flat_idx].add(vals)
            return dense.reshape(shape)

        return apply(fn, Tensor(self.indices), self.values,
                     op_name="sparse_to_dense")

    def coalesce(self, max_nnz: Optional[int] = None) -> "SparseCooTensor":
        """Merge duplicate coordinates (static nnz: merged slots keep one
        representative, freed slots park at coordinate 0 with value 0 —
        to_dense output is identical). With ``max_nnz`` the result is
        trimmed to that budget: distinct coordinates occupy a prefix after
        the merge, so the trim is lossless whenever the distinct count fits
        (checked eagerly; a traced overflow cannot be detected)."""
        sd = self.indices.shape[0]
        strides = np.cumprod((1,) + self.shape[:sd][::-1])[::-1][1:]
        strides = jnp.asarray(np.ascontiguousarray(strides), jnp.int32)
        flat = jnp.sum(self.indices * strides[:, None], axis=0)
        uniq, inv = jnp.unique(flat, return_inverse=True,
                               size=flat.shape[0], fill_value=-1)
        summed = jax.ops.segment_sum(self.values._value, inv, flat.shape[0])
        keep = uniq >= 0
        safe = jnp.where(keep, uniq, 0)
        new_idx = jnp.stack([(safe // s) % d for s, d in
                             zip(np.ascontiguousarray(strides),
                                 self.shape[:sd])])
        vals = jnp.where(
            keep.reshape((-1,) + (1,) * (self.values._value.ndim - 1)),
            summed, 0.0)
        out = SparseCooTensor(new_idx, Tensor(vals.astype(self.values._value.dtype)),
                              self.shape)
        if max_nnz is not None and max_nnz < out.nnz():
            try:
                distinct = int(jnp.sum(keep))
            except Exception:
                distinct = None  # traced: trust the caller's budget
            if distinct is not None and distinct > max_nnz:
                raise ValueError(
                    f"coalesce: {distinct} distinct coordinates exceed "
                    f"max_nnz={max_nnz}")
            # jnp.unique pads fill_value at the END: distinct coords occupy
            # the prefix, so a head-trim is lossless within the budget
            out = SparseCooTensor(out.indices[:, :max_nnz],
                                  Tensor(out.values._value[:max_nnz]),
                                  self.shape)
        return out

    # -- math ---------------------------------------------------------------
    def __add__(self, other: "SparseCooTensor") -> "SparseCooTensor":
        """Sparse + sparse. The result is coalesced and, when the combined
        support fits, trimmed back to max(nnz_a, nnz_b) — so a repeated
        accumulation over a fixed support (the SelectedRows embedding-grad
        loop) keeps a STATIC nnz instead of growing (and recompiling) every
        step. Disjoint supports keep the full nnz_a + nnz_b."""
        if not isinstance(other, SparseCooTensor):
            raise TypeError("sparse + dense: use to_dense() explicitly")
        if other.shape != self.shape:
            raise ValueError("shape mismatch")
        idx = jnp.concatenate([self.indices, other.indices], axis=1)
        vals = Tensor(jnp.concatenate([self.values._value,
                                       other.values._value], axis=0))
        merged = SparseCooTensor(idx, vals, self.shape)
        budget = max(self.nnz(), other.nnz())
        try:
            return merged.coalesce(max_nnz=budget)
        except ValueError:  # combined support larger than either input
            return merged.coalesce()

    def __mul__(self, scalar):
        return SparseCooTensor(self.indices, self.values * scalar, self.shape)

    __rmul__ = __mul__

    def matmul(self, dense) -> Tensor:
        """(M, K) sparse @ (K, N) dense → (M, N) dense, via gather over K
        and a segment-sum over the row coordinate (MXU-free scatter form —
        the SelectedRows-style embedding-gradient product)."""
        if len(self.shape) != 2 or self.indices.shape[0] != 2:
            raise ValueError("matmul needs a 2-D sparse matrix")
        m = self.shape[0]

        def fn(idx, vals, d):
            rows, cols = idx[0], idx[1]
            contrib = vals[:, None] * d[cols]            # (nnz, N)
            return jax.ops.segment_sum(contrib, rows, m)

        return apply(fn, Tensor(self.indices), self.values,
                     dense if isinstance(dense, Tensor) else Tensor(dense),
                     op_name="sparse_matmul")

    def transpose(self, perm: Sequence[int]) -> "SparseCooTensor":
        perm = list(perm)
        new_idx = self.indices[jnp.asarray(perm, jnp.int32)]
        return SparseCooTensor(new_idx, self.values,
                               tuple(self.shape[p] for p in perm))


class SparseCsrTensor:
    """CSR sparse matrix: crows (M+1,), cols (nnz,), values (nnz,)."""

    def __init__(self, crows, cols, values, shape):
        self.crows = _unwrap(crows).astype(jnp.int32)
        self.cols = _unwrap(cols).astype(jnp.int32)
        self.values = values if isinstance(values, Tensor) else Tensor(values)
        self.shape = tuple(int(s) for s in shape)

    def nnz(self) -> int:
        return int(self.cols.shape[0])

    def is_sparse_csr(self) -> bool:
        return True

    def to_dense(self) -> Tensor:
        m, n = self.shape

        def fn(crows, cols, vals):
            counts = crows[1:] - crows[:-1]
            rows = jnp.repeat(jnp.arange(m, dtype=jnp.int32), counts,
                              total_repeat_length=cols.shape[0])
            return jnp.zeros((m, n), vals.dtype).at[rows, cols].add(vals)

        return apply(fn, Tensor(self.crows), Tensor(self.cols), self.values,
                     op_name="sparse_csr_to_dense")


# -- constructors (paddle.sparse API names) ---------------------------------
def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True) -> SparseCooTensor:
    idx = _unwrap(indices)
    vals = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
    if dtype is not None:
        vals = vals.astype(dtype)
    if shape is None:
        shape = tuple(int(x) for x in np.asarray(idx).max(axis=1) + 1)
    t = SparseCooTensor(idx, vals, shape)
    t.values.stop_gradient = stop_gradient
    return t


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseCsrTensor:
    vals = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
    if dtype is not None:
        vals = vals.astype(dtype)
    return SparseCsrTensor(crows, cols, vals, shape)


def to_sparse_coo(dense: Tensor, sparse_dim: Optional[int] = None,
                  nnz: Optional[int] = None) -> SparseCooTensor:
    """Densify → COO with a static nnz budget (default: exact count at trace
    time via host round-trip; pass ``nnz`` to keep it jit-friendly)."""
    v = _unwrap(dense)
    sd = sparse_dim or v.ndim
    flat = np.asarray(v.reshape((-1,) + v.shape[sd:]))
    mask = np.any(flat != 0, axis=tuple(range(1, flat.ndim))) \
        if flat.ndim > 1 else flat != 0
    pos = np.nonzero(mask)[0]
    if nnz is not None:
        pos = pos[:nnz]
        pad = nnz - pos.size
        if pad > 0:
            pos = np.concatenate([pos, np.zeros(pad, pos.dtype)])
    idx = np.stack(np.unravel_index(pos, v.shape[:sd]))
    vals = flat[pos]
    if nnz is not None and pad > 0:
        vals = vals.copy()
        vals[len(pos) - pad:] = 0
    return SparseCooTensor(jnp.asarray(idx, jnp.int32), Tensor(jnp.asarray(vals)),
                           v.shape)


# -- functional surface ------------------------------------------------------
def add(a: SparseCooTensor, b: SparseCooTensor) -> SparseCooTensor:
    return a + b


def matmul(a: SparseCooTensor, dense) -> Tensor:
    return a.matmul(dense)


def relu(a: SparseCooTensor) -> SparseCooTensor:
    from .nn import functional as F
    return SparseCooTensor(a.indices, F.relu(a.values), a.shape)


def is_sparse(x) -> bool:
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))
