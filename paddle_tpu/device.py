"""``paddle_tpu.device`` — device query/control namespace.

Parity with python/paddle/device/ of the reference. The accelerator
here is whatever jax exposes (TPU under axon, CPU in tests); the CUDA/
XPU sub-namespaces exist with honest "not compiled in" answers, the
same shape the reference gives on a CPU-only build.
"""

from __future__ import annotations

import types

import jax

from .core.place import current_place, set_device, get_device  # noqa: F401

__all__ = [
    "set_device", "get_device", "get_all_device_type",
    "get_available_device", "get_device_count", "device_count",
    "synchronize", "is_compiled_with_cuda", "is_compiled_with_rocm",
    "is_compiled_with_xpu", "is_compiled_with_distribute",
    "cuda", "xpu",
]


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()} | {"cpu"})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_device_count() -> int:
    return len(jax.devices())


device_count = get_device_count


def synchronize(device=None):
    """Block until pending work on the device finishes. XLA programs
    synchronize through value dependencies; this drains the async
    dispatch queue (jax.effects_barrier would need a live trace)."""
    for d in jax.live_arrays() if hasattr(jax, "live_arrays") else []:
        jax.block_until_ready(d)


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_distribute() -> bool:
    return True  # jax.distributed + the launcher stack


def _stub_ns(name: str) -> types.ModuleType:
    m = types.ModuleType(f"{__name__}.{name}")
    m.device_count = lambda: 0
    m.current_device = lambda: None
    m.get_device_name = lambda device=None: None
    m.get_device_capability = lambda device=None: None
    m.synchronize = lambda device=None: None
    m.empty_cache = lambda: None
    m.max_memory_allocated = lambda device=None: 0
    m.max_memory_reserved = lambda device=None: 0
    m.memory_allocated = lambda device=None: 0
    m.memory_reserved = lambda device=None: 0
    return m


#: reference paddle.device.cuda / paddle.device.xpu — zero devices here
cuda = _stub_ns("cuda")
xpu = _stub_ns("xpu")
