"""Optimizers.

Rebuild of python/paddle/optimizer/{optimizer,sgd,momentum,adam,adamw,lamb}.py
+ the fused CUDA kernels paddle/phi/kernels/gpu/{adam,adamw}_kernel.cu
(SURVEY.md §2.5). The per-parameter update rule is a *pure jax function*
(`_update`), so the same optimizer drives both the eager `.step()` path and
compiled train steps (paddle_tpu.jit lifts state into pytrees and maps
`_update` across them — XLA then fuses the whole update, which is what the
reference's multi_tensor/fused kernels hand-achieve).

Multi-precision (`multi_precision=True`) keeps fp32 master weights for
bf16/fp16 params — parity with the reference's master-weight path.
"""

from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor
from ..observability.profiling import chain_armed as _chain_armed
from ..observability.profiling import note_chain as _note_chain
from .lr import LRScheduler
from .clip import ClipGradBase


class Optimizer:
    _state_keys: Tuple[str, ...] = ()

    #: jit.fusion's optimizer_chain megaregion, when installed
    #: (install_optimizer_fusion); step() then delegates — byte-identical
    #: updates in ONE dispatch instead of the per-param eager chain
    _fused_step = None

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip: Optional[ClipGradBase] = None, multi_precision=False,
                 name=None):
        if parameters is None:
            raise ValueError("parameters must be provided in dygraph mode")
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._weight_decay = self._parse_wd(weight_decay)
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        # state: id(param) -> dict of jnp arrays
        self._accumulators: Dict[int, Dict[str, Any]] = {}
        self._step_count = 0

    @staticmethod
    def _parse_wd(weight_decay):
        if weight_decay is None:
            return 0.0
        if isinstance(weight_decay, (int, float)):
            return float(weight_decay)
        # L2Decay-style object with _coeff/_regularization_coeff
        for attr in ("_regularization_coeff", "_coeff", "coeff"):
            if hasattr(weight_decay, attr):
                return float(getattr(weight_decay, attr))
        return float(weight_decay)

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = value

    # -- state ---------------------------------------------------------------
    def _init_state(self, p: Parameter) -> Dict[str, Any]:
        state: Dict[str, Any] = {}
        if self._multi_precision and p._value.dtype in (jnp.bfloat16, jnp.float16):
            state["master"] = p._value.astype(jnp.float32)
        return state

    def _state_of(self, p: Parameter) -> Dict[str, Any]:
        s = self._accumulators.get(id(p))
        if s is None:
            s = self._init_state(p)
            self._accumulators[id(p)] = s
        return s

    # -- the pure per-param update rule (overridden by subclasses) ----------
    def _update(self, value, grad, state: Dict[str, Any], lr, step):
        raise NotImplementedError

    def _decay_enabled(self, p: Parameter) -> bool:
        """Whether weight decay applies to this param (AdamW's
        apply_decay_param_fun / Lamb's exclude_from_weight_decay_fn);
        consulted by both the eager step and the compiled TrainStep."""
        fn = getattr(self, "_apply_decay_param_fun", None)
        if fn is not None:
            return bool(fn(p.name))
        return True

    # -- eager step ----------------------------------------------------------
    def step(self):
        if self._fused_step is not None:
            return self._fused_step.step()
        # armed-only continuous-profiling taps: the eager grad-transform
        # -> per-param-update chain is the fusion pass's optimizer_chain
        # signature (jit/fusion.py); disarmed cost is one list index
        armed = _chain_armed[0]
        self._step_count += 1
        lr = self.get_lr()
        params_grads = [(p, p._grad_value) for p in self._parameter_list
                        if p._grad_value is not None and p.trainable]
        if self._grad_clip is not None:
            t0 = time.perf_counter_ns() if armed else 0
            params_grads = self._grad_clip(params_grads)
            if armed:
                _note_chain(op_name="grad_clip",
                            dur_ns=time.perf_counter_ns() - t0)
        saved_wd = self._weight_decay
        for p, g in params_grads:
            if g is None:
                continue
            t0 = time.perf_counter_ns() if armed else 0
            state = self._state_of(p)
            plr = lr * p.optimize_attr.get("learning_rate", 1.0)
            self._weight_decay = saved_wd if self._decay_enabled(p) else 0.0
            new_v, new_state = self._update(p._value, g, dict(state), plr,
                                            self._step_count)
            p._value = new_v
            self._accumulators[id(p)] = new_state
            if armed:
                _note_chain(op_name="optimizer_update",
                            dur_ns=time.perf_counter_ns() - t0)
        self._weight_decay = saved_wd

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameter_list:
            p._grad_value = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    # -- serialization -------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"@step": self._step_count}
        for p in self._parameter_list:
            s = self._accumulators.get(id(p))
            if s is None:
                continue
            for k, v in s.items():
                out[f"{p.name}.{k}"] = Tensor(v) if not isinstance(v, Tensor) else v
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state: Dict[str, Any]):
        self._step_count = int(state.get("@step", self._step_count))
        if "LR_Scheduler" in state and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        by_name = {p.name: p for p in self._parameter_list}
        # Positional fallback: auto-generated param names (generated_tensor_N)
        # differ across processes, so a checkpoint resumed in a fresh process
        # would silently drop every accumulator on the name match alone.
        # state_dict() emits slots grouped per parameter in _parameter_list
        # order, so the i-th distinct saved name maps to the i-th parameter.
        # All-or-nothing: positional is used for EVERY key as soon as any
        # saved name is unknown here (and the group count matches) — mixing
        # the two maps would bind partially-overlapping generated names to
        # the wrong parameters.
        saved_names: list = []
        for key in state:
            if key in ("@step", "LR_Scheduler"):
                continue
            pname = key.rpartition(".")[0]
            if pname not in saved_names:
                saved_names.append(pname)
        # Key order out of a checkpoint is not trustworthy (multi-rank
        # metadata merges interleave it); auto-generated names carry the
        # saving process's creation counter, so sort by it to recover the
        # true parameter order before zipping positionally.
        suffixes = [re.search(r"(\d+)$", n) for n in saved_names]
        if all(suffixes):
            saved_names.sort(key=lambda n: int(re.search(r"(\d+)$", n)
                                               .group(1)))
        by_pos = {}
        if len(saved_names) == len(self._parameter_list) and \
                any(n not in by_name for n in saved_names):
            by_pos = {n: p for n, p in zip(saved_names, self._parameter_list)}
        lookup = by_pos or by_name
        for key, v in state.items():
            if key in ("@step", "LR_Scheduler"):
                continue
            pname, _, slot = key.rpartition(".")
            p = lookup.get(pname)
            if p is None:
                continue
            s = self._state_of(p)
            s[slot] = v._value if isinstance(v, Tensor) else jnp.asarray(v)

    set_dict = set_state_dict

    # -- helpers shared by subclasses ---------------------------------------
    def _cast_for_update(self, value, state):
        """Return the fp32 compute value (master weight if kept)."""
        if "master" in state:
            return state["master"]
        return value.astype(jnp.float32) if value.dtype in (jnp.bfloat16, jnp.float16) \
            else value

    def _finish_update(self, value, new_fp32, state):
        if "master" in state:
            state["master"] = new_fp32
            return new_fp32.astype(value.dtype), state
        return new_fp32.astype(value.dtype), state


class SGD(Optimizer):
    def _update(self, value, grad, state, lr, step):
        v32 = self._cast_for_update(value, state)
        g32 = grad.astype(jnp.float32)
        if self._weight_decay:
            g32 = g32 + self._weight_decay * v32
        return self._finish_update(value, v32 - lr * g32, state)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _init_state(self, p):
        s = super()._init_state(p)
        s["velocity"] = jnp.zeros(p._value.shape, jnp.float32)
        return s

    def _update(self, value, grad, state, lr, step):
        v32 = self._cast_for_update(value, state)
        g32 = grad.astype(jnp.float32)
        if self._weight_decay:
            g32 = g32 + self._weight_decay * v32
        vel = self._momentum * state["velocity"] + g32
        state["velocity"] = vel
        if self._use_nesterov:
            new = v32 - lr * (g32 + self._momentum * vel)
        else:
            new = v32 - lr * vel
        return self._finish_update(value, new, state)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _init_state(self, p):
        s = super()._init_state(p)
        s["moment1"] = jnp.zeros(p._value.shape, jnp.float32)
        s["moment2"] = jnp.zeros(p._value.shape, jnp.float32)
        return s

    def _decoupled_wd(self):
        return False

    def _update(self, value, grad, state, lr, step):
        v32 = self._cast_for_update(value, state)
        g32 = grad.astype(jnp.float32)
        wd = self._weight_decay
        if wd and not self._decoupled_wd():
            g32 = g32 + wd * v32
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * g32 * g32
        state["moment1"] = m
        state["moment2"] = v
        mhat = m / (1 - self._beta1 ** step)
        vhat = v / (1 - self._beta2 ** step)
        upd = mhat / (jnp.sqrt(vhat) + self._epsilon)
        if wd and self._decoupled_wd():
            upd = upd + wd * v32
        return self._finish_update(value, v32 - lr * upd, state)


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision, name)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled_wd(self):
        return True

    def step(self):
        # honour apply_decay_param_fun by zeroing wd per-param
        if self._apply_decay_param_fun is None or \
                self._fused_step is not None:
            # the fused megaregion handles per-param decay exclusion
            # itself (it bakes _decay_enabled per parameter)
            return super().step()
        armed = _chain_armed[0]
        wd = self._weight_decay
        self._step_count += 1
        lr = self.get_lr()
        params_grads = [(p, p._grad_value) for p in self._parameter_list
                        if p._grad_value is not None and p.trainable]
        if self._grad_clip is not None:
            t0 = time.perf_counter_ns() if armed else 0
            params_grads = self._grad_clip(params_grads)
            if armed:
                _note_chain(op_name="grad_clip",
                            dur_ns=time.perf_counter_ns() - t0)
        for p, g in params_grads:
            if g is None:
                continue
            t0 = time.perf_counter_ns() if armed else 0
            state = self._state_of(p)
            self._weight_decay = wd if self._apply_decay_param_fun(p.name) else 0.0
            plr = lr * p.optimize_attr.get("learning_rate", 1.0)
            new_v, new_state = self._update(p._value, g, dict(state), plr,
                                            self._step_count)
            p._value = new_v
            self._accumulators[id(p)] = new_state
            if armed:
                _note_chain(op_name="optimizer_update",
                            dur_ns=time.perf_counter_ns() - t0)
        self._weight_decay = wd


class Adamax(Adam):
    def _init_state(self, p):
        s = Optimizer._init_state(self, p)
        s["moment1"] = jnp.zeros(p._value.shape, jnp.float32)
        s["inf_norm"] = jnp.zeros(p._value.shape, jnp.float32)
        return s

    def _update(self, value, grad, state, lr, step):
        v32 = self._cast_for_update(value, state)
        g32 = grad.astype(jnp.float32)
        if self._weight_decay:
            g32 = g32 + self._weight_decay * v32
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g32))
        state["moment1"] = m
        state["inf_norm"] = u
        new = v32 - lr / (1 - self._beta1 ** step) * m / (u + self._epsilon)
        return self._finish_update(value, new, state)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _decay_enabled(self, p):
        if self._exclude_fn is not None and self._exclude_fn(p):
            return False
        return super()._decay_enabled(p)

    def _init_state(self, p):
        s = super()._init_state(p)
        s["moment1"] = jnp.zeros(p._value.shape, jnp.float32)
        s["moment2"] = jnp.zeros(p._value.shape, jnp.float32)
        return s

    def _update(self, value, grad, state, lr, step):
        v32 = self._cast_for_update(value, state)
        g32 = grad.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * g32 * g32
        state["moment1"] = m
        state["moment2"] = v
        mhat = m / (1 - self._beta1 ** step)
        vhat = v / (1 - self._beta2 ** step)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + self._weight_decay * v32
        w_norm = jnp.sqrt(jnp.sum(v32 * v32))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return self._finish_update(value, v32 - lr * trust * r, state)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _init_state(self, p):
        s = super()._init_state(p)
        s["mean_square"] = jnp.zeros(p._value.shape, jnp.float32)
        s["moment"] = jnp.zeros(p._value.shape, jnp.float32)
        if self._centered:
            s["mean_grad"] = jnp.zeros(p._value.shape, jnp.float32)
        return s

    def _update(self, value, grad, state, lr, step):
        v32 = self._cast_for_update(value, state)
        g32 = grad.astype(jnp.float32)
        if self._weight_decay:
            g32 = g32 + self._weight_decay * v32
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g32 * g32
        state["mean_square"] = ms
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g32
            state["mean_grad"] = mg
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["moment"] + lr * g32 / denom
        state["moment"] = mom
        return self._finish_update(value, v32 - mom, state)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        s = super()._init_state(p)
        s["moment"] = jnp.full(p._value.shape, self._init_acc, jnp.float32)
        return s

    def _update(self, value, grad, state, lr, step):
        v32 = self._cast_for_update(value, state)
        g32 = grad.astype(jnp.float32)
        if self._weight_decay:
            g32 = g32 + self._weight_decay * v32
        acc = state["moment"] + g32 * g32
        state["moment"] = acc
        return self._finish_update(value, v32 - lr * g32 / (jnp.sqrt(acc) + self._epsilon),
                                   state)
