"""``paddle_tpu.io`` — datasets and DataLoader.

Parity with python/paddle/io/ of the reference (dataloader_iter, worker,
batch_sampler — SURVEY.md §2.5 DataLoader row). TPU-first: the loader is a
host-side component; multiprocess workers feed numpy batches which the train
step moves to device (or `jax.make_array_from_process_local_data` under
multi-host data parallelism — see distributed.io).
"""

from __future__ import annotations

import itertools
import math
import queue
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no static length")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        self.tensors = [t if isinstance(t, Tensor) else Tensor(np.asarray(t))
                        for t in tensors]

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    idx = np.random.permutation(len(dataset))
    out, off = [], 0
    for ln in lengths:
        out.append(Subset(dataset, idx[off:off + ln].tolist()))
        off += ln
    return out


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


# ---------------------------------------------------------------------------
# samplers
# ---------------------------------------------------------------------------
class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, size=self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), size=self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the dataset across data-parallel ranks.

    Parity with python/paddle/io/dataloader/batch_sampler.py::
    DistributedBatchSampler (SURVEY.md §2.5). On TPU the "rank" is the
    data-parallel process index.
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import env as _env
            num_replicas = num_replicas if num_replicas is not None else _env.get_world_size()
            rank = rank if rank is not None else _env.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - len(indices))]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


# ---------------------------------------------------------------------------
# collate
# ---------------------------------------------------------------------------
def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return Tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._value) for s in batch]))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn([b[i] for b in batch])
                for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


# ---------------------------------------------------------------------------
# DataLoader
# ---------------------------------------------------------------------------
class DataLoader:
    """Reference uses forked worker processes + shared-memory transport
    (python/paddle/io/dataloader/worker.py, mmap_allocator.cc). Host-side numpy
    work here is lighter-weight: a thread pool with prefetch queue (python
    threads release the GIL in numpy) — multiprocess mode can be layered on
    when input pipelines dominate."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("length of IterableDataset-backed loader is unknown")
        return len(self.batch_sampler)

    def _iter_batches(self):
        # Profiler hook (reference: RecordEvent in dataloader, SURVEY §5.1)
        from ..profiler.record import host_recorder, RecordEvent

        def _record(make):
            if not host_recorder.enabled:
                return make()
            with RecordEvent("DataLoader", "Dataloader"):
                return make()

        if self._iterable:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield _record(lambda: self.collate_fn(batch))
                    batch = []
            if batch and not self.drop_last:
                yield _record(lambda: self.collate_fn(batch))
            return
        for idx_batch in self.batch_sampler:
            yield _record(lambda: self.collate_fn(
                [self.dataset[i] for i in idx_batch]))

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._iter_batches()
            return
        q: "queue.Queue" = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()

        def producer():
            try:
                for b in self._iter_batches():
                    q.put(b)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
