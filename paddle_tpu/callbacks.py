"""``paddle_tpu.callbacks`` — hapi training callbacks at the reference's
top-level path (python/paddle/callbacks/ re-exports hapi.callbacks)."""

from .hapi.callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger,
    ReduceLROnPlateau, VisualDL,
)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "ReduceLROnPlateau", "VisualDL"]
