"""Version-tolerant resolvers for jax APIs that moved between releases.

``shard_map`` has lived in three places across the jax versions this
framework targets: ``jax.experimental.shard_map.shard_map`` (<= 0.4.x),
``jax.experimental.shard_map`` re-exported at ``jax.shard_map`` (>= 0.5),
and historical ``jax.experimental.maps``-era spellings. Every module in
this repo imports it from HERE so the resolution logic exists exactly
once; a lint test (tests/test_serving.py::test_no_direct_shard_map_imports)
forbids new direct imports.
"""

from __future__ import annotations

import functools
import inspect


def _resolve_shard_map():
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    try:
        from jax.experimental.shard_map import shard_map as sm
        return sm
    except ImportError as e:  # pragma: no cover - depends on installed jax
        raise ImportError(
            "paddle_tpu requires a jax with shard_map (jax.shard_map or "
            "jax.experimental.shard_map.shard_map); installed jax "
            f"{jax.__version__} has neither") from e


_raw_shard_map = _resolve_shard_map()
try:
    _accepted = frozenset(inspect.signature(_raw_shard_map).parameters)
except (TypeError, ValueError):  # pragma: no cover - exotic wrappers
    _accepted = frozenset()

# the replication-check kwarg was renamed check_rep -> check_vma around the
# varying-manual-axes rework; accept either spelling at every call site
_CHECK_ALIASES = ("check_vma", "check_rep")


@functools.wraps(_raw_shard_map)
def shard_map(f, *args, **kwargs):
    for given in _CHECK_ALIASES:
        if given in kwargs and given not in _accepted:
            other = _CHECK_ALIASES[1 - _CHECK_ALIASES.index(given)]
            if other in _accepted:
                kwargs[other] = kwargs.pop(given)
            else:
                kwargs.pop(given)
    return _raw_shard_map(f, *args, **kwargs)


def axis_size(axis_name):
    """``lax.axis_size`` where it exists (jax >= 0.5); otherwise
    ``lax.psum(1, axis)``, which inside shard_map reduces a static 1 and
    therefore still returns a Python int usable in shapes/range()."""
    from jax import lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized`` (jax >= 0.5) with a fallback to
    the coordination-service client handle on older releases."""
    import jax

    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:  # pragma: no cover - depends on installed jax
        from jax._src import distributed as _dist
        return _dist.global_state.client is not None
    except Exception:
        return False


def jax_export():
    """The jax export module: ``jax.export`` is a lazily-imported
    submodule on some releases and lived in ``jax.experimental.export``
    before that."""
    try:
        import jax.export as export
        return export
    except ImportError:  # pragma: no cover - depends on installed jax
        from jax.experimental import export
        return export
