"""Imperative autograd over jax vjps.

TPU-native rebuild of the reference's eager autograd engine
(paddle/fluid/eager/backward.cc, grad_node_info.h — SURVEY.md §2.1): instead of
generated C++ GradNodes, every op application records one ``GradNode`` holding
the ``jax.vjp`` residual closure. ``backward()`` walks the node graph in
reverse-topological order exactly like ``egr::RunBackward``'s queue.

The graph is owned by output tensors (node refs live on the Tensor), so eager
loops that never call backward free their graphs with the tensors.  The whole
mechanism composes with ``jax.jit``: under trace, vjp residuals are tracers and
the backward walk happens at trace time.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

# --------------------------------------------------------------------------
# Grad mode
# --------------------------------------------------------------------------
_grad_enabled = [True]


def is_grad_enabled() -> bool:
    return _grad_enabled[-1]


@contextlib.contextmanager
def no_grad():
    _grad_enabled.append(False)
    try:
        yield
    finally:
        _grad_enabled.pop()


@contextlib.contextmanager
def enable_grad():
    _grad_enabled.append(True)
    try:
        yield
    finally:
        _grad_enabled.pop()


def set_grad_enabled(mode: bool):
    """Context manager form, parity with paddle.set_grad_enabled."""
    cm = enable_grad() if mode else no_grad()
    return cm


# --------------------------------------------------------------------------
# Node graph
# --------------------------------------------------------------------------
class GradNode:
    """One recorded op: holds the vjp closure and edges to input tensors."""

    __slots__ = ("vjp_fn", "inputs", "n_outputs", "name", "released",
                 "out_avals", "out_refs")

    def __init__(self, vjp_fn: Callable, inputs: Sequence[Any], out_avals: Sequence[Any],
                 name: str = "op"):
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)  # Tensor objects (strong refs keep graph alive)
        self.out_avals = list(out_avals)  # jax.ShapeDtypeStruct per output
        self.n_outputs = len(self.out_avals)
        self.name = name
        self.released = False
        # weakrefs to output Tensors, filled by Tensor.__init__ — lets
        # backward fire Tensor.register_hook with the ACCUMULATED
        # cotangent at node-pop time (outputs only here; no ref cycle)
        self.out_refs = [None] * self.n_outputs

    def _zero_cots(self):
        # jax.vjp requires float0 cotangents for non-differentiable (int/bool)
        # outputs; zeros of the output dtype would raise a cotangent-type error.
        import numpy as _np
        out = []
        for a in self.out_avals:
            if jnp.issubdtype(a.dtype, jnp.floating) or jnp.issubdtype(a.dtype, jnp.complexfloating):
                out.append(jnp.zeros(a.shape, a.dtype))
            else:
                out.append(_np.zeros(a.shape, jax.dtypes.float0))
        return tuple(out)

    def release(self):
        self.vjp_fn = None
        self.inputs = []
        self.released = True


def _toposort(root: GradNode) -> List[GradNode]:
    order: List[GradNode] = []
    seen = set()
    stack: List[tuple] = [(root, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            n = getattr(t, "_grad_node", None)
            if n is not None and id(n) not in seen:
                stack.append((n, False))
    return order  # children before parents; reverse pass iterates reversed()


def _accumulate(a, b):
    if a is None:
        return b
    return a + b


def backward(tensor, grad_tensor=None, retain_graph: bool = False,
             capture: Optional[dict] = None) -> None:
    """Run reverse accumulation from ``tensor``, filling ``.grad`` on leaves.

    Parity with ``paddle.autograd.backward`` / ``Tensor.backward()``.

    ``capture``: optional {id(tensor): None} map used by :func:`grad` — when
    given, cotangents routed into those tensors (leaf OR intermediate) are
    collected there and **no** ``.grad`` fields are mutated anywhere.
    """
    from .tensor import Tensor  # local import to avoid cycle

    root = getattr(tensor, "_grad_node", None)
    if root is None:
        if capture is not None and id(tensor) in capture:
            seed = jnp.ones_like(tensor._value) if grad_tensor is None else (
                grad_tensor._value if isinstance(grad_tensor, Tensor)
                else jnp.asarray(grad_tensor))
            capture[id(tensor)] = _accumulate(capture[id(tensor)], seed)
        return
    if root.released:
        raise RuntimeError(
            "Trying to backward through the graph a second time, but the "
            "graph buffers have already been released. Specify "
            "retain_graph=True on the first backward call.")
    if grad_tensor is None:
        seed = grad_tensor = jnp.ones_like(tensor._value)
    else:
        seed = grad_tensor._value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    if capture is not None and id(tensor) in capture:
        capture[id(tensor)] = _accumulate(capture[id(tensor)], seed)

    # cotangents pending per node, keyed by id(node), a list per output index
    pending = {id(root): [None] * root.n_outputs}
    pending[id(root)][tensor._out_index] = seed

    def _apply_hooks(t, g):
        """Tensor.register_hook chain on an ACCUMULATED gradient."""
        hooks = getattr(t, "_grad_hooks", None)
        if not hooks:
            return g
        for hook in list(hooks["fns"].values()):
            out = hook(Tensor(g, stop_gradient=True))
            if out is not None:
                g = out._value if isinstance(out, Tensor) else out
        return g

    # leaves with hooks: per-backward sums collected here so the hook
    # fires ONCE with the full accumulated gradient at the end (the
    # reference's AccumulateGrad timing), not per incoming edge
    hooked_leaf_sums: dict = {}
    hooked_leaf_tensors: dict = {}

    order = _toposort(root)
    for node in reversed(order):
        cots = pending.pop(id(node), None)
        if cots is None or node.released:
            continue
        # a node's output cotangents are COMPLETE when it pops (all
        # consumers processed first) — the hook point for intermediates
        for i, c in enumerate(cots):
            if c is None:
                continue
            ref = node.out_refs[i]
            t_out = ref() if ref is not None else None
            if t_out is not None and getattr(t_out, "_grad_hooks", None):
                cots[i] = _apply_hooks(t_out, c)
                if capture is not None and id(t_out) in capture:
                    # replace the pre-hook per-edge sums with the
                    # hook-transformed total
                    capture[id(t_out)] = cots[i]
        # jax.vjp requires a cotangent for every output; fill zeros.
        # We need output shapes: vjp_fn handles symbolic zeros poorly, so the
        # dispatcher stores output avals on the node via a closure default.
        full = tuple(c if c is not None else z for c, z in zip(cots, node._zero_cots()))
        in_grads = node.vjp_fn(full)
        for t, g in zip(node.inputs, in_grads):
            if g is None or not isinstance(t, Tensor):
                continue
            if getattr(g, "dtype", None) == jax.dtypes.float0:
                continue
            if capture is not None and id(t) in capture:
                capture[id(t)] = _accumulate(capture[id(t)], g)
            if t.stop_gradient:
                continue
            n = getattr(t, "_grad_node", None)
            if n is None:
                if getattr(t, "_grad_hooks", None):
                    hooked_leaf_sums[id(t)] = _accumulate(
                        hooked_leaf_sums.get(id(t)), g)
                    hooked_leaf_tensors[id(t)] = t
                elif capture is None:
                    # leaf: accumulate into .grad
                    t._grad_value = _accumulate(t._grad_value, g)
            else:
                lst = pending.setdefault(id(n), [None] * n.n_outputs)
                lst[t._out_index] = _accumulate(lst[t._out_index], g)
        if not retain_graph:
            node.release()

    for tid, g in hooked_leaf_sums.items():
        t = hooked_leaf_tensors[tid]
        g = _apply_hooks(t, g)
        if capture is not None:
            if tid in capture:
                capture[tid] = g  # hook-transformed total replaces sums
        else:
            t._grad_value = _accumulate(t._grad_value, g)


def grad(outputs, inputs, grad_outputs=None, retain_graph=False, create_graph=False,
         allow_unused=False):
    """Functional gradient query, parity with ``paddle.grad``.

    Implemented by running the tape backward and reading leaf grads without
    mutating ``.grad`` on parameters (grads are captured and restored).
    """
    from .tensor import Tensor

    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    capture = {id(t): None for t in inputs}
    for i, o in enumerate(outputs):
        g = None if grad_outputs is None else grad_outputs[i]
        backward(o, g, retain_graph=retain_graph or create_graph, capture=capture)
    results = []
    for t in inputs:
        got = capture[id(t)]
        if got is None:
            if not allow_unused:
                raise ValueError("an input tensor is unused in the graph")
            results.append(None)
        else:
            results.append(Tensor(got, stop_gradient=True))
    return results
