// TCPStore — native KV rendezvous store.
//
// TPU-native equivalent of the reference's rendezvous store
// (paddle/fluid/distributed/store/tcp_store.{h,cc}:§0, SURVEY.md §2.3):
// a master daemon owning an in-memory KV map with blocking waits, used to
// bootstrap distributed jobs (peer registration, barriers) before
// jax.distributed takes over device-level coordination.
//
// Design: one daemon thread, poll(2)-driven, single-threaded state — no
// locks on the KV map, waiters parked on a list and woken on SET/ADD.
// Client sockets are NON-BLOCKING with per-connection receive buffers:
// a request is dispatched only once fully buffered, so a client that
// stalls mid-request (SIGSTOP, partition) cannot wedge the daemon — other
// ranks keep being served and waiter timeouts keep firing. Replies use a
// bounded-wait send; a connection that cannot drain its reply within
// kSendTimeoutMs is dropped.
// Exposed through a C ABI consumed from Python via ctypes
// (paddle_tpu/distributed/store.py), which also implements the same wire
// protocol in pure Python as a fallback — the two interoperate.
//
// Wire protocol (little-endian):
//   request:  u8 cmd | u32 keylen | key bytes | payload
//     cmd=1 SET   payload = u32 vallen | val
//     cmd=2 GET   payload = i64 timeout_ms   (blocks until key exists)
//     cmd=3 ADD   payload = i64 delta        (creates key at 0 first)
//     cmd=4 WAIT  payload = i64 timeout_ms
//     cmd=5 DEL   payload = none
//   response: u8 status (0 ok / 1 timeout) | u32 vallen | val bytes
//     (SET/DEL respond vallen=0; ADD responds val = ascii of new value)

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <list>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             Clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------- util io
bool send_all(int fd, const void* buf, size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t len) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

// --------------------------------------------------------------- daemon
constexpr int kSendTimeoutMs = 5000;

// Bounded-wait send on a non-blocking fd: waits for POLLOUT on EAGAIN,
// gives up after kSendTimeoutMs so one undrained client can't stall the
// daemon thread forever.
bool send_bounded(int fd, const void* buf, size_t len) {
  const char* p = static_cast<const char*>(buf);
  int64_t deadline = now_ms() + kSendTimeoutMs;
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n > 0) {
      p += n;
      len -= static_cast<size_t>(n);
      continue;
    }
    if (n < 0 && errno == EINTR) continue;
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      int64_t rem = deadline - now_ms();
      if (rem <= 0) return false;
      pollfd pfd{fd, POLLOUT, 0};
      ::poll(&pfd, 1, static_cast<int>(std::min<int64_t>(rem, 200)));
      continue;
    }
    return false;
  }
  return true;
}

struct Waiter {
  int fd;
  std::string key;
  int64_t deadline_ms;  // -1 = infinite
  bool reply_value;     // GET replies value, WAIT replies status only
};

struct Conn {
  int fd;
  std::string inbuf;  // bytes received but not yet forming a full request
};

struct Daemon {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread thread;
  std::unordered_map<std::string, std::string> kv;
  std::list<Waiter> waiters;
  // fds whose waiter-path reply failed: the byte stream is desynced, so the
  // connection must be dropped (deferred to run()'s drop phase — dropping
  // here would invalidate the client list mid-iteration)
  std::vector<int> failed_fds;

  // false → connection must be dropped (reply could not be delivered)
  bool reply(int fd, uint8_t status, const std::string& val) {
    uint32_t vlen = static_cast<uint32_t>(val.size());
    std::string out;
    out.push_back(static_cast<char>(status));
    out.append(reinterpret_cast<const char*>(&vlen), 4);
    out += val;
    return send_bounded(fd, out.data(), out.size());
  }

  void wake_waiters(const std::string& key) {
    for (auto it = waiters.begin(); it != waiters.end();) {
      if (it->key == key) {
        if (!reply(it->fd, 0, it->reply_value ? kv[key] : std::string()))
          failed_fds.push_back(it->fd);
        it = waiters.erase(it);
      } else {
        ++it;
      }
    }
  }

  // Try to consume ONE complete request from c.inbuf.
  // Returns 1 = handled, 0 = need more bytes, -1 = drop connection.
  int try_handle(Conn& c) {
    const char* p = c.inbuf.data();
    size_t avail = c.inbuf.size();
    if (avail < 5) return 0;
    uint8_t cmd = static_cast<uint8_t>(p[0]);
    uint32_t klen;
    memcpy(&klen, p + 1, 4);
    if (klen > (1u << 20)) return -1;
    size_t fixed;  // payload bytes after the key, before any value
    switch (cmd) {
      case 1: fixed = 4; break;            // SET: u32 vallen
      case 2: case 3: case 4: fixed = 8; break;  // GET/ADD/WAIT: i64
      case 5: fixed = 0; break;            // DEL
      default: return -1;
    }
    size_t base = 5 + static_cast<size_t>(klen);
    if (avail < base + fixed) return 0;
    uint32_t vlen = 0;
    if (cmd == 1) {
      memcpy(&vlen, p + base, 4);
      if (vlen > (1u << 30)) return -1;
      if (avail < base + 4 + vlen) return 0;
    }
    std::string key(p + 5, klen);
    size_t consumed = base + fixed + (cmd == 1 ? vlen : 0);
    bool ok = true;
    switch (cmd) {
      case 1: {  // SET
        kv[key] = std::string(p + base + 4, vlen);
        wake_waiters(key);
        ok = reply(c.fd, 0, "");
        break;
      }
      case 2:    // GET (blocking)
      case 4: {  // WAIT
        int64_t timeout_ms;
        memcpy(&timeout_ms, p + base, 8);
        auto it = kv.find(key);
        if (it != kv.end()) {
          ok = reply(c.fd, 0, cmd == 2 ? it->second : std::string());
        } else {
          Waiter w;
          w.fd = c.fd;
          w.key = key;
          w.deadline_ms = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
          w.reply_value = (cmd == 2);
          waiters.push_back(std::move(w));
        }
        break;
      }
      case 3: {  // ADD
        int64_t delta;
        memcpy(&delta, p + base, 8);
        int64_t cur = 0;
        auto it = kv.find(key);
        if (it != kv.end() && !it->second.empty())
          cur = strtoll(it->second.c_str(), nullptr, 10);
        cur += delta;
        kv[key] = std::to_string(cur);
        wake_waiters(key);
        ok = reply(c.fd, 0, std::to_string(cur));
        break;
      }
      case 5: {  // DEL
        kv.erase(key);
        ok = reply(c.fd, 0, "");
        break;
      }
    }
    c.inbuf.erase(0, consumed);
    return ok ? 1 : -1;
  }

  void drop_fd_waiters(int fd) {
    for (auto it = waiters.begin(); it != waiters.end();)
      it = (it->fd == fd) ? waiters.erase(it) : std::next(it);
  }

  void run() {
    std::vector<Conn> clients;
    auto drop = [&](int fd) {
      drop_fd_waiters(fd);
      ::close(fd);
      clients.erase(std::remove_if(clients.begin(), clients.end(),
                                   [fd](const Conn& c) { return c.fd == fd; }),
                    clients.end());
    };
    while (!stop.load(std::memory_order_relaxed)) {
      std::vector<pollfd> pfds;
      pfds.push_back({listen_fd, POLLIN, 0});
      for (const Conn& c : clients) pfds.push_back({c.fd, POLLIN, 0});
      int rc = ::poll(pfds.data(), pfds.size(), 100);
      if (rc < 0 && errno != EINTR) break;

      // expire timed-out waiters
      int64_t t = now_ms();
      for (auto it = waiters.begin(); it != waiters.end();) {
        if (it->deadline_ms >= 0 && t >= it->deadline_ms) {
          if (!reply(it->fd, 1, "")) failed_fds.push_back(it->fd);
          it = waiters.erase(it);
        } else {
          ++it;
        }
      }
      if (rc <= 0) {
        for (int fd : failed_fds) drop(fd);
        failed_fds.clear();
        continue;
      }

      if (pfds[0].revents & POLLIN) {
        int c = ::accept(listen_fd, nullptr, nullptr);
        if (c >= 0) {
          int one = 1;
          setsockopt(c, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          fcntl(c, F_SETFL, fcntl(c, F_GETFL, 0) | O_NONBLOCK);
          clients.push_back(Conn{c, std::string()});
        }
      }
      std::vector<int> dead;
      for (size_t i = 1; i < pfds.size(); ++i) {
        if (!(pfds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
        Conn& c = clients[i - 1];
        bool closed = false;
        if (pfds[i].revents & POLLIN) {
          char buf[65536];
          for (;;) {  // drain what the kernel has; never block
            ssize_t n = ::recv(c.fd, buf, sizeof(buf), 0);
            if (n > 0) {
              c.inbuf.append(buf, static_cast<size_t>(n));
              continue;
            }
            if (n == 0) closed = true;
            else if (errno == EINTR) continue;
            else if (errno != EAGAIN && errno != EWOULDBLOCK) closed = true;
            break;
          }
        } else {
          closed = true;  // HUP/ERR with no data
        }
        int h;
        while ((h = try_handle(c)) == 1) {}
        if (h == -1 || closed) dead.push_back(c.fd);
      }
      dead.insert(dead.end(), failed_fds.begin(), failed_fds.end());
      failed_fds.clear();
      std::sort(dead.begin(), dead.end());
      dead.erase(std::unique(dead.begin(), dead.end()), dead.end());
      for (int fd : dead) drop(fd);
    }
    for (const Conn& c : clients) ::close(c.fd);
    if (listen_fd >= 0) ::close(listen_fd);
  }
};

// --------------------------------------------------------------- client
struct Client {
  int fd = -1;
};

bool client_request(Client* c, uint8_t cmd, const std::string& key,
                    const std::string& payload, uint8_t* status,
                    std::string* val) {
  std::string msg;
  msg.push_back(static_cast<char>(cmd));
  uint32_t klen = static_cast<uint32_t>(key.size());
  msg.append(reinterpret_cast<const char*>(&klen), 4);
  msg += key;
  msg += payload;
  if (!send_all(c->fd, msg.data(), msg.size())) return false;
  uint8_t st;
  uint32_t vlen;
  if (!recv_all(c->fd, &st, 1) || !recv_all(c->fd, &vlen, 4)) return false;
  val->resize(vlen);
  if (vlen && !recv_all(c->fd, &(*val)[0], vlen)) return false;
  *status = st;
  return true;
}

}  // namespace

// ------------------------------------------------------------------ C ABI
extern "C" {

// Start master daemon; port=0 picks an ephemeral port. Returns handle or
// nullptr. The bound port is written to *out_port.
void* ts_master_start(int port, int* out_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  auto* d = new Daemon();
  d->listen_fd = fd;
  d->port = ntohs(addr.sin_port);
  if (out_port) *out_port = d->port;
  d->thread = std::thread([d] { d->run(); });
  return d;
}

void ts_master_stop(void* h) {
  auto* d = static_cast<Daemon*>(h);
  if (!d) return;
  d->stop.store(true);
  if (d->thread.joinable()) d->thread.join();
  delete d;
}

void* ts_client_connect(const char* host, int port, int timeout_ms) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof(portstr), "%d", port);
  if (getaddrinfo(host, portstr, &hints, &res) != 0 || !res) return nullptr;
  int64_t deadline = now_ms() + timeout_ms;
  int fd = -1;
  // retry loop: master may not be up yet (launch races rendezvous)
  while (now_ms() < deadline || timeout_ms < 0) {
    fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd < 0) break;
    if (::connect(fd, res->ai_addr, res->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
    usleep(100 * 1000);
  }
  freeaddrinfo(res);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Client();
  c->fd = fd;
  return c;
}

void ts_client_close(void* h) {
  auto* c = static_cast<Client*>(h);
  if (!c) return;
  if (c->fd >= 0) ::close(c->fd);
  delete c;
}

// Returns 0 ok, 1 timeout, -1 connection error.
int ts_set(void* h, const char* key, const char* val, int vlen) {
  auto* c = static_cast<Client*>(h);
  std::string payload;
  uint32_t v = static_cast<uint32_t>(vlen);
  payload.append(reinterpret_cast<const char*>(&v), 4);
  payload.append(val, vlen);
  uint8_t st;
  std::string out;
  if (!client_request(c, 1, key, payload, &st, &out)) return -1;
  return st;
}

// GET: blocks server-side up to timeout_ms (-1 infinite). The caller owns
// no memory: value is copied into out_buf (capacity out_cap); actual length
// written to *out_len. Returns 0 ok, 1 timeout, -1 error, -2 buffer small.
int ts_get(void* h, const char* key, int64_t timeout_ms, char* out_buf,
           int out_cap, int* out_len) {
  auto* c = static_cast<Client*>(h);
  std::string payload(reinterpret_cast<const char*>(&timeout_ms), 8);
  uint8_t st;
  std::string out;
  if (!client_request(c, 2, key, payload, &st, &out)) return -1;
  if (st != 0) return st;
  if (static_cast<int>(out.size()) > out_cap) return -2;
  memcpy(out_buf, out.data(), out.size());
  *out_len = static_cast<int>(out.size());
  return 0;
}

// ADD: atomic fetch-add on ascii-integer key; new value via *out_val.
int ts_add(void* h, const char* key, int64_t delta, int64_t* out_val) {
  auto* c = static_cast<Client*>(h);
  std::string payload(reinterpret_cast<const char*>(&delta), 8);
  uint8_t st;
  std::string out;
  if (!client_request(c, 3, key, payload, &st, &out)) return -1;
  if (st != 0) return st;
  *out_val = strtoll(out.c_str(), nullptr, 10);
  return 0;
}

int ts_wait(void* h, const char* key, int64_t timeout_ms) {
  auto* c = static_cast<Client*>(h);
  std::string payload(reinterpret_cast<const char*>(&timeout_ms), 8);
  uint8_t st;
  std::string out;
  if (!client_request(c, 4, key, payload, &st, &out)) return -1;
  return st;
}

int ts_del(void* h, const char* key) {
  auto* c = static_cast<Client*>(h);
  uint8_t st;
  std::string out;
  if (!client_request(c, 5, key, "", &st, &out)) return -1;
  return st;
}

}  // extern "C"
