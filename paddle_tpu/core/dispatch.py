"""Op dispatch: the Phi-dispatcher equivalent.

In the reference, every op goes pybind → generated dygraph forward →
``KernelFactory::SelectKernel`` → CUDA kernel (SURVEY.md §3.3,
paddle/phi/core/kernel_factory.cc). Here "selecting a kernel" means tracing a
jax function: XLA is the kernel library. :func:`apply` is the single funnel —
it unwraps Tensors, runs (or vjp-records) the jax function, and wraps outputs.

Pallas kernels register through the same funnel: an op's ``fn`` may internally
branch to a Pallas call on TPU (see paddle_tpu.ops).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from . import autograd
from ..flags import flag_value
from ..observability import runtime as _obs
from ..observability.profiling import chain_armed as _chain_armed
from ..observability.profiling import chain_profiler as _chain_profiler
from ..observability.runtime import telemetry as _telemetry  # singleton
from ..profiler.record import RecordEvent, host_recorder

import time as _time


def _is_tensor(x) -> bool:
    from .tensor import Tensor
    return isinstance(x, Tensor)


def unwrap(x):
    from .tensor import Tensor
    if isinstance(x, Tensor):
        return x._value
    return x


# AMP hook: paddle_tpu.amp installs a caster here (op_name, vals) -> vals.
# Kept as a mutable slot so the dispatcher has no import-time dependency on amp.
amp_cast_hook = None


def apply(fn: Callable, *args, op_name: str = "op", n_outputs: int = None, **static):
    """Run ``fn(*arrays, **static)`` over Tensor args with tape recording.

    Positional args may be Tensors, jax arrays, or python scalars (scalars are
    passed through untraced w.r.t. grad). Returns Tensor(s) mirroring fn's
    output structure (a single array or a tuple of arrays).
    """
    # Observability hook (reference: RecordEvent inside eager op dispatch,
    # SURVEY.md §5.1, plus always-on dispatch telemetry). dispatch_armed
    # is the ONE boolean consulted on the fast path: False means no
    # capture window AND telemetry disabled, and the dispatch is
    # seed-identical (guarded by benchmarks/bench_dispatch_overhead.py).
    # The armed branch inlines the counter bump (no extra call frames,
    # private ``_enabled`` attrs read directly): the always-on telemetry
    # must stay inside the < 3% per-dispatch budget.
    if _obs.dispatch_armed[0]:
        if host_recorder._enabled:
            return _dispatch_traced(fn, args, op_name, static)
        tele = _telemetry
        if tele._enabled:
            c = tele._counts
            n = c.get(op_name, 0)
            c[op_name] = n + 1
            if _chain_armed[0]:
                # continuous profiling: producer->consumer transition
                # (observability.profiling.DispatchChainProfiler)
                _chain_profiler.note(op_name)
            if n % tele.sample_every == 0:
                t0 = _time.perf_counter_ns()
                out = _apply_impl(fn, args, op_name, static)
                dur = _time.perf_counter_ns() - t0
                tele.observe_duration(dur)
                if _chain_armed[0]:
                    _chain_profiler.note_duration(op_name, dur)
                return out
    return _apply_impl(fn, args, op_name, static)


def _dispatch_traced(fn: Callable, args, op_name: str, static):
    """Capture-window path: wrap the dispatch in a profiler span (and
    still feed the telemetry counters)."""
    ev = RecordEvent(op_name, "Operator")
    ev.begin()
    try:
        tele = _telemetry
        if tele._enabled:
            if _chain_armed[0]:
                _chain_profiler.note(op_name)
            if tele.count(op_name):
                t0 = _time.perf_counter_ns()
                out = _apply_impl(fn, args, op_name, static)
                dur = _time.perf_counter_ns() - t0
                tele.observe_duration(dur)
                if _chain_armed[0]:
                    _chain_profiler.note_duration(op_name, dur)
                return out
        return _apply_impl(fn, args, op_name, static)
    finally:
        ev.end()


def _apply_impl(fn: Callable, args, op_name: str, static):
    from .tensor import Tensor

    if amp_cast_hook is not None:
        args = amp_cast_hook(op_name, args)
    vals = tuple(unwrap(a) for a in args)
    tensor_inputs = [a for a in args if _is_tensor(a)]
    needs_grad = autograd.is_grad_enabled() and any(
        not t.stop_gradient for t in tensor_inputs
    )

    if not needs_grad:
        out = fn(*vals, **static)
        if flag_value("check_nan_inf"):
            _check_nan_inf(op_name,
                           out if isinstance(out, (tuple, list)) else (out,))
        return _wrap_outputs(out, stop_gradient=True)

    # Differentiate only w.r.t. Tensor positional args; close over the rest.
    tensor_pos = [i for i, a in enumerate(args) if _is_tensor(a)]
    tensor_vals = tuple(vals[i] for i in tensor_pos)

    def closed(*tvals):
        full = list(vals)
        for i, v in zip(tensor_pos, tvals):
            full[i] = v
        return fn(*full, **static)

    out_vals, vjp_fn = jax.vjp(closed, *tensor_vals)
    is_tuple = isinstance(out_vals, (tuple, list))
    outs = tuple(out_vals) if is_tuple else (out_vals,)
    avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs]

    def vjp_adapter(cots):
        c = tuple(cots) if is_tuple else cots[0]
        return vjp_fn(c)

    node = autograd.GradNode(vjp_adapter, tensor_inputs, avals, name=op_name)
    wrapped = tuple(
        Tensor(o, stop_gradient=False, _grad_node=node, _out_index=i)
        for i, o in enumerate(outs)
    )
    result = wrapped if is_tuple else wrapped[0]
    if flag_value("check_nan_inf"):
        _check_nan_inf(op_name, outs)
    return result


def _wrap_outputs(out, stop_gradient: bool):
    from .tensor import Tensor
    if isinstance(out, (tuple, list)):
        return tuple(Tensor(o, stop_gradient=stop_gradient) for o in out)
    return Tensor(out, stop_gradient=stop_gradient)


# nan/inf checker policy, configured by paddle_tpu.amp.debugging
nan_inf_abort = [True]          # False: report (log) instead of raising
nan_inf_skip_ops: set = set()   # op names excluded from the scan
nan_inf_check_ops: set = set()  # when non-empty, ONLY these ops are scanned


def _check_nan_inf(op_name: str, outs: Sequence[Any]) -> None:
    """Debug pass: reference FLAGS_check_nan_inf / nan_inf_utils_detail.cc
    (SURVEY.md §5.2). Host-side check; only valid outside jit (for values
    inside compiled fns use amp.debugging.checkify_wrap)."""
    if op_name in nan_inf_skip_ops:
        return
    if nan_inf_check_ops and op_name not in nan_inf_check_ops:
        return
    for i, o in enumerate(outs):
        if isinstance(o, jax.core.Tracer):
            return  # under trace: skip (use checkify-style tools instead)
        if jnp.issubdtype(o.dtype, jnp.floating):
            bad = ~jnp.isfinite(o)
            if bool(jnp.any(bad)):
                msg = f"nan/inf detected in output {i} of op '{op_name}'"
                if nan_inf_abort[0]:
                    raise FloatingPointError(msg)
                import logging
                logging.getLogger("paddle_tpu.debugging").warning(msg)
                return
