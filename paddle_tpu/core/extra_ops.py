"""Tensor-API surface that does not fit the single-source op schema:
list/tuple outputs, host-side results, predicates, and random ops.

Reference: assorted paddle/phi kernels + python/paddle/tensor/* wrappers
(SURVEY.md §2.1 kernel corpus). Installed into the top-level namespace by
paddle_tpu/__init__.py.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .dispatch import apply
from .tensor import Tensor
from .. import random as _random


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# -- predicates / introspection ---------------------------------------------
def is_complex(x) -> bool:
    return jnp.iscomplexobj(_v(x))


def is_floating_point(x) -> bool:
    return jnp.issubdtype(_v(x).dtype, jnp.floating)


def is_empty(x) -> Tensor:
    return Tensor(jnp.asarray(_v(x).size == 0))


def rank(x) -> Tensor:
    return Tensor(jnp.asarray(_v(x).ndim, jnp.int32))


def tolist(x) -> list:
    return _t(x).tolist()


def broadcast_shape(x_shape, y_shape) -> List[int]:
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


# -- copies / views ----------------------------------------------------------
def clone(x) -> Tensor:
    """Differentiable copy (paddle.clone — delegates to Tensor.clone)."""
    return _t(x).clone()


def hstack(x, name=None) -> Tensor:
    """paddle.hstack: takes a LIST/tuple of tensors (concat along dim 1,
    or dim 0 for 1-D inputs — numpy hstack semantics)."""
    ts = [_t(t) for t in x]
    return apply(lambda *vs: jnp.hstack(vs), *ts, op_name="hstack")


def view(x, shape_or_dtype):
    """paddle.view: reshape view (or dtype reinterpret for a dtype arg)."""
    if isinstance(shape_or_dtype, (list, tuple)):
        return apply(lambda v: v.reshape(tuple(
            int(s) for s in shape_or_dtype)), _t(x), op_name="view")
    from .dtype import convert_dtype
    return apply(lambda v: v.view(convert_dtype(shape_or_dtype)), _t(x),
                 op_name="view_dtype")


def broadcast_tensors(inputs: Sequence) -> List[Tensor]:
    vals = [_v(i) for i in inputs]
    shape = jnp.broadcast_shapes(*[v.shape for v in vals])
    return [apply(lambda v, s=shape: jnp.broadcast_to(v, s), _t(i),
                  op_name="broadcast_tensors") for i in inputs]


# -- splits / stacks ---------------------------------------------------------
def unstack(x, axis=0, num=None) -> List[Tensor]:
    v = _v(x)
    n = num or v.shape[axis]
    return [apply(lambda a, i=i: jnp.take(a, i, axis=axis), _t(x),
                  op_name="unstack") for i in range(n)]


def _nsplit(x, num_or_sections, axis):
    from .math_ops import split
    return split(_t(x), num_or_sections, axis=axis)


def hsplit(x, num_or_sections):
    v = _v(x)
    return _nsplit(x, num_or_sections, 0 if v.ndim == 1 else 1)


def vsplit(x, num_or_sections):
    return _nsplit(x, num_or_sections, 0)


def dsplit(x, num_or_sections):
    return _nsplit(x, num_or_sections, 2)


# -- indexing ---------------------------------------------------------------
def slice(x, axes, starts, ends) -> Tensor:  # noqa: A001 — paddle name
    """paddle.slice: static slice along the given axes."""
    import builtins

    def fn(v):
        idx = [builtins.slice(None)] * v.ndim
        for ax, s, e in zip(axes, starts, ends):
            dim = v.shape[ax]
            s = int(s) if s >= 0 else int(s) + dim
            e = int(e) if e >= 0 else int(e) + dim
            idx[ax] = builtins.slice(max(s, 0), min(e, dim))
        return v[tuple(idx)]

    return apply(fn, _t(x), op_name="slice")


def shard_index(input, index_num: int, nshards: int, shard_id: int,
                ignore_value: int = -1) -> Tensor:
    """paddle.shard_index: map a global index to its shard-local value,
    ignore_value for indices owned by other shards (PS-era embedding
    sharding helper; kept for API parity)."""
    size = (index_num + nshards - 1) // nshards

    def fn(v):
        owner = v // size
        local = v % size
        return jnp.where(owner == shard_id, local,
                         jnp.full_like(v, ignore_value))

    return apply(fn, _t(input), op_name="shard_index")


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None):
    """Host-side op (output length is data-dependent — not jittable; the
    reference's GPU kernel also compacts dynamically)."""
    v = np.asarray(_v(x))
    moved = False
    if axis is None:
        v = v.reshape(-1)
    elif axis % v.ndim != 0:
        v = np.moveaxis(v, axis, 0)  # dedupe runs along the given axis
        moved = True
    keep = np.ones(v.shape[0], bool)
    if v.shape[0] > 1:
        if v.ndim == 1:
            keep[1:] = v[1:] != v[:-1]
        else:
            keep[1:] = np.any(v[1:] != v[:-1],
                              axis=tuple(range(1, v.ndim)))
    kept = v[keep]
    if moved:
        kept = np.moveaxis(kept, 0, axis)
    out = Tensor(jnp.asarray(kept))
    res = [out]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        res.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        pos = np.flatnonzero(keep)
        counts = np.diff(np.append(pos, v.shape[0]))
        res.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return res[0] if len(res) == 1 else tuple(res)


# -- linalg adjacent ---------------------------------------------------------
def inverse(x) -> Tensor:
    return apply(lambda v: jnp.linalg.inv(v), _t(x), op_name="inverse")


# -- random ------------------------------------------------------------------
def poisson(x) -> Tensor:
    """Element-wise Poisson sample with rate x (paddle.poisson)."""
    key = _random.next_key()
    return apply(lambda v: jax.random.poisson(key, v, v.shape).astype(
        v.dtype), _t(x), op_name="poisson")


# -- round-4 API audit: stacks / splits / scatter views ----------------------
def vstack(x, name=None) -> Tensor:
    """paddle.vstack (row_stack): stack along dim 0, 1-D inputs become
    rows (numpy vstack semantics)."""
    return apply(lambda *vs: jnp.vstack(vs), *[_t(t) for t in x],
                 op_name="vstack")


def row_stack(x, name=None) -> Tensor:
    return vstack(x, name)


def column_stack(x, name=None) -> Tensor:
    """paddle.column_stack: 1-D inputs become columns."""
    return apply(lambda *vs: jnp.column_stack(vs), *[_t(t) for t in x],
                 op_name="column_stack")


def dstack(x, name=None) -> Tensor:
    return apply(lambda *vs: jnp.dstack(vs), *[_t(t) for t in x],
                 op_name="dstack")


def _atleast(nd, inputs):
    f = {1: jnp.atleast_1d, 2: jnp.atleast_2d, 3: jnp.atleast_3d}[nd]
    outs = [apply(lambda v, f=f: f(v), _t(t), op_name=f"atleast_{nd}d")
            for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_1d(*inputs, name=None):
    return _atleast(1, inputs)


def atleast_2d(*inputs, name=None):
    return _atleast(2, inputs)


def atleast_3d(*inputs, name=None):
    return _atleast(3, inputs)


def tensor_split(x, num_or_indices, axis=0, name=None) -> List[Tensor]:
    """paddle.tensor_split: like numpy array_split — uneven splits allowed
    for an int count; a list gives explicit cut indices."""
    v = _v(x)
    dim = v.shape[axis]
    if isinstance(num_or_indices, int):
        n = num_or_indices
        base, extra = divmod(dim, n)
        sizes = [base + (1 if i < extra else 0) for i in range(n)]
        cuts = np.cumsum([0] + sizes)
    else:
        idx = [int(i) for i in num_or_indices]
        cuts = np.asarray([0] + idx + [dim])
    outs = []
    for s, e in zip(cuts[:-1], cuts[1:]):
        outs.append(apply(
            lambda vv, s=int(s), e=int(e): jax.lax.slice_in_dim(
                vv, s, e, axis=axis), _t(x), op_name="tensor_split"))
    return outs


def mode(x, axis=-1, keepdim=False, name=None):
    """paddle.mode: most frequent value (+ its last index) along ``axis``.

    Sort-based: per-element frequencies come from searchsorted over the
    sorted row (O(n log n) time, O(n) memory — not the O(n^2) pairwise
    equality matrix). The mode maximises the count, ties resolved toward
    the LARGEST index (paddle returns the last occurrence of the modal
    value)."""
    def fn(v):
        ax = axis % v.ndim
        mv = jnp.moveaxis(v, ax, -1)
        lead = mv.shape[:-1]
        n = mv.shape[-1]
        flat = mv.reshape(-1, n)
        sv = jnp.sort(flat, axis=-1)

        def row_counts(srow, qrow):
            hi = jnp.searchsorted(srow, qrow, side="right")
            lo = jnp.searchsorted(srow, qrow, side="left")
            return hi - lo

        counts = jax.vmap(row_counts)(sv, flat)
        best = jnp.max(counts, axis=-1, keepdims=True)
        idx = jnp.arange(n)
        pick = jnp.max(jnp.where(counts == best, idx, -1), axis=-1)
        vals = jnp.take_along_axis(flat, pick[:, None], axis=-1)[:, 0]
        vals = vals.reshape(lead)
        pick = pick.reshape(lead)
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            pick = jnp.expand_dims(pick, ax)
        # default int dtype (int32 unless x64 is enabled) — a hard int64
        # cast silently truncates + warns when x64 is off
        return vals, pick.astype(jax.dtypes.canonicalize_dtype(jnp.int64))

    return apply(fn, _t(x), op_name="mode", n_outputs=2)


def masked_scatter(x, mask, value, name=None) -> Tensor:
    """paddle.masked_scatter: fill True positions of ``mask`` with
    consecutive elements of ``value`` (row-major)."""
    def fn(v, m, val):
        mb = jnp.broadcast_to(m.astype(bool), v.shape)
        k = jnp.cumsum(mb.reshape(-1)) - 1          # source index per slot
        src = val.reshape(-1)[jnp.clip(k, 0, None)].reshape(v.shape)
        return jnp.where(mb, src.astype(v.dtype), v)

    return apply(fn, _t(x), _t(mask), _t(value), op_name="masked_scatter")


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None) -> Tensor:
    """paddle.diagonal_scatter: write ``y`` (shaped like the diagonal
    view, diagonal length last) onto the selected diagonal."""
    def fn(v, yv):
        vm = jnp.moveaxis(v, (axis1, axis2), (-2, -1))
        n1, n2 = vm.shape[-2], vm.shape[-1]
        if offset >= 0:
            dlen = min(n1, n2 - offset)
            r = jnp.arange(dlen)
            c = r + offset
        else:
            dlen = min(n1 + offset, n2)
            c = jnp.arange(dlen)
            r = c - offset
        out = vm.at[..., r, c].set(yv.astype(v.dtype))
        return jnp.moveaxis(out, (-2, -1), (axis1, axis2))

    return apply(fn, _t(x), _t(y), op_name="diagonal_scatter")


def select_scatter(x, values, axis, index, name=None) -> Tensor:
    """paddle.select_scatter: write ``values`` into position ``index`` of
    dimension ``axis``."""
    def fn(v, val):
        expanded = jnp.expand_dims(val.astype(v.dtype), axis)
        return jax.lax.dynamic_update_slice_in_dim(
            v, expanded, index, axis=axis)

    return apply(fn, _t(x), _t(values), op_name="select_scatter")


def slice_scatter(x, value, axes, starts, ends, strides, name=None) -> Tensor:
    """paddle.slice_scatter: write ``value`` into the strided slice."""
    import builtins

    def fn(v, val):
        idx = [builtins.slice(None)] * v.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(int(s), int(e), int(st))
        return v.at[tuple(idx)].set(val.astype(v.dtype))

    return apply(fn, _t(x), _t(value), op_name="slice_scatter")


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    """paddle.histogramdd: D-dimensional histogram of an (N, D) sample.
    Returns (hist, list_of_edges) — numpy.histogramdd semantics.

    Device-side and trace-safe: binning is searchsorted + bincount in
    jnp, so it works under jit (bin COUNTS stay static; edges may be
    traced values) and never forces a device→host sync in eager mode."""
    v = _v(x)
    if v.ndim == 1:           # numpy promotes a 1-D sample to (N, 1)
        v = v[:, None]
    n_samples, ndim = v.shape
    w = None if weights is None else _v(weights)

    # resolve per-dimension bin counts (static) and edges (maybe traced)
    if isinstance(bins, (list, tuple)) and len(bins) and \
            not np.isscalar(bins[0]):
        edges = [_v(b).astype(jnp.float32) for b in bins]
        nbins = [int(e.shape[0]) - 1 for e in edges]
    else:
        if np.isscalar(bins):
            nbins = [int(bins)] * ndim
        else:
            nbins = [int(b) for b in bins]
    if len(nbins) != ndim:
        raise ValueError(
            "The dimension of bins must be equal to the dimension of the "
            f"sample x ({len(nbins)} vs {ndim}).")
    if not (isinstance(bins, (list, tuple)) and len(bins)
            and not np.isscalar(bins[0])):
        if ranges is not None:
            r = list(ranges)
            lo = [jnp.float32(r[2 * i]) for i in range(ndim)]
            hi = [jnp.float32(r[2 * i + 1]) for i in range(ndim)]
        else:
            lo = [jnp.min(v[:, d]).astype(jnp.float32) for d in range(ndim)]
            hi = [jnp.max(v[:, d]).astype(jnp.float32) for d in range(ndim)]
            # span is degenerate only when max == min: numpy then widens
            # to [lo-0.5, hi+0.5]; any non-zero span is kept exactly
            deg = [h == l for l, h in zip(lo, hi)]
            lo = [jnp.where(d, l - 0.5, l) for d, l in zip(deg, lo)]
            hi = [jnp.where(d, h + 0.5, h) for d, h in zip(deg, hi)]
        edges = [jnp.linspace(lo[d], hi[d], nbins[d] + 1)
                 for d in range(ndim)]

    flat_idx = jnp.zeros((n_samples,), jnp.int32)
    valid = jnp.ones((n_samples,), bool)
    for d in range(ndim):
        e = edges[d]
        col = v[:, d].astype(e.dtype)
        idx_d = jnp.searchsorted(e, col, side="right") - 1
        # rightmost bin is closed on both sides (numpy semantics)
        idx_d = jnp.where(col == e[-1], nbins[d] - 1, idx_d)
        valid &= (col >= e[0]) & (col <= e[-1])
        idx_d = jnp.clip(idx_d, 0, nbins[d] - 1)
        flat_idx = flat_idx * nbins[d] + idx_d.astype(jnp.int32)

    if w is None:
        wv = valid.astype(jnp.float32)
    else:
        wv = jnp.where(valid, w.astype(jnp.float32), 0.0)
    total = int(np.prod(nbins)) if nbins else 1
    hist = jnp.bincount(flat_idx, weights=wv, length=total)
    hist = hist.reshape(tuple(nbins))
    if density:
        hist = hist / jnp.sum(hist)
        for d in range(ndim):
            widths = jnp.diff(edges[d])
            shape = [1] * ndim
            shape[d] = nbins[d]
            hist = hist / widths.reshape(shape)
    return (Tensor(hist.astype(jnp.float32)),
            [Tensor(e.astype(jnp.float32)) for e in edges])


# -- round-5 API-audit batch (sweep 4) ---------------------------------------
def frac(x, name=None) -> Tensor:
    """paddle.frac: x - trunc(x)."""
    return apply(lambda v: v - jnp.trunc(v), _t(x), op_name="frac")


def gammaln(x, name=None) -> Tensor:
    """paddle.gammaln: log |Gamma(x)|."""
    from jax.scipy.special import gammaln as _g
    return apply(lambda v: _g(v.astype(jnp.float32)), _t(x),
                 op_name="gammaln")


def isin(x, test_x, assume_unique=False, invert=False, name=None) -> Tensor:
    """paddle.isin: elementwise membership of x in test_x."""
    return apply(lambda v, t: jnp.isin(v, t, assume_unique=assume_unique,
                                       invert=invert),
                 _t(x), _t(test_x), op_name="isin")


def clip_(x, min=None, max=None, name=None) -> Tensor:
    """paddle.Tensor.clip_ (in place)."""
    t = _t(x)
    t._value = jnp.clip(t._value,
                        None if min is None else min,
                        None if max is None else max)
    return t


def geometric_(x, probs, name=None) -> Tensor:
    """paddle.Tensor.geometric_ (in place): fill with Geometric(probs)
    samples (number of Bernoulli trials to first success, support 1..inf)."""
    t = _t(x)
    key = _random.next_key()
    u = jax.random.uniform(key, t._value.shape, jnp.float32,
                           minval=jnp.finfo(jnp.float32).tiny)
    p = jnp.asarray(_v(probs) if not np.isscalar(probs) else probs,
                    jnp.float32)
    g = jnp.floor(jnp.log(u) / jnp.log1p(-p)) + 1.0
    t._value = g.astype(t._value.dtype)
    return t


def index_put(x, indices, value, accumulate=False, name=None) -> Tensor:
    """paddle.index_put: out[indices] = value (scatter by index tensors;
    ``accumulate`` adds instead of overwriting)."""
    def fn(v, val, *idx):
        ref = v.at[tuple(i.astype(jnp.int32) for i in idx)]
        return ref.add(val.astype(v.dtype)) if accumulate \
            else ref.set(val.astype(v.dtype))

    return apply(fn, _t(x), _t(value), *[_t(i) for i in indices],
                 op_name="index_put")


def index_put_(x, indices, value, accumulate=False, name=None) -> Tensor:
    t = _t(x)
    t._value = index_put(t, indices, value, accumulate)._value
    return t


def unfold(x, axis, size, step, name=None) -> Tensor:
    """paddle.Tensor.unfold: sliding windows of ``size`` every ``step``
    along ``axis``; the window dim is appended LAST (paddle semantics)."""
    def fn(v):
        ax = axis % v.ndim
        n = v.shape[ax]
        starts = jnp.arange(0, n - size + 1, step)
        win = starts[:, None] + jnp.arange(size)[None, :]   # (W, size)
        g = jnp.take(v, win.reshape(-1), axis=ax)
        shp = list(v.shape)
        shp[ax:ax + 1] = [starts.shape[0], size]
        g = g.reshape(shp)
        return jnp.moveaxis(g, ax + 1, -1)

    return apply(fn, _t(x), op_name="unfold")
