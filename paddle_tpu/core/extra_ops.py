"""Tensor-API surface that does not fit the single-source op schema:
list/tuple outputs, host-side results, predicates, and random ops.

Reference: assorted paddle/phi kernels + python/paddle/tensor/* wrappers
(SURVEY.md §2.1 kernel corpus). Installed into the top-level namespace by
paddle_tpu/__init__.py.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .dispatch import apply
from .tensor import Tensor
from .. import random as _random


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# -- predicates / introspection ---------------------------------------------
def is_complex(x) -> bool:
    return jnp.iscomplexobj(_v(x))


def is_floating_point(x) -> bool:
    return jnp.issubdtype(_v(x).dtype, jnp.floating)


def is_empty(x) -> Tensor:
    return Tensor(jnp.asarray(_v(x).size == 0))


def rank(x) -> Tensor:
    return Tensor(jnp.asarray(_v(x).ndim, jnp.int32))


def tolist(x) -> list:
    return _t(x).tolist()


def broadcast_shape(x_shape, y_shape) -> List[int]:
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


# -- copies / views ----------------------------------------------------------
def clone(x) -> Tensor:
    """Differentiable copy (paddle.clone — delegates to Tensor.clone)."""
    return _t(x).clone()


def hstack(x, name=None) -> Tensor:
    """paddle.hstack: takes a LIST/tuple of tensors (concat along dim 1,
    or dim 0 for 1-D inputs — numpy hstack semantics)."""
    ts = [_t(t) for t in x]
    return apply(lambda *vs: jnp.hstack(vs), *ts, op_name="hstack")


def view(x, shape_or_dtype):
    """paddle.view: reshape view (or dtype reinterpret for a dtype arg)."""
    if isinstance(shape_or_dtype, (list, tuple)):
        return apply(lambda v: v.reshape(tuple(
            int(s) for s in shape_or_dtype)), _t(x), op_name="view")
    from .dtype import convert_dtype
    return apply(lambda v: v.view(convert_dtype(shape_or_dtype)), _t(x),
                 op_name="view_dtype")


def broadcast_tensors(inputs: Sequence) -> List[Tensor]:
    vals = [_v(i) for i in inputs]
    shape = jnp.broadcast_shapes(*[v.shape for v in vals])
    return [apply(lambda v, s=shape: jnp.broadcast_to(v, s), _t(i),
                  op_name="broadcast_tensors") for i in inputs]


# -- splits / stacks ---------------------------------------------------------
def unstack(x, axis=0, num=None) -> List[Tensor]:
    v = _v(x)
    n = num or v.shape[axis]
    return [apply(lambda a, i=i: jnp.take(a, i, axis=axis), _t(x),
                  op_name="unstack") for i in range(n)]


def _nsplit(x, num_or_sections, axis):
    from .math_ops import split
    return split(_t(x), num_or_sections, axis=axis)


def hsplit(x, num_or_sections):
    v = _v(x)
    return _nsplit(x, num_or_sections, 0 if v.ndim == 1 else 1)


def vsplit(x, num_or_sections):
    return _nsplit(x, num_or_sections, 0)


def dsplit(x, num_or_sections):
    return _nsplit(x, num_or_sections, 2)


# -- indexing ---------------------------------------------------------------
def slice(x, axes, starts, ends) -> Tensor:  # noqa: A001 — paddle name
    """paddle.slice: static slice along the given axes."""
    import builtins

    def fn(v):
        idx = [builtins.slice(None)] * v.ndim
        for ax, s, e in zip(axes, starts, ends):
            dim = v.shape[ax]
            s = int(s) if s >= 0 else int(s) + dim
            e = int(e) if e >= 0 else int(e) + dim
            idx[ax] = builtins.slice(max(s, 0), min(e, dim))
        return v[tuple(idx)]

    return apply(fn, _t(x), op_name="slice")


def shard_index(input, index_num: int, nshards: int, shard_id: int,
                ignore_value: int = -1) -> Tensor:
    """paddle.shard_index: map a global index to its shard-local value,
    ignore_value for indices owned by other shards (PS-era embedding
    sharding helper; kept for API parity)."""
    size = (index_num + nshards - 1) // nshards

    def fn(v):
        owner = v // size
        local = v % size
        return jnp.where(owner == shard_id, local,
                         jnp.full_like(v, ignore_value))

    return apply(fn, _t(input), op_name="shard_index")


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None):
    """Host-side op (output length is data-dependent — not jittable; the
    reference's GPU kernel also compacts dynamically)."""
    v = np.asarray(_v(x))
    moved = False
    if axis is None:
        v = v.reshape(-1)
    elif axis % v.ndim != 0:
        v = np.moveaxis(v, axis, 0)  # dedupe runs along the given axis
        moved = True
    keep = np.ones(v.shape[0], bool)
    if v.shape[0] > 1:
        if v.ndim == 1:
            keep[1:] = v[1:] != v[:-1]
        else:
            keep[1:] = np.any(v[1:] != v[:-1],
                              axis=tuple(range(1, v.ndim)))
    kept = v[keep]
    if moved:
        kept = np.moveaxis(kept, 0, axis)
    out = Tensor(jnp.asarray(kept))
    res = [out]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        res.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        pos = np.flatnonzero(keep)
        counts = np.diff(np.append(pos, v.shape[0]))
        res.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return res[0] if len(res) == 1 else tuple(res)


# -- linalg adjacent ---------------------------------------------------------
def inverse(x) -> Tensor:
    return apply(lambda v: jnp.linalg.inv(v), _t(x), op_name="inverse")


# -- random ------------------------------------------------------------------
def poisson(x) -> Tensor:
    """Element-wise Poisson sample with rate x (paddle.poisson)."""
    key = _random.next_key()
    return apply(lambda v: jax.random.poisson(key, v, v.shape).astype(
        v.dtype), _t(x), op_name="poisson")
