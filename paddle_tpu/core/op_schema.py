"""Single-source op schema: one table drives the public API entry, the
numpy oracle test, the dtype sweep, and the gradient check.

Rebuild of the reference's YAML op definitions + OpTest harness
(paddle/phi/api/yaml/ops.yaml, paddle/phi/api/generator/*.py,
test/legacy_test/op_test.py — SURVEY.md §2.1 op-codegen row, §4 op-test
row). The reference generates C++ APIs and grad nodes from YAML and tests
every op on every backend with per-dtype tolerances; here each
:class:`OpSpec` carries

* ``fn``      — the jax implementation (vjp comes free via the tape),
* ``oracle``  — an independent numpy reference,
* ``sample``  — example-argument generator (shapes per case),
* ``dtypes`` / per-dtype ``tol`` — the sweep matrix,
* ``grad``    — whether to finite-difference-check the tape gradient.

``install()`` materialises a paddle-shaped public wrapper (through the
dispatch funnel, so AMP / nan-inf checks / profiler spans apply) for every
spec not already hand-written; tests/test_op_schema.py consumes the same
table, so adding ONE spec adds the API and its fp32+bf16 oracle coverage.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .dispatch import apply
from .tensor import Tensor


# per-dtype default relative tolerances (reference OpTest: fp32 1e-5-ish,
# bf16 ~1e-2 — bf16 has 8 mantissa bits)
DEFAULT_TOL = {"float32": 2e-5, "bfloat16": 2e-2, "float16": 2e-3}


@dataclass
class OpSpec:
    name: str
    fn: Callable                        # jax impl: fn(*arrays, **attrs)
    oracle: Callable                    # numpy impl: oracle(*nparrays, **attrs)
    sample: Callable                    # sample(rng) -> (args tuple, attrs dict)
    dtypes: Tuple[str, ...] = ("float32", "bfloat16")
    tol: Dict[str, float] = field(default_factory=dict)
    atol: float = 1e-6
    grad: bool = True                   # finite-difference check (fp32 only)
    grad_arg: int = 0                   # which positional arg to diff against
    n_outputs: int = 1
    integer_inputs: Tuple[int, ...] = ()  # positions NOT cast to the dtype

    def tolerance(self, dtype: str) -> float:
        return self.tol.get(dtype, DEFAULT_TOL.get(dtype, 1e-5))


OPS: Dict[str, OpSpec] = {}


def register(spec: OpSpec) -> OpSpec:
    assert spec.name not in OPS, f"duplicate op spec {spec.name}"
    OPS[spec.name] = spec
    return spec


def make_public(spec: OpSpec) -> Callable:
    """Public paddle-shaped wrapper for a spec (Tensor in/out, dispatch
    funnel for AMP/nan-inf/profiler)."""

    def op(*args, **attrs):
        attrs.pop("name", None)
        return apply(functools.partial(spec.fn, **attrs), *args,
                     op_name=spec.name)

    op.__name__ = spec.name
    op.__qualname__ = spec.name
    op.__doc__ = (f"``{spec.name}`` — generated from the single-source op "
                  f"schema (core/op_schema.py); oracle-tested on "
                  f"{'/'.join(spec.dtypes)}.")
    return op


def install(namespace: dict, only_missing: bool = True) -> list:
    """Install public wrappers for every registered spec into ``namespace``
    (e.g. paddle_tpu's module dict). Returns the installed names."""
    added = []
    for name, spec in OPS.items():
        if only_missing and name in namespace and namespace[name] is not None:
            continue
        namespace[name] = make_public(spec)
        added.append(name)
    return added


# ===========================================================================
# specs — tensor ops the round-1 corpus lacked (reference:
# paddle/phi/kernels/{cpu,gpu}/*_kernel.* — SURVEY.md §2.1 kernel corpus)
# ===========================================================================
def _r(shape):
    def gen(rng):
        return (rng.randn(*shape).astype(np.float32),), {}
    return gen


def _seg_ids(n, m):
    def gen(rng):
        data = rng.randn(n, 4).astype(np.float32)
        ids = np.sort(rng.randint(0, m, n)).astype(np.int32)
        return (data, ids), {"num_segments": m}
    return gen


def _np_segment(reduce):
    def oracle(data, ids, num_segments):
        out_shape = (num_segments,) + data.shape[1:]
        init = {"sum": 0.0, "mean": 0.0,
                "max": -np.inf, "min": np.inf}[reduce]
        out = np.full(out_shape, init, np.float32)
        cnt = np.zeros((num_segments,), np.int64)
        for i, s in enumerate(ids):
            if reduce in ("sum", "mean"):
                out[s] += data[i]
            elif reduce == "max":
                out[s] = np.maximum(out[s], data[i])
            else:
                out[s] = np.minimum(out[s], data[i])
            cnt[s] += 1
        if reduce == "mean":
            out = out / np.maximum(cnt, 1)[(...,) + (None,) * (data.ndim - 1)]
        if reduce in ("max", "min"):
            out[cnt == 0] = 0.0  # paddle zeroes empty segments
        return out
    return oracle


def _jax_segment(reduce):
    def fn(data, ids, num_segments):
        f = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
             "min": jax.ops.segment_min}.get(reduce)
        if reduce == "mean":
            s = jax.ops.segment_sum(data, ids, num_segments)
            cnt = jax.ops.segment_sum(jnp.ones_like(ids, jnp.float32), ids,
                                      num_segments)
            return s / jnp.maximum(cnt, 1.0).reshape(
                (-1,) + (1,) * (data.ndim - 1))
        out = f(data, ids, num_segments)
        if reduce in ("max", "min"):
            cnt = jax.ops.segment_sum(jnp.ones_like(ids, jnp.float32), ids,
                                      num_segments)
            mask = (cnt > 0).reshape((-1,) + (1,) * (data.ndim - 1))
            out = jnp.where(mask, out, 0.0)
        return out.astype(data.dtype)
    return fn


for _red in ("sum", "mean", "max", "min"):
    register(OpSpec(
        name=f"segment_{_red}",
        fn=_jax_segment(_red),
        oracle=_np_segment(_red),
        sample=_seg_ids(16, 5),
        integer_inputs=(1,),
        grad=_red in ("sum", "mean"),
        tol={"bfloat16": 4e-2},
    ))


register(OpSpec(
    name="index_add",
    fn=lambda x, index, value, axis=0: (
        x + jnp.zeros_like(x).at[
            (slice(None),) * (axis % x.ndim) + (index,)].add(value)),
    oracle=lambda x, index, value, axis=0: _np_index_add(x, index, value, axis),
    sample=lambda rng: ((rng.randn(8, 4).astype(np.float32),
                         rng.randint(0, 8, 5).astype(np.int32),
                         rng.randn(5, 4).astype(np.float32)), {"axis": 0}),
    integer_inputs=(1,),
    grad_arg=0,
))


def _np_index_add(x, index, value, axis):
    out = x.astype(np.float64).copy()
    for i, ix in enumerate(index):
        sl = [slice(None)] * x.ndim
        sl[axis] = ix
        out[tuple(sl)] += value[i]
    return out.astype(x.dtype)


register(OpSpec(
    name="trace",
    fn=lambda x, offset=0, axis1=0, axis2=1: jnp.trace(
        x, offset=offset, axis1=axis1, axis2=axis2),
    oracle=lambda x, offset=0, axis1=0, axis2=1: np.trace(
        x, offset=offset, axis1=axis1, axis2=axis2),
    sample=lambda rng: ((rng.randn(5, 6).astype(np.float32),),
                        {"offset": 1}),
))

register(OpSpec(
    name="nanmedian",
    fn=lambda x, axis=None, keepdim=False: jnp.nanmedian(
        x, axis=axis, keepdims=keepdim),
    oracle=lambda x, axis=None, keepdim=False: np.nanmedian(
        x, axis=axis, keepdims=keepdim),
    sample=lambda rng: ((np.where(rng.rand(6, 7) < 0.2, np.nan,
                                  rng.randn(6, 7)).astype(np.float32),),
                        {"axis": 1}),
    grad=False,
))

register(OpSpec(
    name="histogram",
    fn=lambda x, bins=100, min=0.0, max=0.0: jnp.histogram(
        x, bins=bins,
        range=None if (min == 0.0 and max == 0.0) else (min, max))[0],
    oracle=lambda x, bins=100, min=0.0, max=0.0: np.histogram(
        x, bins=bins,
        range=None if (min == 0.0 and max == 0.0) else (min, max))[0],
    sample=lambda rng: ((rng.randn(64).astype(np.float32),),
                        {"bins": 8, "min": -2.0, "max": 2.0}),
    dtypes=("float32",),
    grad=False,
))

register(OpSpec(
    name="bucketize",
    # int64 only materialises under jax_enable_x64; default to int32 to
    # avoid a per-call truncation warning with identical results
    fn=lambda x, sorted_sequence, out_int32=False, right=False:
        jnp.searchsorted(sorted_sequence, x,
                         side="right" if right else "left").astype(jnp.int32),
    oracle=lambda x, sorted_sequence, out_int32=False, right=False:
        np.searchsorted(sorted_sequence, x,
                        side="right" if right else "left"),
    sample=lambda rng: ((rng.randn(10).astype(np.float32),
                         np.sort(rng.randn(6)).astype(np.float32)), {}),
    dtypes=("float32",),
    grad=False,
))

register(OpSpec(
    name="rot90",
    fn=lambda x, k=1, axes=(0, 1): jnp.rot90(x, k=k, axes=tuple(axes)),
    oracle=lambda x, k=1, axes=(0, 1): np.rot90(x, k=k, axes=tuple(axes)),
    sample=lambda rng: ((rng.randn(4, 5).astype(np.float32),), {"k": 3}),
))

register(OpSpec(
    name="diff",
    fn=lambda x, n=1, axis=-1: jnp.diff(x, n=n, axis=axis),
    oracle=lambda x, n=1, axis=-1: np.diff(x, n=n, axis=axis),
    sample=lambda rng: ((rng.randn(4, 9).astype(np.float32),), {"n": 2}),
))

register(OpSpec(
    name="logcumsumexp",
    fn=lambda x, axis=-1: jax.lax.associative_scan(
        jnp.logaddexp, x, axis=axis),
    oracle=lambda x, axis=-1: np.log(np.cumsum(
        np.exp(x.astype(np.float64)), axis=axis)),
    sample=lambda rng: ((rng.randn(4, 8).astype(np.float32),), {}),
    tol={"bfloat16": 5e-2},
))

register(OpSpec(
    name="renorm",
    fn=lambda x, p=2.0, axis=0, max_norm=1.0: _jax_renorm(x, p, axis, max_norm),
    oracle=lambda x, p=2.0, axis=0, max_norm=1.0: _np_renorm(x, p, axis, max_norm),
    sample=lambda rng: ((rng.randn(5, 6).astype(np.float32) * 3,),
                        {"p": 2.0, "axis": 0, "max_norm": 1.0}),
))


def _jax_renorm(x, p, axis, max_norm):
    ax = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    norms = jnp.sum(jnp.abs(x.astype(jnp.float32)) ** p,
                    axis=ax, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return (x * factor).astype(x.dtype)


def _np_renorm(x, p, axis, max_norm):
    ax = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    norms = np.sum(np.abs(x.astype(np.float64)) ** p,
                   axis=ax, keepdims=True) ** (1.0 / p)
    factor = np.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return (x * factor).astype(x.dtype)


register(OpSpec(
    name="logaddexp",
    fn=jnp.logaddexp,
    oracle=np.logaddexp,
    sample=lambda rng: ((rng.randn(6, 6).astype(np.float32),
                         rng.randn(6, 6).astype(np.float32)), {}),
))

register(OpSpec(
    name="hypot",
    # same impl as the hand-written math_ops.hypot (which install() keeps):
    # the overflow-safe jnp.hypot, so the spec tests the live op either way
    fn=jnp.hypot,
    oracle=np.hypot,
    sample=lambda rng: ((rng.randn(6).astype(np.float32),
                         rng.randn(6).astype(np.float32)), {}),
))

register(OpSpec(
    name="copysign",
    fn=jnp.copysign,
    oracle=np.copysign,
    sample=lambda rng: ((rng.randn(8).astype(np.float32),
                         rng.randn(8).astype(np.float32)), {}),
    grad=False,
))

register(OpSpec(
    name="frexp",
    fn=lambda x: jnp.frexp(x),
    oracle=lambda x: np.frexp(x),
    sample=lambda rng: ((rng.randn(8).astype(np.float32),), {}),
    dtypes=("float32",),
    grad=False,
    n_outputs=2,
))

register(OpSpec(
    name="ldexp",
    fn=lambda x, y: jnp.ldexp(x, y),
    oracle=lambda x, y: np.ldexp(x, y),
    sample=lambda rng: ((rng.randn(8).astype(np.float32),
                         rng.randint(-3, 3, 8).astype(np.int32)), {}),
    dtypes=("float32",),
    integer_inputs=(1,),
    grad=False,
))

register(OpSpec(
    name="vander",
    fn=lambda x, n=None, increasing=False: jnp.vander(
        x, N=n, increasing=increasing),
    oracle=lambda x, n=None, increasing=False: np.vander(
        x, N=n, increasing=increasing),
    sample=lambda rng: ((rng.randn(5).astype(np.float32),),
                        {"n": 4, "increasing": True}),
    dtypes=("float32",),
))

# --- elementwise / special functions ---------------------------------------
for _name, _jf, _nf, _gen, _grad in [
    ("heaviside", lambda x, y: jnp.heaviside(x, y), np.heaviside,
     lambda rng: ((rng.randn(8).astype(np.float32),
                   rng.rand(8).astype(np.float32)), {}), False),
    ("nextafter", jnp.nextafter, np.nextafter,
     lambda rng: ((rng.randn(8).astype(np.float32),
                   rng.randn(8).astype(np.float32)), {}), False),
    ("i0", lambda x: jnp.i0(x), lambda x: np.i0(x),
     lambda rng: ((rng.randn(8).astype(np.float32),), {}), False),
    ("sinc", jnp.sinc, np.sinc,
     lambda rng: ((rng.randn(8).astype(np.float32),), {}), True),
    ("signbit", jnp.signbit, np.signbit,
     lambda rng: ((rng.randn(8).astype(np.float32),), {}), False),
    ("deg2rad", jnp.deg2rad, np.deg2rad,
     lambda rng: ((rng.randn(8).astype(np.float32) * 90,), {}), True),
    ("rad2deg", jnp.rad2deg, np.rad2deg,
     lambda rng: ((rng.randn(8).astype(np.float32),), {}), True),
    ("xlogy", lambda x, y: jnp.where(x == 0, 0.0, x * jnp.log(y)),
     lambda x, y: np.where(x == 0, 0.0, x * np.log(y)),
     lambda rng: ((rng.rand(8).astype(np.float32),
                   rng.rand(8).astype(np.float32) + 0.1), {}), True),
    ("logit", lambda x, eps=1e-6: jnp.log(
        jnp.clip(x, eps, 1 - eps) / (1 - jnp.clip(x, eps, 1 - eps))),
     lambda x, eps=1e-6: np.log(
         np.clip(x, eps, 1 - eps) / (1 - np.clip(x, eps, 1 - eps))),
     lambda rng: ((rng.rand(8).astype(np.float32),), {}), True),
    ("nansum", lambda x, axis=None, keepdim=False: jnp.nansum(
        x, axis=axis, keepdims=keepdim),
     lambda x, axis=None, keepdim=False: np.nansum(
         x, axis=axis, keepdims=keepdim),
     lambda rng: ((np.where(rng.rand(5, 6) < 0.2, np.nan,
                            rng.randn(5, 6)).astype(np.float32),),
                  {"axis": 1}), False),
    ("nanmean", lambda x, axis=None, keepdim=False: jnp.nanmean(
        x, axis=axis, keepdims=keepdim),
     lambda x, axis=None, keepdim=False: np.nanmean(
         x, axis=axis, keepdims=keepdim),
     lambda rng: ((np.where(rng.rand(5, 6) < 0.2, np.nan,
                            rng.randn(5, 6)).astype(np.float32),),
                  {"axis": 1}), False),
]:
    register(OpSpec(name=_name, fn=_jf, oracle=_nf, sample=_gen, grad=_grad,
                    dtypes=("float32",) if _name in
                    ("nextafter", "signbit", "i0") else ("float32", "bfloat16")))


# --- integer ops ------------------------------------------------------------
for _name, _jf, _nf in [
    ("gcd", jnp.gcd, np.gcd),
    ("lcm", jnp.lcm, np.lcm),
    ("bitwise_left_shift", jnp.left_shift, np.left_shift),
    ("bitwise_right_shift", jnp.right_shift, np.right_shift),
]:
    register(OpSpec(
        name=_name, fn=_jf, oracle=_nf,
        sample=(lambda rng: ((rng.randint(1, 40, 8).astype(np.int32),
                              rng.randint(1, 6, 8).astype(np.int32)), {})),
        dtypes=("int32",), integer_inputs=(0, 1), grad=False))


# --- linalg-adjacent --------------------------------------------------------
register(OpSpec(
    name="addmm",
    fn=lambda input, x, y, beta=1.0, alpha=1.0: beta * input + alpha * (x @ y),
    oracle=lambda input, x, y, beta=1.0, alpha=1.0:
        beta * input + alpha * (x @ y),
    sample=lambda rng: ((rng.randn(4, 6).astype(np.float32),
                         rng.randn(4, 5).astype(np.float32),
                         rng.randn(5, 6).astype(np.float32)),
                        {"beta": 0.5, "alpha": 2.0}),
    tol={"bfloat16": 5e-2},
))

register(OpSpec(
    name="cross",
    fn=lambda x, y, axis=-1: jnp.cross(x, y, axis=axis),
    oracle=lambda x, y, axis=-1: np.cross(x, y, axis=axis),
    sample=lambda rng: ((rng.randn(4, 3).astype(np.float32),
                         rng.randn(4, 3).astype(np.float32)), {}),
))

register(OpSpec(
    name="cdist",
    fn=lambda x, y, p=2.0: _jax_cdist(x, y, p),
    oracle=lambda x, y, p=2.0: _np_cdist(x, y, p),
    sample=lambda rng: ((rng.randn(5, 3).astype(np.float32),
                         rng.randn(6, 3).astype(np.float32)), {}),
    tol={"bfloat16": 5e-2},
))


def _jax_cdist(x, y, p):
    d = jnp.abs(x[..., :, None, :] - y[..., None, :, :])
    if p == 2.0:
        return jnp.sqrt(jnp.sum(d * d, axis=-1) + 1e-12)
    return jnp.sum(d ** p, axis=-1) ** (1.0 / p)


def _np_cdist(x, y, p):
    d = np.abs(x[..., :, None, :] - y[..., None, :, :]).astype(np.float64)
    if p == 2.0:
        return np.sqrt((d * d).sum(-1) + 1e-12)
    return (d ** p).sum(-1) ** (1.0 / p)


register(OpSpec(
    name="pdist",
    fn=lambda x, p=2.0: _jax_cdist(x, x, p)[
        tuple(jnp.triu_indices(x.shape[0], k=1))],
    oracle=lambda x, p=2.0: _np_cdist(x, x, p)[
        np.triu_indices(x.shape[0], k=1)],
    sample=lambda rng: ((rng.randn(6, 4).astype(np.float32),), {}),
    tol={"bfloat16": 5e-2},
))

register(OpSpec(
    name="clip_by_norm",
    fn=lambda x, max_norm: x * jnp.minimum(
        1.0, max_norm / (jnp.sqrt(jnp.sum(
            x.astype(jnp.float32) ** 2)) + 1e-12)).astype(x.dtype),
    oracle=lambda x, max_norm: x * min(
        1.0, max_norm / (np.sqrt((x.astype(np.float64) ** 2).sum()) + 1e-12)),
    sample=lambda rng: ((rng.randn(6, 4).astype(np.float32) * 3,),
                        {"max_norm": 1.0}),
))

register(OpSpec(
    name="block_diag",
    fn=lambda *xs: jax.scipy.linalg.block_diag(*xs),
    oracle=lambda *xs: _np_block_diag(*xs),
    sample=lambda rng: ((rng.randn(2, 3).astype(np.float32),
                         rng.randn(3, 2).astype(np.float32)), {}),
))


def _np_block_diag(*xs):
    rows = sum(a.shape[0] for a in xs)
    cols = sum(a.shape[1] for a in xs)
    out = np.zeros((rows, cols), xs[0].dtype)
    r = c = 0
    for a in xs:
        out[r:r + a.shape[0], c:c + a.shape[1]] = a
        r += a.shape[0]
        c += a.shape[1]
    return out


# --- indexing ---------------------------------------------------------------
def _jax_take(x, index, mode="raise"):
    n = x.size
    if mode == "raise":
        # paddle errors on out-of-range; enforceable only on concrete
        # (eager) indices — under tracing fall back to wrap, documented
        try:
            lo, hi = int(jnp.min(index)), int(jnp.max(index))
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            lo, hi = -n, n - 1
        if lo < -n or hi >= n:
            raise IndexError(
                f"take: index out of range for {n} elements "
                f"(min {lo}, max {hi}); use mode='wrap' or 'clip'")
        mode = "wrap"  # in-range negatives behave pythonically
    return jnp.take(x.reshape(-1), index,
                    mode="clip" if mode == "clip" else "wrap")


register(OpSpec(
    name="take",
    fn=_jax_take,
    oracle=lambda x, index, mode="raise": np.take(
        x.reshape(-1), index, mode="clip" if mode == "clip" else "wrap"),
    sample=lambda rng: ((rng.randn(4, 5).astype(np.float32),
                         rng.randint(0, 20, 7).astype(np.int32)), {}),
    integer_inputs=(1,),
))

register(OpSpec(
    name="index_fill",
    fn=lambda x, index, axis, value: x.at[
        (slice(None),) * (axis % x.ndim) + (index,)].set(value),
    oracle=lambda x, index, axis, value: _np_index_fill(x, index, axis, value),
    sample=lambda rng: ((rng.randn(6, 4).astype(np.float32),
                         rng.permutation(6)[:3].astype(np.int32)),
                        {"axis": 0, "value": 9.0}),
    integer_inputs=(1,),
))


def _np_index_fill(x, index, axis, value):
    out = x.copy()
    sl = [slice(None)] * x.ndim
    sl[axis] = index
    out[tuple(sl)] = value
    return out


register(OpSpec(
    name="triu_indices",
    fn=lambda row, col=None, offset=0: jnp.stack(
        jnp.triu_indices(row, k=offset, m=col or row)),
    oracle=lambda row, col=None, offset=0: np.stack(
        np.triu_indices(row, k=offset, m=col or row)),
    sample=lambda rng: ((), {"row": 5, "offset": 1}),
    dtypes=("float32",),
    grad=False,
))

register(OpSpec(
    name="tril_indices",
    fn=lambda row, col=None, offset=0: jnp.stack(
        jnp.tril_indices(row, k=offset, m=col or row)),
    oracle=lambda row, col=None, offset=0: np.stack(
        np.tril_indices(row, k=offset, m=col or row)),
    sample=lambda rng: ((), {"row": 5, "offset": -1}),
    dtypes=("float32",),
    grad=False,
))


# --- batch 4: tensor-API audit gaps (round 2) -------------------------------
register(OpSpec(
    name="as_complex",
    fn=lambda x: jax.lax.complex(x[..., 0], x[..., 1]),
    oracle=lambda x: x[..., 0] + 1j * x[..., 1],
    sample=lambda rng: ((rng.randn(4, 3, 2).astype(np.float32),), {}),
    dtypes=("float32",), grad=False,
))

register(OpSpec(
    name="as_real",
    fn=lambda x: jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1),
    oracle=lambda x: np.stack([np.real(x), np.imag(x)], axis=-1),
    sample=lambda rng: (((rng.randn(4, 3) + 1j * rng.randn(4, 3))
                         .astype(np.complex64),), {}),
    dtypes=("complex64",), integer_inputs=(0,), grad=False,
))

register(OpSpec(
    name="diagflat",
    fn=lambda x, offset=0: jnp.diagflat(x, k=offset),
    oracle=lambda x, offset=0: np.diagflat(x, k=offset),
    sample=lambda rng: ((rng.randn(4).astype(np.float32),), {"offset": 1}),
))

register(OpSpec(
    name="dist",
    fn=lambda x, y, p=2.0: _jax_dist(x, y, p),
    oracle=lambda x, y, p=2.0: _np_dist(x, y, p),
    sample=lambda rng: ((rng.randn(4, 3).astype(np.float32),
                         rng.randn(4, 3).astype(np.float32)), {"p": 2.0}),
))


def _jax_dist(x, y, p):
    d = jnp.abs(x - y).astype(jnp.float32)
    if p == float("inf"):
        return jnp.max(d)
    if p == 0:
        return jnp.sum((d != 0).astype(jnp.float32))
    return jnp.sum(d ** p) ** (1.0 / p)


def _np_dist(x, y, p):
    d = np.abs(np.asarray(x, np.float64) - np.asarray(y, np.float64))
    if p == float("inf"):
        return d.max()
    if p == 0:
        return float((d != 0).sum())
    return (d ** p).sum() ** (1.0 / p)


register(OpSpec(
    name="inner",
    fn=jnp.inner,
    oracle=np.inner,
    sample=lambda rng: ((rng.randn(3, 4).astype(np.float32),
                         rng.randn(5, 4).astype(np.float32)), {}),
    tol={"bfloat16": 5e-2},
))

register(OpSpec(
    name="mv",
    fn=lambda x, vec: jnp.matmul(x, vec),
    oracle=lambda x, vec: np.matmul(x, vec),
    sample=lambda rng: ((rng.randn(4, 6).astype(np.float32),
                         rng.randn(6).astype(np.float32)), {}),
    tol={"bfloat16": 5e-2},
))

register(OpSpec(
    name="nan_to_num",
    fn=lambda x, nan=0.0, posinf=None, neginf=None: jnp.nan_to_num(
        x, nan=nan, posinf=posinf, neginf=neginf),
    # inf maps to the dtype's max — pin the oracle to fp32 (the harness
    # passes float64 args, whose max differs)
    oracle=lambda x, nan=0.0, posinf=None, neginf=None: np.nan_to_num(
        np.asarray(x, np.float32), nan=nan, posinf=posinf, neginf=neginf),
    sample=lambda rng: ((np.asarray([1.0, np.nan, np.inf, -np.inf, 2.0],
                                    np.float32),), {"nan": 9.0}),
    dtypes=("float32",), grad=False,
))

register(OpSpec(
    name="nanquantile",
    fn=lambda x, q, axis=None, keepdim=False: jnp.nanquantile(
        x, q, axis=axis, keepdims=keepdim),
    oracle=lambda x, q, axis=None, keepdim=False: np.nanquantile(
        x, q, axis=axis, keepdims=keepdim),
    sample=lambda rng: ((np.where(rng.rand(5, 8) < 0.2, np.nan,
                                  rng.randn(5, 8)).astype(np.float32),),
                        {"q": 0.75, "axis": 1}),
    dtypes=("float32",), grad=False,
))

register(OpSpec(
    name="polar",
    fn=lambda abs, angle: jax.lax.complex(abs * jnp.cos(angle),
                                          abs * jnp.sin(angle)),
    oracle=lambda abs, angle: abs * np.exp(1j * angle.astype(np.float64)),
    sample=lambda rng: ((rng.rand(6).astype(np.float32) + 0.1,
                         rng.randn(6).astype(np.float32)), {}),
    dtypes=("float32",), grad=False,
))

register(OpSpec(
    name="sgn",
    fn=lambda x: jnp.where(jnp.abs(x) == 0, 0.0 * x, x / jnp.abs(x))
    if jnp.iscomplexobj(x) else jnp.sign(x),
    oracle=lambda x: np.where(np.abs(x) == 0, 0 * x, x / np.abs(x))
    if np.iscomplexobj(x) else np.sign(x),
    sample=lambda rng: ((rng.randn(8).astype(np.float32),), {}),
    grad=False,
))

register(OpSpec(
    name="stanh",
    fn=lambda x, scale_a=0.67, scale_b=1.7159: scale_b * jnp.tanh(
        scale_a * x),
    oracle=lambda x, scale_a=0.67, scale_b=1.7159: scale_b * np.tanh(
        scale_a * x),
    sample=lambda rng: ((rng.randn(8).astype(np.float32),), {}),
))

register(OpSpec(
    name="tensordot",
    fn=lambda x, y, axes=2: jnp.tensordot(x, y, axes=axes),
    oracle=lambda x, y, axes=2: np.tensordot(x, y, axes=axes),
    sample=lambda rng: ((rng.randn(3, 4, 5).astype(np.float32),
                         rng.randn(4, 5, 6).astype(np.float32)), {}),
    tol={"bfloat16": 5e-2},
))

register(OpSpec(
    name="unflatten",
    fn=lambda x, axis, shape: x.reshape(
        x.shape[:axis % x.ndim] + tuple(shape)
        + x.shape[axis % x.ndim + 1:]),
    oracle=lambda x, axis, shape: x.reshape(
        x.shape[:axis % x.ndim] + tuple(shape)
        + x.shape[axis % x.ndim + 1:]),
    sample=lambda rng: ((rng.randn(2, 12).astype(np.float32),),
                        {"axis": 1, "shape": (3, 4)}),
))


def _cummax_impl(op):
    def fn(x, axis=-1):
        ax = axis % x.ndim

        def comb(a, b):
            av, ai = a
            bv, bi = b
            take_b = bv > av if op == "max" else bv < av
            return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

        iota = jax.lax.broadcasted_iota(jnp.int32, x.shape, ax)
        vals, idx = jax.lax.associative_scan(comb, (x, iota), axis=ax)
        return vals, idx
    return fn


def _np_cummax(op):
    def oracle(x, axis=-1):
        x = np.asarray(x)
        ax = axis % x.ndim
        xm = np.moveaxis(x, ax, -1)
        # C-contiguous buffers: reshape on a non-contiguous view would
        # COPY, discarding the writes (moveaxis makes xm non-contiguous
        # for middle axes)
        flat = np.ascontiguousarray(xm).reshape(-1, xm.shape[-1])
        vals = np.empty(xm.shape, xm.dtype)
        idx = np.empty(xm.shape, np.int64)
        fv = vals.reshape(-1, xm.shape[-1])
        fi = idx.reshape(-1, xm.shape[-1])
        for r in range(flat.shape[0]):
            best, bi = flat[r, 0], 0
            for c in range(flat.shape[1]):
                better = flat[r, c] > best if op == "max" else flat[r, c] < best
                if better:
                    best, bi = flat[r, c], c
                fv[r, c], fi[r, c] = best, bi
        return np.moveaxis(vals, -1, ax), np.moveaxis(idx, -1, ax)
    return oracle


for _op in ("max", "min"):
    register(OpSpec(
        name=f"cum{_op}",
        fn=_cummax_impl(_op),
        oracle=_np_cummax(_op),
        sample=lambda rng: ((rng.randn(3, 7).astype(np.float32),),
                            {"axis": 1}),
        n_outputs=2,
        grad=False,
    ))


register(OpSpec(
    name="scatter_nd",
    fn=lambda index, updates, shape: jnp.zeros(
        tuple(shape), updates.dtype).at[tuple(index[..., i]
                                              for i in range(index.shape[-1]))
                                        ].add(updates),
    oracle=lambda index, updates, shape: _np_scatter_nd(index, updates, shape),
    sample=lambda rng: ((rng.randint(0, 5, (6, 1)).astype(np.int32),
                         rng.randn(6).astype(np.float32)),
                        {"shape": (5,)}),
    integer_inputs=(0,),
    grad_arg=1,
))


def _np_scatter_nd(index, updates, shape):
    out = np.zeros(tuple(shape), np.float64)
    for i in range(index.shape[0]):
        out[tuple(index[i])] += updates[i]
    return out


# --- vision rearrangement ---------------------------------------------------
register(OpSpec(
    name="pixel_unshuffle",
    fn=lambda x, downscale_factor, data_format="NCHW": _jax_pixel_unshuffle(
        x, downscale_factor),
    oracle=lambda x, downscale_factor, data_format="NCHW":
        _np_pixel_unshuffle(x, downscale_factor),
    sample=lambda rng: ((rng.randn(2, 3, 4, 4).astype(np.float32),),
                        {"downscale_factor": 2}),
))


def _jax_pixel_unshuffle(x, r):
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // r, r, w // r, r)
    return x.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * r * r, h // r, w // r)


def _np_pixel_unshuffle(x, r):
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // r, r, w // r, r)
    return x.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * r * r, h // r, w // r)


# --- complex ops (FFT companions) -------------------------------------------
def _complex_sample(rng):
    z = (rng.randn(6) + 1j * rng.randn(6)).astype(np.complex64)
    return (z,), {}


for _name, _jf, _nf in [
    ("angle", jnp.angle, np.angle),
    ("conj", jnp.conj, np.conj),
    ("real", jnp.real, np.real),
    ("imag", jnp.imag, np.imag),
]:
    register(OpSpec(name=_name, fn=_jf, oracle=_nf, sample=_complex_sample,
                    dtypes=("complex64",), integer_inputs=(0,), grad=False))


# --- more special functions -------------------------------------------------
register(OpSpec(
    name="i0e",
    fn=lambda x: jax.scipy.special.i0e(x),
    oracle=lambda x: np.i0(x) * np.exp(-np.abs(x)),
    sample=lambda rng: ((rng.randn(8).astype(np.float32),), {}),
    dtypes=("float32",),
    grad=False,
))

register(OpSpec(
    name="i1",
    fn=lambda x: jax.scipy.special.i1(x),
    oracle=lambda x: _np_i1(x),
    sample=lambda rng: ((rng.randn(8).astype(np.float32),), {}),
    dtypes=("float32",),
    grad=False,
))


def _np_i1(x):
    # series-free oracle via numpy's i0 derivative relation is unavailable;
    # use the scipy-compatible polynomial from Abramowitz–Stegun 9.8
    x = np.asarray(x, np.float64)
    ax = np.abs(x)
    small = ax < 3.75
    t = (x / 3.75) ** 2
    ser = x * (0.5 + t * (0.87890594 + t * (0.51498869 + t * (
        0.15084934 + t * (0.02658733 + t * (0.00301532 + t * 0.00032411))))))
    t2 = 3.75 / np.maximum(ax, 1e-12)
    big = (np.exp(ax) / np.sqrt(np.maximum(ax, 1e-12))) * (
        0.39894228 + t2 * (-0.03988024 + t2 * (-0.00362018 + t2 * (
            0.00163801 + t2 * (-0.01031555 + t2 * (0.02282967 + t2 * (
                -0.02895312 + t2 * (0.01787654 - t2 * 0.00420059))))))))
    return np.where(small, ser, np.sign(x) * big)


register(OpSpec(
    name="polygamma",
    fn=lambda x, n=1: jax.scipy.special.polygamma(n, x),
    oracle=lambda x, n=1: _np_polygamma(n, x),
    sample=lambda rng: ((rng.rand(6).astype(np.float32) * 3 + 0.5,),
                        {"n": 1}),
    dtypes=("float32",),
    tol={"float32": 1e-3},
    grad=False,
))


def _np_polygamma(n, x):
    # trigamma via numeric second derivative of lgamma — an independent
    # oracle implemented for n=1 ONLY
    assert n == 1, "oracle implements trigamma (n=1) only"
    h = 1e-4
    from math import lgamma

    def digamma(v):
        return (lgamma(v + h) - lgamma(v - h)) / (2 * h)

    flat = np.asarray(x, np.float64).reshape(-1)
    out = np.array([(digamma(v + h) - digamma(v - h)) / (2 * h)
                    for v in flat])
    return out.reshape(np.shape(x))


register(OpSpec(
    name="combinations",
    fn=lambda x, r=2, with_replacement=False: _jax_combinations(
        x, r, with_replacement),
    oracle=lambda x, r=2, with_replacement=False: _np_combinations(
        x, r, with_replacement),
    sample=lambda rng: ((rng.randn(5).astype(np.float32),), {"r": 2}),
    dtypes=("float32",),
    grad=False,
))


def _jax_combinations(x, r, with_replacement):
    import itertools
    n = x.shape[0]
    idx = list(itertools.combinations_with_replacement(range(n), r)
               if with_replacement else itertools.combinations(range(n), r))
    return x[jnp.asarray(idx, jnp.int32)]


def _np_combinations(x, r, with_replacement):
    # independent oracle: recursive enumeration (NOT itertools, which the
    # jax impl uses — a shared itertools misuse must not self-confirm)
    n = x.shape[0]
    out = []

    def rec(start, combo):
        if len(combo) == r:
            out.append([x[i] for i in combo])
            return
        for i in range(start, n):
            rec(i if with_replacement else i + 1, combo + [i])

    rec(0, [])
    return np.asarray(out, x.dtype)


register(OpSpec(
    name="channel_shuffle",
    fn=lambda x, groups, data_format="NCHW": x.reshape(
        x.shape[0], groups, x.shape[1] // groups, *x.shape[2:]).swapaxes(
            1, 2).reshape(x.shape),
    oracle=lambda x, groups, data_format="NCHW": x.reshape(
        x.shape[0], groups, x.shape[1] // groups, *x.shape[2:]).swapaxes(
            1, 2).reshape(x.shape),
    sample=lambda rng: ((rng.randn(2, 6, 3, 3).astype(np.float32),),
                        {"groups": 3}),
))


# ===========================================================================
# round-3 migration: the mechanical op families (elementwise, reductions,
# comparisons, shape/movement, indexing) onto the schema so the uniform
# fp32+bf16 oracle sweep covers the live public ops (VERDICT round-2 item 5;
# reference paddle/phi/api/yaml/ops.yaml + test/legacy_test/op_test.py:§0).
# install(only_missing=True) keeps every hand-written implementation — these
# specs add test coverage, not new dispatch paths.
# ===========================================================================
def _u1(lo=-2.0, hi=2.0, shape=(8,)):
    def gen(rng):
        return ((rng.rand(*shape) * (hi - lo) + lo).astype(np.float32),), {}
    return gen


def _u2(lo=-2.0, hi=2.0, shape=(6,)):
    def gen(rng):
        a = (rng.rand(*shape) * (hi - lo) + lo).astype(np.float32)
        b = (rng.rand(*shape) * (hi - lo) + lo).astype(np.float32)
        return (a, b), {}
    return gen


# --- smooth unary elementwise (grad-checked, fp32+bf16) ---------------------
for _name, _jf, _nf, _gen in [
    ("abs", jnp.abs, np.abs, _u1()),
    ("cos", jnp.cos, np.cos, _u1()),
    ("sin", jnp.sin, np.sin, _u1()),
    ("tan", jnp.tan, np.tan, _u1(-1.0, 1.0)),
    ("cosh", jnp.cosh, np.cosh, _u1()),
    ("sinh", jnp.sinh, np.sinh, _u1()),
    ("tanh", jnp.tanh, np.tanh, _u1()),
    ("exp", jnp.exp, np.exp, _u1()),
    ("expm1", jnp.expm1, np.expm1, _u1()),
    ("sigmoid", jax.nn.sigmoid, lambda x: 1 / (1 + np.exp(-x)), _u1()),
    ("neg", jnp.negative, np.negative, _u1()),
    ("square", jnp.square, np.square, _u1()),
    ("sqrt", jnp.sqrt, np.sqrt, _u1(0.1, 4.0)),
    ("rsqrt", lambda x: jax.lax.rsqrt(x), lambda x: 1 / np.sqrt(x),
     _u1(0.1, 4.0)),
    ("reciprocal", jnp.reciprocal, np.reciprocal, _u1(0.3, 3.0)),
    ("log", jnp.log, np.log, _u1(0.1, 5.0)),
    ("log2", jnp.log2, np.log2, _u1(0.1, 5.0)),
    ("log10", jnp.log10, np.log10, _u1(0.1, 5.0)),
    ("log1p", jnp.log1p, np.log1p, _u1(-0.5, 4.0)),
    ("erf", jax.scipy.special.erf, None, _u1()),
    ("acos", jnp.arccos, np.arccos, _u1(-0.9, 0.9)),
    ("asin", jnp.arcsin, np.arcsin, _u1(-0.9, 0.9)),
    ("atan", jnp.arctan, np.arctan, _u1()),
    ("acosh", jnp.arccosh, np.arccosh, _u1(1.1, 4.0)),
    ("asinh", jnp.arcsinh, np.arcsinh, _u1()),
    ("atanh", jnp.arctanh, np.arctanh, _u1(-0.9, 0.9)),
]:
    if _nf is None:  # erf oracle from scipy (numpy has none)
        import scipy.special as _sps
        _nf = _sps.erf
    register(OpSpec(name=_name, fn=_jf, oracle=_nf, sample=_gen,
                    tol={"bfloat16": 4e-2}))

# --- non-smooth unary (no FD grad) ------------------------------------------
for _name, _jf, _nf in [
    ("ceil", jnp.ceil, np.ceil),
    ("floor", jnp.floor, np.floor),
    ("round", jnp.round, np.round),
    ("trunc", jnp.trunc, np.trunc),
    ("sign", jnp.sign, np.sign),
]:
    register(OpSpec(name=_name, fn=_jf, oracle=_nf, sample=_u1(), grad=False))

# --- special functions (scipy oracles, fp32) --------------------------------
import scipy.special as _sps  # noqa: E402

for _name, _jf, _nf, _gen, _grad in [
    ("erfinv", jax.scipy.special.erfinv, _sps.erfinv, _u1(-0.9, 0.9), True),
    ("digamma", jax.scipy.special.digamma, _sps.digamma,
     _u1(0.5, 4.0), True),
    ("lgamma", jax.scipy.special.gammaln, _sps.gammaln,
     _u1(0.5, 4.0), True),
]:
    register(OpSpec(name=_name, fn=_jf, oracle=_nf, sample=_gen, grad=_grad,
                    dtypes=("float32",), tol={"float32": 1e-4}))

# --- binary elementwise -----------------------------------------------------
for _name, _jf, _nf, _gen, _grad in [
    ("add", jnp.add, np.add, _u2(), True),
    ("subtract", jnp.subtract, np.subtract, _u2(), True),
    ("multiply", jnp.multiply, np.multiply, _u2(), True),
    ("divide", jnp.divide, np.divide, _u2(0.5, 3.0), True),
    ("maximum", jnp.maximum, np.maximum, _u2(), True),
    ("minimum", jnp.minimum, np.minimum, _u2(), True),
    ("fmax", jnp.fmax, np.fmax, _u2(), True),
    ("fmin", jnp.fmin, np.fmin, _u2(), True),
    ("atan2", jnp.arctan2, np.arctan2, _u2(0.2, 2.0), True),
    ("pow", jnp.power, np.power, _u2(0.3, 2.0), True),
    ("mod", jnp.mod, np.mod, _u2(0.5, 3.0), False),
    ("remainder", jnp.mod, np.mod, _u2(0.5, 3.0), False),
    ("floor_divide", jnp.floor_divide, np.floor_divide,
     _u2(0.5, 5.0), False),
    ("floor_mod", jnp.mod, np.mod, _u2(0.5, 3.0), False),
]:
    register(OpSpec(name=_name, fn=_jf, oracle=_nf, sample=_gen, grad=_grad,
                    tol={"bfloat16": 4e-2}))

register(OpSpec(
    name="lerp",
    fn=lambda x, y, weight: x + weight * (y - x),
    oracle=lambda x, y, weight: x + weight * (y - x),
    sample=lambda rng: ((rng.randn(6).astype(np.float32),
                         rng.randn(6).astype(np.float32),
                         rng.rand(6).astype(np.float32)), {}),
))

register(OpSpec(
    name="scale",
    fn=lambda x, scale=1.0, bias=0.0, bias_after_scale=True:
        x * scale + bias if bias_after_scale else (x + bias) * scale,
    oracle=lambda x, scale=1.0, bias=0.0, bias_after_scale=True:
        x * scale + bias if bias_after_scale else (x + bias) * scale,
    sample=lambda rng: ((rng.randn(8).astype(np.float32),),
                        {"scale": 2.0, "bias": 0.5,
                         "bias_after_scale": False}),
))

register(OpSpec(
    name="increment",
    fn=lambda x, value=1.0: x + value,
    oracle=lambda x, value=1.0: x + value,
    sample=lambda rng: ((rng.randn(4).astype(np.float32),), {"value": 2.5}),
    grad=False,  # paddle-faithful IN-PLACE op: mutates x, not a tape leaf
))

register(OpSpec(
    name="clip",
    fn=lambda x, min=None, max=None: jnp.clip(x, min, max),
    oracle=lambda x, min=None, max=None: np.clip(x, min, max),
    sample=lambda rng: ((rng.randn(8).astype(np.float32) * 2,),
                        {"min": -1.0, "max": 1.5}),
    grad=False,  # FD undefined at the clip boundaries
))

# --- comparisons / logicals / predicates (fp32, no grad) --------------------
def _b2(rng):
    a = (rng.rand(8) > 0.5).astype(np.float32)
    b = (rng.rand(8) > 0.5).astype(np.float32)
    return (a, b), {}


for _name, _jf, _nf, _gen in [
    ("equal", jnp.equal, np.equal, _u2()),
    ("not_equal", jnp.not_equal, np.not_equal, _u2()),
    ("greater_than", jnp.greater, np.greater, _u2()),
    ("greater_equal", jnp.greater_equal, np.greater_equal, _u2()),
    ("less_than", jnp.less, np.less, _u2()),
    ("less_equal", jnp.less_equal, np.less_equal, _u2()),
    ("logical_and", jnp.logical_and, np.logical_and, _b2),
    ("logical_or", jnp.logical_or, np.logical_or, _b2),
    ("logical_xor", jnp.logical_xor, np.logical_xor, _b2),
    ("logical_not", jnp.logical_not, np.logical_not,
     lambda rng: (((rng.rand(8) > 0.5).astype(np.float32),), {})),
    ("isfinite", jnp.isfinite, np.isfinite,
     lambda rng: ((np.asarray([1.0, np.inf, -np.inf, np.nan, 2.0],
                              np.float32),), {})),
    ("isinf", jnp.isinf, np.isinf,
     lambda rng: ((np.asarray([1.0, np.inf, -np.inf, np.nan, 2.0],
                              np.float32),), {})),
    ("isnan", jnp.isnan, np.isnan,
     lambda rng: ((np.asarray([1.0, np.inf, np.nan, 2.0],
                              np.float32),), {})),
    ("isclose", jnp.isclose, np.isclose,
     lambda rng: ((np.asarray([1.0, 2.0, 3.0], np.float32),
                   np.asarray([1.0, 2.000001, 3.5], np.float32)), {})),
    ("allclose", lambda x, y, **kw: jnp.allclose(x, y, **kw),
     lambda x, y, **kw: np.allclose(x, y, **kw),
     lambda rng: ((np.ones(4, np.float32),
                   np.ones(4, np.float32) * (1 + 1e-7)), {})),
]:
    register(OpSpec(name=_name, fn=_jf, oracle=_nf, sample=_gen,
                    dtypes=("float32",), grad=False))

# --- bitwise (int32) --------------------------------------------------------
for _name, _jf, _nf, _nargs in [
    ("bitwise_and", jnp.bitwise_and, np.bitwise_and, 2),
    ("bitwise_or", jnp.bitwise_or, np.bitwise_or, 2),
    ("bitwise_xor", jnp.bitwise_xor, np.bitwise_xor, 2),
    ("bitwise_not", jnp.bitwise_not, np.bitwise_not, 1),
]:
    register(OpSpec(
        name=_name, fn=_jf, oracle=_nf,
        sample=(lambda k: lambda rng: (tuple(
            rng.randint(0, 63, 8).astype(np.int32) for _ in range(k)),
            {}))(_nargs),
        dtypes=("int32",), integer_inputs=(0, 1), grad=False))

# --- reductions -------------------------------------------------------------
def _red(shape=(4, 6), **attrs):
    def gen(rng):
        return (rng.randn(*shape).astype(np.float32),), dict(attrs)
    return gen


for _name, _jf, _nf, _gen, _grad in [
    ("sum", lambda x, axis=None, keepdim=False: jnp.sum(
        x, axis=axis, keepdims=keepdim),
     lambda x, axis=None, keepdim=False: np.sum(
         x, axis=axis, keepdims=keepdim), _red(axis=1), True),
    ("mean", lambda x, axis=None, keepdim=False: jnp.mean(
        x, axis=axis, keepdims=keepdim),
     lambda x, axis=None, keepdim=False: np.mean(
         x, axis=axis, keepdims=keepdim), _red(axis=1, keepdim=True), True),
    ("prod", lambda x, axis=None, keepdim=False: jnp.prod(
        x, axis=axis, keepdims=keepdim),
     lambda x, axis=None, keepdim=False: np.prod(
         x, axis=axis, keepdims=keepdim),
     lambda rng: ((rng.rand(4, 5).astype(np.float32) + 0.5,), {"axis": 1}),
     True),
    ("max", lambda x, axis=None, keepdim=False: jnp.max(
        x, axis=axis, keepdims=keepdim),
     lambda x, axis=None, keepdim=False: np.max(
         x, axis=axis, keepdims=keepdim), _red(axis=0), False),
    ("min", lambda x, axis=None, keepdim=False: jnp.min(
        x, axis=axis, keepdims=keepdim),
     lambda x, axis=None, keepdim=False: np.min(
         x, axis=axis, keepdims=keepdim), _red(axis=0), False),
    ("amax", lambda x, axis=None, keepdim=False: jnp.max(
        x, axis=axis, keepdims=keepdim),
     lambda x, axis=None, keepdim=False: np.max(
         x, axis=axis, keepdims=keepdim), _red(axis=1), False),
    ("amin", lambda x, axis=None, keepdim=False: jnp.min(
        x, axis=axis, keepdims=keepdim),
     lambda x, axis=None, keepdim=False: np.min(
         x, axis=axis, keepdims=keepdim), _red(axis=1), False),
    ("logsumexp", lambda x, axis=None, keepdim=False: jax.nn.logsumexp(
        x, axis=axis, keepdims=keepdim),
     lambda x, axis=None, keepdim=False: np.log(np.sum(
         np.exp(x), axis=axis, keepdims=keepdim)), _red(axis=1), True),
    ("count_nonzero", lambda x, axis=None, keepdim=False: jnp.count_nonzero(
        x, axis=axis, keepdims=keepdim),
     lambda x, axis=None, keepdim=False: np.count_nonzero(
         x, axis=axis, keepdims=keepdim),
     lambda rng: ((np.where(rng.rand(4, 5) < 0.3, 0.0,
                            rng.randn(4, 5)).astype(np.float32),),
                  {"axis": 1}), False),
    ("argmax", lambda x, axis=None, keepdim=False: jnp.argmax(x, axis=axis),
     lambda x, axis=None, keepdim=False: np.argmax(x, axis=axis),
     _red(axis=1), False),
    ("argmin", lambda x, axis=None, keepdim=False: jnp.argmin(x, axis=axis),
     lambda x, axis=None, keepdim=False: np.argmin(x, axis=axis),
     _red(axis=1), False),
    ("cumsum", lambda x, axis=None: jnp.cumsum(
        x, axis=axis if axis is not None else None),
     lambda x, axis=None: np.cumsum(x, axis=axis), _red(axis=1), True),
    ("median", lambda x, axis=None, keepdim=False: jnp.median(
        x, axis=axis, keepdims=keepdim),
     lambda x, axis=None, keepdim=False: np.median(
         x, axis=axis, keepdims=keepdim), _red(shape=(3, 7), axis=1), False),
    ("quantile", lambda x, q, axis=None, keepdim=False: jnp.quantile(
        x, q, axis=axis, keepdims=keepdim),
     lambda x, q, axis=None, keepdim=False: np.quantile(
         x, q, axis=axis, keepdims=keepdim),
     lambda rng: ((rng.randn(4, 9).astype(np.float32),),
                  {"q": 0.25, "axis": 1}), False),
]:
    register(OpSpec(name=_name, fn=_jf, oracle=_nf, sample=_gen, grad=_grad,
                    dtypes=("float32", "bfloat16")
                    if _name in ("sum", "mean", "max", "min", "amax", "amin")
                    else ("float32",),
                    tol={"bfloat16": 5e-2}))

register(OpSpec(
    name="cumprod",
    fn=lambda x, dim=None: jnp.cumprod(x, axis=dim),
    oracle=lambda x, dim=None: np.cumprod(x, axis=dim),
    sample=lambda rng: ((rng.rand(3, 6).astype(np.float32) + 0.5,),
                        {"dim": 1}),
    dtypes=("float32",),
))

for _name, _unb in [("std", True), ("var", True)]:
    register(OpSpec(
        name=_name,
        fn=(lambda f: lambda x, axis=None, unbiased=True, keepdim=False:
            f(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim))(
            jnp.std if _name == "std" else jnp.var),
        oracle=(lambda f: lambda x, axis=None, unbiased=True, keepdim=False:
                f(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim))(
            np.std if _name == "std" else np.var),
        sample=_red(axis=1),
        dtypes=("float32",),
    ))

register(OpSpec(
    name="norm",
    fn=lambda x, p="fro", axis=None, keepdim=False: jnp.linalg.norm(
        x, ord=p, axis=axis, keepdims=keepdim),
    oracle=lambda x, p="fro", axis=None, keepdim=False: np.linalg.norm(
        x, ord=p, axis=axis, keepdims=keepdim),
    sample=lambda rng: ((rng.randn(4, 6).astype(np.float32),),
                        {"p": 2, "axis": 1}),
    dtypes=("float32",),
))

register(OpSpec(
    name="kthvalue",
    fn=lambda x, k, axis=-1, keepdim=False: (
        jnp.take(jnp.sort(x, axis=axis), k - 1, axis=axis),
        jnp.take(jnp.argsort(x, axis=axis), k - 1, axis=axis)),
    oracle=lambda x, k, axis=-1, keepdim=False: (
        np.take(np.sort(x, axis=axis), k - 1, axis=axis),
        np.take(np.argsort(x, axis=axis), k - 1, axis=axis)),
    sample=lambda rng: ((rng.randn(3, 8).astype(np.float32),), {"k": 3}),
    dtypes=("float32",), grad=False, n_outputs=2,
))

# --- shape / movement -------------------------------------------------------
for _name, _jf, _nf, _gen in [
    ("reshape", lambda x, shape: jnp.reshape(x, shape),
     lambda x, shape: np.reshape(x, shape),
     lambda rng: ((rng.randn(3, 8).astype(np.float32),),
                  {"shape": (4, 6)})),
    ("transpose", lambda x, perm: jnp.transpose(x, perm),
     lambda x, perm: np.transpose(x, perm),
     lambda rng: ((rng.randn(2, 3, 4).astype(np.float32),),
                  {"perm": (2, 0, 1)})),
    ("squeeze", lambda x, axis=None: jnp.squeeze(x, axis=axis),
     lambda x, axis=None: np.squeeze(x, axis=axis),
     lambda rng: ((rng.randn(3, 1, 4).astype(np.float32),), {"axis": 1})),
    ("unsqueeze", lambda x, axis: jnp.expand_dims(x, axis),
     lambda x, axis: np.expand_dims(x, axis),
     lambda rng: ((rng.randn(3, 4).astype(np.float32),), {"axis": 1})),
    ("flatten", lambda x, start_axis=0, stop_axis=-1: x.reshape(
        x.shape[:start_axis]
        + (-1,) + x.shape[(stop_axis % x.ndim) + 1:]),
     lambda x, start_axis=0, stop_axis=-1: x.reshape(
         x.shape[:start_axis]
         + (-1,) + x.shape[(stop_axis % x.ndim) + 1:]),
     lambda rng: ((rng.randn(2, 3, 4).astype(np.float32),),
                  {"start_axis": 1, "stop_axis": 2})),
    ("flip", lambda x, axis: jnp.flip(x, axis=axis),
     lambda x, axis: np.flip(x, axis=axis),
     lambda rng: ((rng.randn(3, 4).astype(np.float32),), {"axis": 1})),
    ("roll", lambda x, shifts, axis=None: jnp.roll(x, shifts, axis=axis),
     lambda x, shifts, axis=None: np.roll(x, shifts, axis=axis),
     lambda rng: ((rng.randn(3, 5).astype(np.float32),),
                  {"shifts": 2, "axis": 1})),
    ("tile", lambda x, repeat_times: jnp.tile(x, repeat_times),
     lambda x, repeat_times: np.tile(x, repeat_times),
     lambda rng: ((rng.randn(2, 3).astype(np.float32),),
                  {"repeat_times": (2, 2)})),
    ("broadcast_to", lambda x, shape: jnp.broadcast_to(x, shape),
     lambda x, shape: np.broadcast_to(x, shape),
     lambda rng: ((rng.randn(1, 4).astype(np.float32),),
                  {"shape": (3, 4)})),
    ("expand", lambda x, shape: jnp.broadcast_to(x, shape),
     lambda x, shape: np.broadcast_to(x, shape),
     lambda rng: ((rng.randn(1, 5).astype(np.float32),),
                  {"shape": (4, 5)})),
    ("moveaxis", lambda x, source, destination: jnp.moveaxis(
        x, source, destination),
     lambda x, source, destination: np.moveaxis(x, source, destination),
     lambda rng: ((rng.randn(2, 3, 4).astype(np.float32),),
                  {"source": 0, "destination": 2})),
    ("t", lambda x: x.T, lambda x: x.T,
     lambda rng: ((rng.randn(3, 5).astype(np.float32),), {})),
    ("tril", lambda x, diagonal=0: jnp.tril(x, k=diagonal),
     lambda x, diagonal=0: np.tril(x, k=diagonal),
     lambda rng: ((rng.randn(4, 5).astype(np.float32),),
                  {"diagonal": 1})),
    ("triu", lambda x, diagonal=0: jnp.triu(x, k=diagonal),
     lambda x, diagonal=0: np.triu(x, k=diagonal),
     lambda rng: ((rng.randn(4, 5).astype(np.float32),),
                  {"diagonal": -1})),
    ("diag", lambda x, offset=0, padding_value=0: jnp.diag(x, k=offset),
     lambda x, offset=0, padding_value=0: np.diag(x, k=offset),
     lambda rng: ((rng.randn(5).astype(np.float32),), {"offset": 1})),
    ("diagonal", lambda x, offset=0, axis1=0, axis2=1: jnp.diagonal(
        x, offset=offset, axis1=axis1, axis2=axis2),
     lambda x, offset=0, axis1=0, axis2=1: np.diagonal(
         x, offset=offset, axis1=axis1, axis2=axis2),
     lambda rng: ((rng.randn(4, 5).astype(np.float32),), {"offset": 1})),
    ("diag_embed", lambda x, offset=0, dim1=-2, dim2=-1: _jax_diag_embed(
        x, offset),
     lambda x, offset=0, dim1=-2, dim2=-1: _np_diag_embed(x, offset),
     lambda rng: ((rng.randn(3, 4).astype(np.float32),), {})),
]:
    register(OpSpec(name=_name, fn=_jf, oracle=_nf, sample=_gen,
                    tol={"bfloat16": 4e-2}))


def _jax_diag_embed(x, offset=0):
    n = x.shape[-1] + abs(offset)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    return out.at[..., r, c].set(x)


def _np_diag_embed(x, offset=0):
    n = x.shape[-1] + abs(offset)
    out = np.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = np.arange(x.shape[-1])
    out[..., idx + max(-offset, 0), idx + max(offset, 0)] = x
    return out


# --- matmul family ----------------------------------------------------------
for _name, _jf, _nf, _gen in [
    ("dot", jnp.dot, np.dot,
     lambda rng: ((rng.randn(6).astype(np.float32),
                   rng.randn(6).astype(np.float32)), {})),
    ("outer", jnp.outer, np.outer,
     lambda rng: ((rng.randn(4).astype(np.float32),
                   rng.randn(5).astype(np.float32)), {})),
    ("kron", jnp.kron, np.kron,
     lambda rng: ((rng.randn(2, 3).astype(np.float32),
                   rng.randn(3, 2).astype(np.float32)), {})),
    ("bmm", jnp.matmul, np.matmul,
     lambda rng: ((rng.randn(2, 3, 4).astype(np.float32),
                   rng.randn(2, 4, 5).astype(np.float32)), {})),
    ("mm", jnp.matmul, np.matmul,
     lambda rng: ((rng.randn(3, 4).astype(np.float32),
                   rng.randn(4, 5).astype(np.float32)), {})),
    ("matmul", jnp.matmul, np.matmul,
     lambda rng: ((rng.randn(3, 4).astype(np.float32),
                   rng.randn(4, 5).astype(np.float32)), {})),
]:
    register(OpSpec(name=_name, fn=_jf, oracle=_nf, sample=_gen,
                    tol={"bfloat16": 5e-2}))

register(OpSpec(
    name="einsum",
    fn=lambda equation, *ops: jnp.einsum(equation, *ops),
    oracle=lambda equation, *ops: np.einsum(equation, *ops),
    sample=lambda rng: (("ij,jk->ik", rng.randn(3, 4).astype(np.float32),
                         rng.randn(4, 5).astype(np.float32)), {}),
    integer_inputs=(0,), grad=False,
    tol={"bfloat16": 5e-2},
))

# --- indexing / selection ---------------------------------------------------
register(OpSpec(
    name="gather",
    fn=lambda x, index, axis=0: jnp.take(x, index, axis=axis),
    oracle=lambda x, index, axis=0: np.take(x, index, axis=axis),
    sample=lambda rng: ((rng.randn(6, 4).astype(np.float32),
                         rng.randint(0, 6, 5).astype(np.int32)), {}),
    integer_inputs=(1,),
))

register(OpSpec(
    name="index_select",
    fn=lambda x, index, axis=0: jnp.take(x, index, axis=axis),
    oracle=lambda x, index, axis=0: np.take(x, index, axis=axis),
    sample=lambda rng: ((rng.randn(6, 4).astype(np.float32),
                         rng.randint(0, 6, 3).astype(np.int32)),
                        {"axis": 1 - 1}),
    integer_inputs=(1,),
))

register(OpSpec(
    name="gather_nd",
    fn=lambda x, index: x[tuple(index[..., i]
                               for i in range(index.shape[-1]))],
    oracle=lambda x, index: np.stack(
        [x[tuple(ix)] for ix in index.reshape(-1, index.shape[-1])]
    ).reshape(index.shape[:-1] + x.shape[index.shape[-1]:]),
    sample=lambda rng: ((rng.randn(5, 4).astype(np.float32),
                         rng.randint(0, 4, (6, 2)).astype(np.int32)), {}),
    integer_inputs=(1,),
))

register(OpSpec(
    name="take_along_axis",
    fn=lambda arr, indices, axis: jnp.take_along_axis(arr, indices, axis),
    oracle=lambda arr, indices, axis: np.take_along_axis(
        arr, indices, axis),
    sample=lambda rng: ((rng.randn(4, 6).astype(np.float32),
                         rng.randint(0, 6, (4, 3)).astype(np.int32)),
                        {"axis": 1}),
    integer_inputs=(1,),
))

def _jax_put_along_axis(arr, indices, values, axis, reduce="assign"):
    if reduce != "assign":
        raise NotImplementedError(
            f"put_along_axis: reduce={reduce!r} not supported")
    return jnp.put_along_axis(arr, indices, values, axis, inplace=False)


register(OpSpec(
    name="put_along_axis",
    fn=_jax_put_along_axis,
    oracle=lambda arr, indices, values, axis, reduce="assign":
        _np_put_along_axis(arr, indices, values, axis),
    sample=lambda rng: ((rng.randn(4, 5).astype(np.float32),
                         np.stack([rng.permutation(5)[:2]
                                   for _ in range(4)]).astype(np.int32),
                         rng.randn(4, 2).astype(np.float32)),
                        {"axis": 1}),
    integer_inputs=(1,), grad_arg=0,
))


def _np_put_along_axis(arr, indices, values, axis):
    out = np.asarray(arr).copy()
    np.put_along_axis(out, np.asarray(indices), np.asarray(values), axis)
    return out


register(OpSpec(
    name="index_sample",
    fn=lambda x, index: jnp.take_along_axis(x, index, axis=1),
    oracle=lambda x, index: np.take_along_axis(x, index, axis=1),
    sample=lambda rng: ((rng.randn(4, 6).astype(np.float32),
                         rng.randint(0, 6, (4, 3)).astype(np.int32)), {}),
    integer_inputs=(1,),
))

register(OpSpec(
    name="scatter",
    fn=lambda x, index, updates, overwrite=True:
        x.at[index].set(updates) if overwrite else x.at[index].add(updates),
    oracle=lambda x, index, updates, overwrite=True:
        _np_scatter(x, index, updates, overwrite),
    sample=lambda rng: ((rng.randn(6, 4).astype(np.float32),
                         rng.permutation(6)[:3].astype(np.int32),
                         rng.randn(3, 4).astype(np.float32)), {}),
    integer_inputs=(1,), grad_arg=0,
))


def _np_scatter(x, index, updates, overwrite):
    out = np.asarray(x, np.float64).copy()
    for i, ix in enumerate(index):
        if overwrite:
            out[ix] = updates[i]
        else:
            out[ix] += updates[i]
    return out


register(OpSpec(
    name="scatter_nd_add",
    fn=lambda x, index, updates: x.at[
        tuple(index[..., i] for i in range(index.shape[-1]))].add(updates),
    oracle=lambda x, index, updates: _np_scatter_nd_add(x, index, updates),
    sample=lambda rng: ((rng.randn(5, 4).astype(np.float32),
                         rng.randint(0, 5, (6, 1)).astype(np.int32),
                         rng.randn(6, 4).astype(np.float32)), {}),
    integer_inputs=(1,), grad_arg=0,
))


def _np_scatter_nd_add(x, index, updates):
    out = np.asarray(x, np.float64).copy()
    for i in range(index.shape[0]):
        out[tuple(index[i])] += updates[i]
    return out


register(OpSpec(
    name="masked_fill",
    fn=lambda x, mask, value: jnp.where(mask.astype(bool), value, x),
    oracle=lambda x, mask, value: np.where(np.asarray(mask, bool), value, x),
    sample=lambda rng: ((rng.randn(4, 5).astype(np.float32),
                         (rng.rand(4, 5) > 0.5)), {"value": 9.0}),
    integer_inputs=(1,), grad_arg=0,
))

register(OpSpec(
    name="masked_select",
    fn=lambda x, mask: x[mask.astype(bool)],
    oracle=lambda x, mask: np.asarray(x)[np.asarray(mask, bool)],
    sample=lambda rng: ((rng.randn(4, 5).astype(np.float32),
                         (rng.rand(4, 5) > 0.5)), {}),
    integer_inputs=(1,), grad=False,
))

register(OpSpec(
    name="where",
    fn=lambda condition, x=None, y=None: jnp.where(
        condition.astype(bool), x, y),
    oracle=lambda condition, x=None, y=None: np.where(
        np.asarray(condition, bool), x, y),
    sample=lambda rng: (((rng.rand(6) > 0.5),
                         rng.randn(6).astype(np.float32),
                         rng.randn(6).astype(np.float32)), {}),
    integer_inputs=(0,), grad_arg=1,
))

register(OpSpec(
    name="one_hot",
    fn=lambda x, num_classes: jax.nn.one_hot(x, num_classes),
    oracle=lambda x, num_classes: np.eye(num_classes, dtype=np.float32)[x],
    sample=lambda rng: ((rng.randint(0, 5, 7).astype(np.int32),),
                        {"num_classes": 5}),
    dtypes=("float32",), integer_inputs=(0,), grad=False,
))

def _jax_topk(x, k, axis=-1, largest=True, sorted=True):
    xm = jnp.moveaxis(x, axis, -1)
    v, i = jax.lax.top_k(xm if largest else -xm, k)
    if not largest:
        v = -v
    return jnp.moveaxis(v, -1, axis), jnp.moveaxis(i, -1, axis)


register(OpSpec(
    name="topk",
    fn=_jax_topk,
    oracle=lambda x, k, axis=-1, largest=True, sorted=True: (
        np.sort(x, axis=axis)[..., ::-1][..., :k] if largest
        else np.sort(x, axis=axis)[..., :k],
        np.argsort(-x if largest else x, kind="stable",
                   axis=axis)[..., :k]),
    sample=lambda rng: ((rng.randn(3, 9).astype(np.float32),), {"k": 4}),
    dtypes=("float32",), grad=False, n_outputs=2,
))

register(OpSpec(
    name="sort",
    fn=lambda x, axis=-1, descending=False: (
        jnp.flip(jnp.sort(x, axis=axis), axis=axis) if descending
        else jnp.sort(x, axis=axis)),
    oracle=lambda x, axis=-1, descending=False: (
        np.flip(np.sort(x, axis=axis), axis=axis) if descending
        else np.sort(x, axis=axis)),
    sample=lambda rng: ((rng.randn(3, 7).astype(np.float32),),
                        {"descending": True}),
    grad=False,
))

register(OpSpec(
    name="argsort",
    fn=lambda x, axis=-1, descending=False: jnp.argsort(
        -x if descending else x, axis=axis),
    oracle=lambda x, axis=-1, descending=False: np.argsort(
        -x if descending else x, kind="stable", axis=axis),
    sample=lambda rng: ((rng.randn(3, 7).astype(np.float32),), {}),
    dtypes=("float32",), grad=False,
))

register(OpSpec(
    name="searchsorted",
    fn=lambda sorted_sequence, values, out_int32=False, right=False:
        jnp.searchsorted(sorted_sequence, values,
                         side="right" if right else "left"),
    oracle=lambda sorted_sequence, values, out_int32=False, right=False:
        np.searchsorted(sorted_sequence, values,
                        side="right" if right else "left"),
    sample=lambda rng: ((np.sort(rng.randn(8)).astype(np.float32),
                         rng.randn(5).astype(np.float32)), {}),
    dtypes=("float32",), grad=False,
))

register(OpSpec(
    name="bincount",
    fn=lambda x, weights=None, minlength=0: jnp.bincount(
        x, weights=weights, minlength=minlength),
    oracle=lambda x, weights=None, minlength=0: np.bincount(
        x, weights=weights, minlength=minlength),
    sample=lambda rng: ((rng.randint(0, 6, 12).astype(np.int32),),
                        {"minlength": 8}),
    dtypes=("int32",), integer_inputs=(0,), grad=False,
))

register(OpSpec(
    name="repeat_interleave",
    fn=lambda x, repeats, axis=None: jnp.repeat(x, repeats, axis=axis),
    oracle=lambda x, repeats, axis=None: np.repeat(x, repeats, axis=axis),
    sample=lambda rng: ((rng.randn(3, 4).astype(np.float32),),
                        {"repeats": 2, "axis": 1}),
))

register(OpSpec(
    name="shard_index",
    fn=lambda input, index_num, nshards, shard_id, ignore_value=-1:
        jnp.where(input // ((index_num + nshards - 1) // nshards) == shard_id,
                  input % ((index_num + nshards - 1) // nshards),
                  ignore_value),
    oracle=lambda input, index_num, nshards, shard_id, ignore_value=-1:
        _np_shard_index(input, index_num, nshards, shard_id, ignore_value),
    sample=lambda rng: ((rng.randint(0, 12, (6, 1)).astype(np.int32),),
                        {"index_num": 12, "nshards": 3, "shard_id": 1}),
    dtypes=("int32",), integer_inputs=(0,), grad=False,
))


def _np_shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = (index_num + nshards - 1) // nshards
    inp = np.asarray(input)
    return np.where(inp // size == shard_id, inp % size, ignore_value)


# --- integration family (round-3 long tail) ---------------------------------
def _jax_trapezoid(y, x=None, dx=1.0, axis=-1):
    if x is not None:
        d = jnp.diff(x, axis=axis if x.ndim > 1 else -1)
        if x.ndim == 1 and y.ndim > 1:
            shape = [1] * y.ndim
            shape[axis % y.ndim] = d.shape[0]
            d = d.reshape(shape)
    else:
        d = dx
    n = y.shape[axis % y.ndim]
    lo = jax.lax.slice_in_dim(y, 0, n - 1, axis=axis % y.ndim)
    hi = jax.lax.slice_in_dim(y, 1, n, axis=axis % y.ndim)
    return jnp.sum((lo + hi) * 0.5 * d, axis=axis % y.ndim)


def _np_trapezoid(y, x=None, dx=1.0, axis=-1):
    return np.trapezoid(y, x=x, dx=dx, axis=axis)


register(OpSpec(
    name="trapezoid",
    fn=_jax_trapezoid,
    oracle=_np_trapezoid,
    sample=lambda rng: ((rng.randn(4, 9).astype(np.float32),),
                        {"dx": 0.5, "axis": 1}),
))


def _cumtrap(y, x=None, dx=1.0, axis=-1, mod=None):
    m = mod
    ax = axis % y.ndim
    n = y.shape[ax]
    lo = y.take(indices=range(0, n - 1), axis=ax) if m is np else \
        jax.lax.slice_in_dim(y, 0, n - 1, axis=ax)
    hi = y.take(indices=range(1, n), axis=ax) if m is np else \
        jax.lax.slice_in_dim(y, 1, n, axis=ax)
    if x is not None:
        d = m.diff(x, axis=ax if getattr(x, "ndim", 1) > 1 else -1)
        if getattr(x, "ndim", 1) == 1 and y.ndim > 1:
            shape = [1] * y.ndim
            shape[ax] = d.shape[0]
            d = d.reshape(shape)
    else:
        d = dx
    return m.cumsum((lo + hi) * 0.5 * d, axis=ax)


register(OpSpec(
    name="cumulative_trapezoid",
    fn=lambda y, x=None, dx=1.0, axis=-1: _cumtrap(y, x, dx, axis, jnp),
    oracle=lambda y, x=None, dx=1.0, axis=-1: _cumtrap(y, x, dx, axis, np),
    sample=lambda rng: ((rng.randn(3, 8).astype(np.float32),),
                        {"dx": 0.25, "axis": 1}),
))


# --- round-4 API audit: remaining elementwise long tail ----------------------
register(OpSpec(
    name="i1e",
    fn=lambda x: jax.scipy.special.i1e(x),
    oracle=lambda x: _np_i1(x) * np.exp(-np.abs(np.asarray(x, np.float64))),
    sample=lambda rng: ((rng.randn(8).astype(np.float32),), {}),
    dtypes=("float32",),
    grad=False,
))


def _np_multigammaln(x, p):
    from math import lgamma, log, pi
    flat = np.asarray(x, np.float64).reshape(-1)
    out = []
    for v in flat:
        s = 0.25 * p * (p - 1) * log(pi)
        s += sum(lgamma(v - 0.5 * j) for j in range(p))
        out.append(s)
    return np.asarray(out).reshape(np.shape(x))


register(OpSpec(
    name="multigammaln",
    fn=lambda x, p=2: (0.25 * p * (p - 1) * jnp.log(jnp.pi)
                       + sum(jax.scipy.special.gammaln(x - 0.5 * j)
                             for j in range(p))),
    oracle=lambda x, p=2: _np_multigammaln(x, p),
    sample=lambda rng: ((rng.rand(6).astype(np.float32) * 3 + 2.0,),
                        {"p": 2}),
    dtypes=("float32",),
    grad=False,
))

register(OpSpec(
    name="isneginf",
    fn=lambda x: jnp.isneginf(x),
    oracle=lambda x: np.isneginf(x),
    sample=lambda rng: ((np.asarray([1.0, -np.inf, np.inf, np.nan],
                                    np.float32),), {}),
    dtypes=("float32",),
    grad=False,
))

register(OpSpec(
    name="isposinf",
    fn=lambda x: jnp.isposinf(x),
    oracle=lambda x: np.isposinf(x),
    sample=lambda rng: ((np.asarray([1.0, -np.inf, np.inf, np.nan],
                                    np.float32),), {}),
    dtypes=("float32",),
    grad=False,
))

register(OpSpec(
    name="isreal",
    fn=lambda x: jnp.isreal(x),
    oracle=lambda x: np.isreal(x),
    sample=_complex_sample,
    dtypes=("complex64",),
    integer_inputs=(0,),
    grad=False,
))

register(OpSpec(
    name="positive",
    fn=lambda x: jnp.positive(x),
    oracle=lambda x: np.positive(x),
    sample=lambda rng: ((rng.randn(6).astype(np.float32),), {}),
    dtypes=("float32", "float64", "int32"),
))

register(OpSpec(
    name="negative",
    fn=lambda x: jnp.negative(x),
    oracle=lambda x: np.negative(x),
    sample=lambda rng: ((rng.randn(6).astype(np.float32),), {}),
    dtypes=("float32", "float64", "int32"),
))

register(OpSpec(
    name="float_power",
    fn=lambda x, y: jnp.float_power(x, y),
    oracle=lambda x, y: np.float_power(x, y),
    sample=lambda rng: ((np.abs(rng.randn(6)).astype(np.float32) + 0.1,
                         rng.randn(6).astype(np.float32)), {}),
    dtypes=("float32",),
    grad=False,
))
