"""The framework Tensor: a thin imperative wrapper over ``jax.Array``.

Rebuild of the reference's DenseTensor + eager Tensor surface
(paddle/phi/core/dense_tensor.cc, paddle/fluid/pybind/eager_method.cc —
SURVEY.md §2.1). Storage IS a jax.Array (or a tracer under jit); autograd
metadata (``stop_gradient``, ``.grad``, grad-node edge) lives on this wrapper,
mirroring AutogradMeta.

Paddle semantics preserved:
 - fresh tensors default ``stop_gradient=True``; Parameters default False.
 - ``.backward()`` accumulates into ``.grad`` on leaves.
 - ``.shape`` is a python list; ``.numpy()`` materialises to host.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from .place import Place, current_place
from .dtype import convert_dtype

# Cap on how many rows a `for` over a TRACED tensor may statically unroll
# (each row duplicates the consuming code in the jaxpr). dy2static reuses
# this constant; its eager fallback catches TracedIterationError.
TRACED_ITER_UNROLL_LIMIT = 256


class TracedIterationError(RuntimeError):
    """Iterating a traced tensor in a way that cannot (or should not)
    lower to a compiled program; the message says what to change."""

_tensor_count = [0]


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "_grad_node",
        "_out_index",
        "_grad_value",
        "name",
        "persistable",
        "_sharding_spec",
        "is_distributed",
        "_grad_hooks",
        "__weakref__",
    )

    def __init__(
        self,
        value,
        stop_gradient: bool = True,
        name: Optional[str] = None,
        _grad_node=None,
        _out_index: int = 0,
    ):
        if isinstance(value, Tensor):
            value = value._value
        elif not isinstance(value, (jax.Array, jax.core.Tracer)):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad_node = _grad_node
        if _grad_node is not None and getattr(_grad_node, "out_refs", None) \
                is not None:
            import weakref
            _grad_node.out_refs[_out_index] = weakref.ref(self)
        self._out_index = _out_index
        self._grad_value = None
        if name is None:
            _tensor_count[0] += 1
            name = f"generated_tensor_{_tensor_count[0]}"
        self.name = name
        self.persistable = False
        self._sharding_spec = None  # PartitionSpec hint for pjit paths
        self.is_distributed = False

    # -- basic meta ---------------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self) -> Place:
        return current_place()

    def numel(self) -> int:
        return self.size

    # -- host bridge --------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def item(self):
        return self._value.item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self._value)

    def __int__(self):
        return int(self._value)

    def __bool__(self):
        return bool(self._value)

    def __len__(self):
        if not self._value.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    # -- autograd -----------------------------------------------------------
    @property
    def grad(self) -> Optional["Tensor"]:
        if self._grad_value is None:
            return None
        return Tensor(self._grad_value, stop_gradient=True, name=self.name + "@GRAD")

    @grad.setter
    def grad(self, g):
        if g is None:
            self._grad_value = None
        else:
            self._grad_value = g._value if isinstance(g, Tensor) else jnp.asarray(g)

    def backward(self, grad_tensor=None, retain_graph: bool = False):
        autograd.backward(self, grad_tensor, retain_graph=retain_graph)

    def clear_grad(self):
        self._grad_value = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        return Tensor(self._value, stop_gradient=True, name=self.name + "@detached")

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from .dispatch import apply
        return apply(lambda x: x + 0, self, op_name="clone")

    # -- dtype/shape sugar (full op surface installed by tensor_methods) ----
    def astype(self, dtype) -> "Tensor":
        from .dispatch import apply
        dtype = convert_dtype(dtype)
        return apply(lambda x: x.astype(dtype), self, op_name="cast")

    cast = astype

    def _replace(self, new: "Tensor") -> "Tensor":
        """In-place rebind used by setitem/inplace ops: keep identity, new value."""
        self._value = new._value
        self._grad_node = new._grad_node
        self._out_index = new._out_index
        self.stop_gradient = new.stop_gradient
        return self

    def __getitem__(self, idx) -> "Tensor":
        from .dispatch import apply
        idx = _unwrap_index(idx)
        return apply(lambda x: x[idx], self, op_name="getitem")

    def __setitem__(self, idx, value):
        from .dispatch import apply
        idx = _unwrap_index(idx)
        if isinstance(value, Tensor):
            out = apply(
                lambda x, v: x.at[idx].set(v.astype(x.dtype)), self, value,
                op_name="setitem",
            )
        else:
            out = apply(lambda x: x.at[idx].set(value), self, op_name="setitem")
        self._replace(out)

    def __repr__(self):
        try:
            data = np.asarray(self._value)
            body = np.array2string(data, precision=6, separator=", ")
        except Exception:
            body = repr(self._value)  # tracer
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype}, "
            f"stop_gradient={self.stop_gradient},\n       {body})"
        )

    def __hash__(self):
        return id(self)

    # -- grad hooks ---------------------------------------------------------
    def register_hook(self, hook):
        """Call ``hook(grad)`` when this tensor's gradient is computed
        during backward; a non-None return replaces the gradient
        (reference Tensor.register_hook). Returns a removable handle."""
        if self.stop_gradient:
            raise RuntimeError(
                "cannot register a grad hook on a tensor with "
                "stop_gradient=True")
        hooks = getattr(self, "_grad_hooks", None)
        if hooks is None:
            hooks = {"n": 0, "fns": {}}
            self._grad_hooks = hooks
        hid = hooks["n"]          # monotonic: a stale handle's second
        hooks["n"] += 1           # remove() must never hit a newer hook
        hooks["fns"][hid] = hook

        class _Handle:
            def remove(_self):
                hooks["fns"].pop(hid, None)

        return _Handle()

    # -- iteration ----------------------------------------------------------
    def __iter__(self):
        # Under a jax trace, iteration unrolls shape[0] copies of whatever
        # consumes the rows into the jaxpr. Guard here — not only in
        # dy2static's check_iterable — so wrapped iteration (enumerate/zip/
        # reversed over a tensor) hits the same actionable error instead of
        # silently emitting a giant program (round-5 review finding).
        if isinstance(self._value, jax.core.Tracer):
            if not self._value.shape:
                raise TracedIterationError(
                    "iterating a 0-d traced tensor; loops need a leading "
                    "axis (or use a tensor op)")
            n = self._value.shape[0]
            if n > TRACED_ITER_UNROLL_LIMIT:
                raise TracedIterationError(
                    f"iterating a traced tensor with leading axis {n} would "
                    f"unroll {n} copies of the consuming code (limit "
                    f"{TRACED_ITER_UNROLL_LIMIT}); loop over `range(n)` and "
                    "index, or use a tensor op (scan/vmap)")
        for i in range(len(self)):
            yield self[i]


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(idx)
    return idx


class Parameter(Tensor):
    """Trainable tensor; ``stop_gradient=False`` by default (reference:
    python/paddle — framework Parameter; SURVEY.md §2.1 AutogradMeta)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip",
                 "is_distributed_param", "expert", "is_sequence_parallel",
                 "main_grad")

    def __init__(self, value, trainable: bool = True, name: Optional[str] = None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed_param = False
        self.expert = False  # expert-parallel param (MoE): excluded from dp sync
        self.is_sequence_parallel = False  # SP-marked (grad allreduced over mp)
        self.main_grad = None  # fp32 accumulation buffer (mix_precision_utils)

    def set_value(self, value):
        v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
        self._value = v.astype(self._value.dtype) if hasattr(v, "astype") else v
