"""Public op surface (the ``paddle.*`` tensor-math namespace).

Kernel-library equivalent of the reference's Phi op corpus
(paddle/phi/kernels/{cpu,gpu}/, python/paddle/tensor/{math,linalg,manipulation,
logic,search,stat}.py — SURVEY.md §2.1). Every op funnels through
``dispatch.apply`` so it is tape-recorded, jit-traceable, and XLA-lowered.

Paddle calling conventions are preserved (``axis`` kwargs, ``keepdim``,
``transpose_x/transpose_y`` on matmul, list-of-sections ``split`` …).
"""

from __future__ import annotations

import builtins
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .dispatch import apply, unwrap
from .dtype import canonical_dtype, convert_dtype
from .tensor import Tensor


def _t(x) -> Tensor:
    return x if isinstance(x, Tensor) else Tensor(x)


# ---------------------------------------------------------------------------
# elementwise binary
# ---------------------------------------------------------------------------
def _binary(name, fn):
    def op(x, y, name=None):
        return apply(fn, _t(x) if not _scalar(x) else x,
                     _t(y) if not _scalar(y) else y, op_name=name_)
    name_ = name
    op.__name__ = name
    return op


def _scalar(x):
    return isinstance(x, (int, float, bool, complex))


add = _binary("add", lambda x, y: jnp.add(x, y))
subtract = _binary("subtract", lambda x, y: jnp.subtract(x, y))
multiply = _binary("multiply", lambda x, y: jnp.multiply(x, y))
divide = _binary("divide", lambda x, y: jnp.true_divide(x, y))
floor_divide = _binary("floor_divide", lambda x, y: jnp.floor_divide(x, y))
remainder = _binary("remainder", lambda x, y: jnp.remainder(x, y))
mod = remainder
floor_mod = remainder
pow = _binary("pow", lambda x, y: jnp.power(x, y))
maximum = _binary("maximum", lambda x, y: jnp.maximum(x, y))
minimum = _binary("minimum", lambda x, y: jnp.minimum(x, y))
fmax = maximum
fmin = minimum
atan2 = _binary("atan2", lambda x, y: jnp.arctan2(x, y))
hypot = _binary("hypot", lambda x, y: jnp.hypot(x, y))

logical_and = _binary("logical_and", lambda x, y: jnp.logical_and(x, y))
logical_or = _binary("logical_or", lambda x, y: jnp.logical_or(x, y))
logical_xor = _binary("logical_xor", lambda x, y: jnp.logical_xor(x, y))
bitwise_and = _binary("bitwise_and", lambda x, y: jnp.bitwise_and(x, y))
bitwise_or = _binary("bitwise_or", lambda x, y: jnp.bitwise_or(x, y))
bitwise_xor = _binary("bitwise_xor", lambda x, y: jnp.bitwise_xor(x, y))

equal = _binary("equal", lambda x, y: jnp.equal(x, y))
not_equal = _binary("not_equal", lambda x, y: jnp.not_equal(x, y))
greater_than = _binary("greater_than", lambda x, y: jnp.greater(x, y))
greater_equal = _binary("greater_equal", lambda x, y: jnp.greater_equal(x, y))
less_than = _binary("less_than", lambda x, y: jnp.less(x, y))
less_equal = _binary("less_equal", lambda x, y: jnp.less_equal(x, y))


# ---------------------------------------------------------------------------
# elementwise unary
# ---------------------------------------------------------------------------
def _unary(name, fn):
    def op(x, name=None):
        return apply(fn, _t(x), op_name=name_)
    name_ = name
    op.__name__ = name
    return op


exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", lambda x: jax.lax.rsqrt(x))
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
square = _unary("square", jnp.square)
sign = _unary("sign", jnp.sign)
reciprocal = _unary("reciprocal", jnp.reciprocal)
logical_not = _unary("logical_not", jnp.logical_not)
bitwise_not = _unary("bitwise_not", jnp.bitwise_not)
isnan = _unary("isnan", jnp.isnan)
isinf = _unary("isinf", jnp.isinf)
isfinite = _unary("isfinite", jnp.isfinite)
digamma = _unary("digamma", jax.scipy.special.digamma)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    if bias_after_scale:
        out = apply(lambda v: v * scale + bias, _t(x), op_name="scale")
    else:
        out = apply(lambda v: (v + bias) * scale, _t(x), op_name="scale")
    return out


def clip(x, min=None, max=None, name=None):
    lo = unwrap(min) if isinstance(min, Tensor) else min
    hi = unwrap(max) if isinstance(max, Tensor) else max
    return apply(lambda v: jnp.clip(v, lo, hi), _t(x), op_name="clip")


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply(lambda a, b, w: a + w * (b - a), _t(x), _t(y), weight, op_name="lerp")
    return apply(lambda a, b: a + weight * (b - a), _t(x), _t(y), op_name="lerp")


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------
def _norm_axis(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis if axis is None else int(axis)


def _reduction(name, fn, has_dtype=False):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        ax = _norm_axis(axis)
        if has_dtype and dtype is not None:
            d = convert_dtype(dtype)
            return apply(lambda v: fn(v.astype(d), axis=ax, keepdims=keepdim),
                         _t(x), op_name=name_)
        return apply(lambda v: fn(v, axis=ax, keepdims=keepdim), _t(x), op_name=name_)
    name_ = name
    op.__name__ = name
    return op


sum = _reduction("sum", jnp.sum, has_dtype=True)
mean = _reduction("mean", jnp.mean, has_dtype=True)
prod = _reduction("prod", jnp.prod, has_dtype=True)
max = _reduction("max", jnp.max)
min = _reduction("min", jnp.min)
amax = max
amin = min
all = _reduction("all", jnp.all)
any = _reduction("any", jnp.any)
logsumexp = _reduction("logsumexp", jax.scipy.special.logsumexp)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply(lambda v: jnp.std(v, axis=ax, ddof=ddof, keepdims=keepdim),
                 _t(x), op_name="std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply(lambda v: jnp.var(v, axis=ax, ddof=ddof, keepdims=keepdim),
                 _t(x), op_name="var")


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = canonical_dtype(dtype)
    return apply(lambda v: jnp.argmax(v, axis=axis, keepdims=keepdim).astype(d),
                 _t(x), op_name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = canonical_dtype(dtype)
    return apply(lambda v: jnp.argmin(v, axis=axis, keepdims=keepdim).astype(d),
                 _t(x), op_name="argmin")


def cumsum(x, axis=None, dtype=None, name=None):
    def fn(v):
        if axis is None:
            v = v.reshape(-1)
            return jnp.cumsum(v, dtype=convert_dtype(dtype))
        return jnp.cumsum(v, axis=axis, dtype=convert_dtype(dtype))
    return apply(fn, _t(x), op_name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    return apply(lambda v: jnp.cumprod(v, axis=dim, dtype=convert_dtype(dtype)),
                 _t(x), op_name="cumprod")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply(lambda v: jnp.count_nonzero(v, axis=ax, keepdims=keepdim),
                 _t(x), op_name="count_nonzero")


def median(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.median(v, axis=axis, keepdims=keepdim),
                 _t(x), op_name="median")


def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.quantile(v, q, axis=axis, keepdims=keepdim),
                 _t(x), op_name="quantile")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(v):
        sorted_v = jnp.sort(v, axis=axis)
        idx = jnp.argsort(v, axis=axis)
        vals = jnp.take(sorted_v, k - 1, axis=axis)
        inds = jnp.take(idx, k - 1, axis=axis)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            inds = jnp.expand_dims(inds, axis)
        return vals, inds.astype(canonical_dtype("int64"))
    return apply(fn, _t(x), op_name="kthvalue")


# ---------------------------------------------------------------------------
# matmul / linalg
# ---------------------------------------------------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply(fn, _t(x), _t(y), op_name="matmul")


mm = matmul


def bmm(x, y, name=None):
    return apply(jnp.matmul, _t(x), _t(y), op_name="bmm")


def dot(x, y, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), _t(x), _t(y), op_name="dot")


def outer(x, y, name=None):
    return apply(jnp.outer, _t(x), _t(y), op_name="outer")


def t(x, name=None):
    return apply(lambda v: v.T, _t(x), op_name="t")


def einsum(equation, *operands):
    tensors = [_t(o) for o in operands]
    return apply(lambda *vs: jnp.einsum(equation, *vs), *tensors, op_name="einsum")


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)

    def fn(v):
        if p in ("fro", 2, 2.0):
            return jnp.sqrt(jnp.sum(jnp.square(v), axis=ax, keepdims=keepdim))
        if p in (np.inf, "inf", float("inf")):
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == 1:
            return jnp.sum(jnp.abs(v), axis=ax, keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=ax, keepdims=keepdim), 1.0 / p)

    return apply(fn, _t(x), op_name="norm")


def matmul_nt(x, y):
    """matmul(x, y.T) convenience used by parallel layers."""
    return matmul(x, y, transpose_y=True)


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------
def reshape(x, shape, name=None):
    shape = [int(s) for s in (shape.tolist() if isinstance(shape, (Tensor, np.ndarray)) else shape)]
    return apply(lambda v: jnp.reshape(v, shape), _t(x), op_name="reshape")


def transpose(x, perm, name=None):
    perm = [int(p) for p in perm]
    return apply(lambda v: jnp.transpose(v, perm), _t(x), op_name="transpose")


def squeeze(x, axis=None, name=None):
    ax = _norm_axis(axis)
    if isinstance(ax, int):
        ax = (ax,)

    def fn(v):
        if ax is None:
            return jnp.squeeze(v)
        keep = [a for a in ax if v.shape[a] == 1]
        return jnp.squeeze(v, axis=tuple(keep)) if keep else v

    return apply(fn, _t(x), op_name="squeeze")


def unsqueeze(x, axis, name=None):
    ax = _norm_axis(axis)
    if isinstance(ax, int):
        ax = (ax,)
    def fn(v):
        for a in sorted(ax):
            v = jnp.expand_dims(v, a)
        return v
    return apply(fn, _t(x), op_name="unsqueeze")


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def fn(v):
        nd = v.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = v.shape[:s] + (-1,) + v.shape[e + 1:]
        return jnp.reshape(v, new_shape)
    return apply(fn, _t(x), op_name="flatten")


def concat(x: Sequence, axis=0, name=None):
    tensors = [_t(e) for e in x]
    axis = int(unwrap(axis)) if isinstance(axis, Tensor) else int(axis)
    return apply(lambda *vs: jnp.concatenate(vs, axis=axis), *tensors, op_name="concat")


def stack(x: Sequence, axis=0, name=None):
    tensors = [_t(e) for e in x]
    return apply(lambda *vs: jnp.stack(vs, axis=axis), *tensors, op_name="stack")


def split(x, num_or_sections, axis=0, name=None):
    axis = int(unwrap(axis)) if isinstance(axis, Tensor) else int(axis)

    def fn(v):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(v, num_or_sections, axis=axis))
        sections = list(num_or_sections)
        total = v.shape[axis]
        known = builtins.sum(s for s in sections if s != -1)
        sections = [s if s != -1 else total - known for s in sections]
        idx = np.cumsum(sections)[:-1].tolist()
        return tuple(jnp.split(v, idx, axis=axis))

    return list(apply(fn, _t(x), op_name="split"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


def unbind(x, axis=0, name=None):
    n = _t(x).shape[axis]
    return list(apply(lambda v: tuple(jnp.moveaxis(v, axis, 0)[i] for i in range(n)),
                      _t(x), op_name="unbind"))


def tile(x, repeat_times, name=None):
    reps = [int(r) for r in repeat_times]
    return apply(lambda v: jnp.tile(v, reps), _t(x), op_name="tile")


def expand(x, shape, name=None):
    shape = [int(s) for s in shape]

    def fn(v):
        tgt = list(shape)
        src = list(v.shape)
        # paddle expand: -1 keeps dim
        off = len(tgt) - len(src)
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = src[i - off] if i >= off else 1
        return jnp.broadcast_to(v, tgt)

    return apply(fn, _t(x), op_name="expand")


broadcast_to = expand


def expand_as(x, y, name=None):
    return expand(x, _t(y).shape)


def flip(x, axis, name=None):
    ax = _norm_axis(axis)
    return apply(lambda v: jnp.flip(v, axis=ax), _t(x), op_name="flip")


def roll(x, shifts, axis=None, name=None):
    return apply(lambda v: jnp.roll(v, shifts, axis=axis), _t(x), op_name="roll")


def repeat_interleave(x, repeats, axis=None, name=None):
    return apply(lambda v: jnp.repeat(v, repeats, axis=axis), _t(x), op_name="repeat_interleave")


def tril(x, diagonal=0, name=None):
    return apply(lambda v: jnp.tril(v, k=diagonal), _t(x), op_name="tril")


def triu(x, diagonal=0, name=None):
    return apply(lambda v: jnp.triu(v, k=diagonal), _t(x), op_name="triu")


def diag(x, offset=0, padding_value=0, name=None):
    def fn(v):
        if v.ndim == 1:
            out = jnp.diag(v, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(v), k=offset) == 0
                out = jnp.where(mask, padding_value, out)
            return out
        return jnp.diagonal(v, offset=offset)
    return apply(fn, _t(x), op_name="diag")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2),
                 _t(x), op_name="diagonal")


def kron(x, y, name=None):
    return apply(jnp.kron, _t(x), _t(y), op_name="kron")


def moveaxis(x, source, destination, name=None):
    return apply(lambda v: jnp.moveaxis(v, source, destination), _t(x), op_name="moveaxis")


def as_strided(x, shape, stride, offset=0, name=None):
    raise NotImplementedError("as_strided has no XLA analog; use reshape/slice ops")


# ---------------------------------------------------------------------------
# indexing / search
# ---------------------------------------------------------------------------
def gather(x, index, axis=0, name=None):
    return apply(lambda v, i: jnp.take(v, i.astype(jnp.int32), axis=axis),
                 _t(x), _t(index), op_name="gather")


def gather_nd(x, index, name=None):
    def fn(v, idx):
        idx = idx.astype(jnp.int32)
        return v[tuple(jnp.moveaxis(idx, -1, 0))]
    return apply(fn, _t(x), _t(index), op_name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(v, i, u):
        i = i.astype(jnp.int32)
        if overwrite:
            return v.at[i].set(u)
        return v.at[i].add(u)
    return apply(fn, _t(x), _t(index), _t(updates), op_name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    def fn(v, i, u):
        i = i.astype(jnp.int32)
        return v.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return apply(fn, _t(x), _t(index), _t(updates), op_name="scatter_nd_add")


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis=axis)


def index_sample(x, index):
    def fn(v, i):
        i = i.astype(jnp.int32)
        rows = jnp.arange(v.shape[0])[:, None]
        return v[rows, i]
    return apply(fn, _t(x), _t(index), op_name="index_sample")


def take_along_axis(arr, indices, axis, name=None):
    return apply(lambda v, i: jnp.take_along_axis(v, i.astype(jnp.int32), axis=axis),
                 _t(arr), _t(indices), op_name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def fn(v, i, u):
        i = i.astype(jnp.int32)
        idx = [jnp.arange(s).reshape([-1 if k == d else 1 for k in range(v.ndim)])
               for d, s in enumerate(i.shape)]
        idx[axis] = i
        if reduce == "add":
            return v.at[tuple(idx)].add(u)
        if reduce == "multiply":
            return v.at[tuple(idx)].multiply(u)
        return v.at[tuple(idx)].set(u)
    return apply(fn, _t(arr), _t(indices), _t(values), op_name="put_along_axis")


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return apply(lambda c, a, b: jnp.where(c, a, b), _t(condition), _t(x), _t(y),
                 op_name="where")


def nonzero(x, as_tuple=False):
    # dynamic output shape: host-side only (parity with reference's CPU sync)
    v = np.asarray(unwrap(_t(x)))
    nz = np.nonzero(v)
    if as_tuple:
        return tuple(Tensor(z.astype(np.int64)) for z in nz)
    return Tensor(np.stack(nz, axis=-1).astype(np.int64))


def masked_select(x, mask, name=None):
    v = np.asarray(unwrap(_t(x)))
    m = np.asarray(unwrap(_t(mask))).astype(bool)
    return Tensor(v[m])


def masked_fill(x, mask, value, name=None):
    val = unwrap(value) if isinstance(value, Tensor) else value
    return apply(lambda v, m: jnp.where(m, val, v), _t(x), _t(mask), op_name="masked_fill")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    def fn(v):
        ax = axis % v.ndim
        vm = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vm, k)
        else:
            vals, idx = jax.lax.top_k(-vm, k)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax).astype(canonical_dtype("int64")))
    return apply(fn, _t(x), op_name="topk")


def sort(x, axis=-1, descending=False, name=None):
    def fn(v):
        s = jnp.sort(v, axis=axis)
        return jnp.flip(s, axis=axis) if descending else s
    return apply(fn, _t(x), op_name="sort")


def argsort(x, axis=-1, descending=False, name=None):
    def fn(v):
        i = jnp.argsort(v, axis=axis)
        i = jnp.flip(i, axis=axis) if descending else i
        return i.astype(canonical_dtype("int64"))
    return apply(fn, _t(x), op_name="argsort")


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    d = jnp.int32 if out_int32 else jnp.int64
    return apply(lambda s, v: jnp.searchsorted(s, v, side=side).astype(d),
                 _t(sorted_sequence), _t(values), op_name="searchsorted")


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None,
           dtype="int64", name=None):
    v = np.asarray(unwrap(_t(x)))
    res = np.unique(v, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(Tensor(r) for r in res)
    return Tensor(res)


def one_hot(x, num_classes, name=None):
    return apply(lambda v: jax.nn.one_hot(v.astype(jnp.int32), num_classes),
                 _t(x), op_name="one_hot")


def bincount(x, weights=None, minlength=0, name=None):
    if weights is not None:
        return apply(lambda v, w: jnp.bincount(v.astype(jnp.int32), w, minlength=minlength,
                                               length=None),
                     _t(x), _t(weights), op_name="bincount")
    v = np.asarray(unwrap(_t(x)))
    return Tensor(np.bincount(v, minlength=minlength))


# ---------------------------------------------------------------------------
# comparisons returning scalars / misc
# ---------------------------------------------------------------------------
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                 _t(x), _t(y), op_name="allclose")


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                 _t(x), _t(y), op_name="isclose")


def equal_all(x, y, name=None):
    return apply(lambda a, b: jnp.array_equal(a, b), _t(x), _t(y), op_name="equal_all")


def cast(x, dtype):
    return _t(x).astype(dtype)


def increment(x, value=1.0, name=None):
    out = apply(lambda v: v + value, _t(x), op_name="increment")
    if isinstance(x, Tensor):
        x._replace(out)
        return x
    return out


def assign(x, output=None):
    src = _t(x)
    out = apply(lambda v: v + 0, src, op_name="assign")
    if output is not None:
        output._replace(out)
        return output
    return out


def numel(x, name=None):
    return Tensor(np.int64(_t(x).size))


def shape(x):
    return Tensor(np.asarray(_t(x).shape, dtype=np.int32))


def is_tensor(x):
    return isinstance(x, Tensor)


def iinfo(dtype):
    return np.iinfo(np.dtype(convert_dtype(dtype)))


def finfo(dtype):
    d = convert_dtype(dtype)
    return jnp.finfo(d)


# ---------------------------------------------------------------------------
# install Tensor methods + operators
# ---------------------------------------------------------------------------
_METHODS = dict(
    add=add, subtract=subtract, multiply=multiply, divide=divide, pow=pow,
    matmul=matmul, mm=mm, bmm=bmm, dot=dot, t=t, floor_divide=floor_divide,
    remainder=remainder, mod=mod, maximum=maximum, minimum=minimum,
    exp=exp, log=log, sqrt=sqrt, rsqrt=rsqrt, abs=abs, floor=floor, ceil=ceil,
    round=round, sin=sin, cos=cos, tan=tan, tanh=tanh, sigmoid=sigmoid, erf=erf,
    square=square, sign=sign, reciprocal=reciprocal, isnan=isnan, isinf=isinf,
    isfinite=isfinite, scale=scale, clip=clip, lerp=lerp,
    sum=sum, mean=mean, prod=prod, max=max, min=min, all=all, any=any,
    logsumexp=logsumexp, std=std, var=var, argmax=argmax, argmin=argmin,
    cumsum=cumsum, cumprod=cumprod, median=median,
    reshape=reshape, transpose=transpose, squeeze=squeeze, unsqueeze=unsqueeze,
    flatten=flatten, split=split, chunk=chunk, unbind=unbind, tile=tile,
    expand=expand, expand_as=expand_as, broadcast_to=broadcast_to, flip=flip,
    roll=roll, repeat_interleave=repeat_interleave, tril=tril, triu=triu,
    gather=gather, gather_nd=gather_nd, scatter=scatter, index_select=index_select,
    take_along_axis=take_along_axis, put_along_axis=put_along_axis,
    masked_fill=masked_fill, masked_select=masked_select, where=where,
    nonzero=nonzero, topk=topk, sort=sort, argsort=argsort, unique=unique,
    allclose=allclose, isclose=isclose, equal_all=equal_all, equal=equal,
    not_equal=not_equal, greater_than=greater_than, greater_equal=greater_equal,
    less_than=less_than, less_equal=less_equal, logical_and=logical_and,
    logical_or=logical_or, logical_xor=logical_xor, logical_not=logical_not,
    norm=norm, one_hot=one_hot, moveaxis=moveaxis, diagonal=diagonal,
    count_nonzero=count_nonzero, kthvalue=kthvalue, bincount=bincount,
)

for _name, _fn in _METHODS.items():
    setattr(Tensor, _name, _fn)

Tensor.__add__ = lambda self, o: add(self, o)
Tensor.__radd__ = lambda self, o: add(o, self)
Tensor.__sub__ = lambda self, o: subtract(self, o)
Tensor.__rsub__ = lambda self, o: subtract(o, self)
Tensor.__mul__ = lambda self, o: multiply(self, o)
Tensor.__rmul__ = lambda self, o: multiply(o, self)
Tensor.__truediv__ = lambda self, o: divide(self, o)
Tensor.__rtruediv__ = lambda self, o: divide(o, self)
Tensor.__floordiv__ = lambda self, o: floor_divide(self, o)
Tensor.__mod__ = lambda self, o: remainder(self, o)
Tensor.__pow__ = lambda self, o: pow(self, o)
Tensor.__rpow__ = lambda self, o: pow(o, self)
Tensor.__matmul__ = lambda self, o: matmul(self, o)
Tensor.__neg__ = lambda self: neg(self)
Tensor.__abs__ = lambda self: abs(self)
Tensor.__invert__ = lambda self: logical_not(self)
Tensor.__eq__ = lambda self, o: equal(self, o)
Tensor.__ne__ = lambda self, o: not_equal(self, o)
Tensor.__gt__ = lambda self, o: greater_than(self, o)
Tensor.__ge__ = lambda self, o: greater_equal(self, o)
Tensor.__lt__ = lambda self, o: less_than(self, o)
Tensor.__le__ = lambda self, o: less_equal(self, o)
Tensor.__and__ = lambda self, o: logical_and(self, o)
Tensor.__or__ = lambda self, o: logical_or(self, o)
Tensor.__xor__ = lambda self, o: logical_xor(self, o)
