"""Data types.

Parity with the reference's dtype surface (paddle/phi/common/data_type.h,
python/paddle — `paddle.float32` etc., see SURVEY.md §2.1). Dtypes are jax/numpy
dtypes directly; this module provides the paddle-shaped names and helpers.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGRAL = {uint8, int8, int16, int32, int64}

_default_dtype = [jnp.float32]


def convert_dtype(dtype) -> "np.dtype":
    """Normalise str/np/jnp dtype spellings to a canonical numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _STR_TO_DTYPE:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
        dtype = _STR_TO_DTYPE[dtype]
    return jnp.dtype(dtype)


def canonical_dtype(dtype) -> "np.dtype":
    """convert_dtype + x64-aware canonicalization: an int64/float64 request
    maps to the platform default (int32/float32 with x64 disabled) silently,
    instead of tripping jax's truncation warning at every astype."""
    d = convert_dtype(dtype)
    if d is None:
        return None
    import jax

    return jax.dtypes.canonicalize_dtype(d)


def dtype_name(dtype) -> str:
    return jnp.dtype(dtype).name


def is_floating_point(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)


def set_default_dtype(d) -> None:
    _default_dtype[0] = convert_dtype(d)


def get_default_dtype():
    return _default_dtype[0]
