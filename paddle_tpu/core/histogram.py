"""Shared fixed-bucket histogram for metrics sinks.

Subsystem-neutral home (serving AND the training resilience runtime both
export latency histograms; neither should import the other's metrics
stack for it). ``paddle_tpu.serving.metrics`` re-exports these names, so
existing ``serving.metrics.Histogram`` references keep working.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

#: default latency bucket upper bounds (milliseconds)
DEFAULT_BOUNDS_MS: Tuple[float, ...] = (
    0.5, 1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000, 10000)

#: default quantiles reported in summaries and the Prometheus dump
DEFAULT_QUANTILES: Tuple[float, ...] = (0.5, 0.95, 0.99)


class Histogram:
    """Fixed-bucket histogram that also keeps raw samples (ring buffer,
    ``max_samples`` cap) so small/medium runs report *exact* percentiles;
    beyond the cap the ring keeps the most recent window."""

    def __init__(self, bounds: Sequence[float] = DEFAULT_BOUNDS_MS,
                 max_samples: int = 65536):
        self.bounds = tuple(float(b) for b in bounds)
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # +inf bucket
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._samples: List[float] = []
        self._cap = max_samples
        self._sorted: Optional[List[float]] = None   # cache for percentile()

    def record(self, value: float) -> None:
        value = float(value)
        i = 0
        for b in self.bounds:
            if value <= b:
                break
            i += 1
        self.bucket_counts[i] += 1
        if len(self._samples) < self._cap:
            self._samples.append(value)
        else:
            self._samples[self.count % self._cap] = value
        self._sorted = None
        self.count += 1
        self.sum += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)

    def percentile(self, q: float) -> float:
        """Exact percentile over the retained samples (nearest-rank).
        The sort is cached until the next record() so a multi-quantile
        export costs one sort per histogram, not one per quantile — the
        per-token hot path shares the sink's lock with exports."""
        if not self._samples:
            return 0.0
        ordered = self._sorted
        if ordered is None:   # bind locally: a concurrent record() may
            ordered = self._sorted = sorted(self._samples)  # null the cache
        rank = max(0, min(len(ordered) - 1,
                          int(round(q * (len(ordered) - 1)))))
        return ordered[rank]

    def summary(self, quantiles: Sequence[float] = DEFAULT_QUANTILES
                ) -> Dict[str, float]:
        out = {"count": float(self.count), "sum": self.sum,
               "min": self.min or 0.0, "max": self.max or 0.0,
               "mean": (self.sum / self.count) if self.count else 0.0}
        for q in quantiles:
            out[f"p{int(q * 100)}"] = self.percentile(q)
        return out
