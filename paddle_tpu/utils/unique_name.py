"""paddle_tpu.utils.unique_name — reference-parity name generator
(python/paddle/utils/unique_name.py:§0 re-exports the fluid generator;
same counters-per-prefix behaviour, plus the guard context manager)."""

from __future__ import annotations

import contextlib
from collections import defaultdict

__all__ = ["generate", "switch", "guard"]


class _Generator:
    def __init__(self):
        self.ids = defaultdict(int)

    def __call__(self, prefix: str) -> str:
        n = self.ids[prefix]
        self.ids[prefix] += 1
        return f"{prefix}_{n}"


_generator = _Generator()


def generate(key: str) -> str:
    """Next unique name for ``key`` ("fc" -> "fc_0", "fc_1", …)."""
    return _generator(key)


def switch(new_generator=None):
    """Swap the global generator; returns the previous one."""
    global _generator
    old = _generator
    _generator = new_generator if new_generator is not None else _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Fresh name scope within the context (reference guard)."""
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
