"""paddle_tpu.utils.dlpack — zero-copy tensor interchange.

Reference: python/paddle/utils/dlpack.py:§0. jax arrays speak the
dlpack protocol natively (``__dlpack__``), so interchange with torch /
numpy / cupy is the standard-protocol path rather than the reference's
handwritten capsule plumbing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor → DLPack-protocol carrier.

    Returns an object implementing ``__dlpack__``/``__dlpack_device__``
    (the modern protocol every consumer's ``from_dlpack`` accepts —
    torch, numpy, cupy, jax). The reference hands back a raw legacy
    capsule; jax dropped raw-capsule ingestion, and the protocol object
    is strictly more capable (stream-aware, multi-consume)."""
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def from_dlpack(dlpack):
    """Any object speaking the DLPack protocol → Tensor.

    CPU/host producers (torch CPU tensors, numpy arrays) import
    zero-copy onto the host backend; device transfer happens only when
    an op later moves the value.
    """
    if not hasattr(dlpack, "__dlpack__"):
        raise TypeError(
            "from_dlpack needs an object with __dlpack__ (torch tensor, "
            "numpy array, jax array, paddle to_dlpack output); raw legacy "
            "capsules are not ingestible by this jax version")
    return Tensor(jax.dlpack.from_dlpack(dlpack))
