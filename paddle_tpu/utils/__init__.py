"""``paddle_tpu.utils`` — misc public helpers.

Parity with python/paddle/utils/ of the reference: dlpack interchange,
unique_name, try_import, deprecated. The reference's
``utils.cpp_extension`` (CUDA custom-op builds) is scoped out — custom
ops here are Pallas kernels or jax primitives (SURVEY §2.1
custom-device-ABI row); ``utils.download`` is scoped out (zero egress).
"""

from . import dlpack  # noqa: F401
from . import unique_name  # noqa: F401

import functools
import importlib
import warnings

__all__ = ["dlpack", "unique_name", "try_import", "deprecated",
           "run_check"]


def try_import(module_name: str, err_msg: str = None):
    """Import a module by name with the reference's friendlier error."""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        if err_msg is None:
            err_msg = (f"Failed to import {module_name!r}; this optional "
                       "dependency is not installed in the environment")
        raise ImportError(err_msg)


def deprecated(update_to: str = "", since: str = "", reason: str = ""):
    """Decorator marking an API deprecated (warns once per site)."""

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__name__!r} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f"; use {update_to!r} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return decorator


def run_check():
    """Smoke-check the install (reference paddle.utils.run_check): one
    matmul on the available accelerator, one on the 1-device mesh path."""
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    x = jnp.ones((128, 128), jnp.float32)
    y = jax.jit(lambda a: a @ a)(x)
    assert float(y[0, 0]) == 128.0
    print(f"paddle_tpu is installed successfully! device: {dev}")
