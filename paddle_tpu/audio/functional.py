"""paddle_tpu.audio.functional — mel/dct/window helpers.

Reference: python/paddle/audio/functional/{functional,window}.py:§0. All
pure jnp; formulas follow the reference's HTK/Slaney conventions.
"""

from __future__ import annotations

import math
from typing import Optional, Union

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = [
    "hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
    "compute_fbank_matrix", "power_to_db", "create_dct", "get_window",
]


def _t(x):
    return x._value if isinstance(x, Tensor) else x


def hz_to_mel(freq, htk: bool = False):
    """Hz → mel. Slaney (default) is linear below 1 kHz, log above; htk
    is the 2595·log10(1+f/700) form."""
    f = _t(freq)
    scalar = not hasattr(f, "shape") or jnp.asarray(f).shape == ()
    f = jnp.asarray(f, jnp.float32)
    if htk:
        out = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(f / min_log_hz) / logstep,
                        mels)
    return float(out) if scalar and not isinstance(freq, Tensor) \
        else Tensor(out)


def mel_to_hz(mel, htk: bool = False):
    """mel → Hz (inverse of hz_to_mel)."""
    m = _t(mel)
    scalar = not hasattr(m, "shape") or jnp.asarray(m).shape == ()
    m = jnp.asarray(m, jnp.float32)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(m >= min_log_mel,
                        min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                        freqs)
    return float(out) if scalar and not isinstance(mel, Tensor) \
        else Tensor(out)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype: str = "float32"):
    """n_mels mel-spaced frequencies in [f_min, f_max] (Hz)."""
    lo = float(_t(hz_to_mel(f_min, htk=htk)))
    hi = float(_t(hz_to_mel(f_max, htk=htk)))
    mels = jnp.linspace(lo, hi, n_mels, dtype=jnp.float32)
    return Tensor(_t(mel_to_hz(Tensor(mels), htk=htk)).astype(dtype))


def fft_frequencies(sr: int, n_fft: int, dtype: str = "float32"):
    """Center frequencies of rfft bins: linspace(0, sr/2, 1+n_fft//2)."""
    return Tensor(jnp.linspace(0, sr / 2.0, 1 + n_fft // 2,
                               dtype=jnp.float32).astype(dtype))


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: Union[str, float] = "slaney",
                         dtype: str = "float32"):
    """Mel filterbank matrix (n_mels, 1 + n_fft//2) — triangular filters
    between successive mel frequencies (reference compute_fbank_matrix)."""
    if f_max is None:
        f_max = float(sr) / 2
    fftfreqs = _t(fft_frequencies(sr, n_fft))
    mel_f = _t(mel_frequencies(n_mels + 2, f_min=f_min, f_max=f_max,
                               htk=htk))
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]    # (n_mels+2, n_bins)

    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))

    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    elif isinstance(norm, (int, float)):
        norms = jnp.linalg.norm(weights, ord=norm, axis=-1, keepdims=True)
        weights = weights / jnp.maximum(norms, 1e-10)
    return Tensor(weights.astype(dtype))


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    """Power → decibels with amin flooring and optional top_db clamp."""
    if amin <= 0:
        raise ValueError("amin must be strictly positive")
    if ref_value <= 0:
        raise ValueError("ref_value must be strictly positive")
    x = jnp.asarray(_t(spect))
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        if top_db < 0:
            raise ValueError("top_db must be non-negative")
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return Tensor(log_spec)


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho",
               dtype: str = "float32"):
    """DCT-II matrix (n_mels, n_mfcc) for MFCC (reference create_dct)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm is None:
        dct = dct * 2.0
    elif norm == "ortho":
        scale = jnp.full((n_mfcc,), math.sqrt(2.0 / n_mels))
        scale = scale.at[0].set(math.sqrt(1.0 / n_mels))
        dct = dct * scale[None, :]
    else:
        raise ValueError(f"unsupported norm: {norm!r}")
    return Tensor(dct.astype(dtype))


def get_window(window: Union[str, tuple], win_length: int,
               fftbins: bool = True, dtype: str = "float32"):
    """Window by name — hann/hamming/blackman/bartlett/kaiser(beta)/
    gaussian(std)/general_gaussian(p, sig)/exponential(center, tau)/
    triang/bohman. Of the reference's set only ``taylor`` is absent
    (sidelobe-design iteration, named in the unsupported error).
    ``fftbins=True`` gives the periodic form (symmetric window of N+1
    truncated to N)."""
    if isinstance(window, tuple):
        name, *args = window
    else:
        name, args = window, []
    n = win_length + 1 if fftbins else win_length
    i = jnp.arange(n, dtype=jnp.float32)
    if name == "hann":
        w = 0.5 - 0.5 * jnp.cos(2 * math.pi * i / (n - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * math.pi * i / (n - 1))
    elif name == "blackman":
        a = 2 * math.pi * i / (n - 1)
        w = 0.42 - 0.5 * jnp.cos(a) + 0.08 * jnp.cos(2 * a)
    elif name == "bartlett":
        w = 1.0 - jnp.abs(2.0 * i / (n - 1) - 1.0)
    elif name == "triang":
        # scipy triang: no zero endpoints
        if n % 2 == 0:
            w = jnp.where(i < n / 2, (2 * i + 1) / n, (2 * (n - i) - 1) / n)
        else:
            w = 1.0 - jnp.abs(i - (n - 1) / 2.0) / ((n + 1) / 2.0)
    elif name == "bohman":
        x = jnp.abs(2.0 * i / (n - 1) - 1.0)
        w = (1 - x) * jnp.cos(math.pi * x) + jnp.sin(math.pi * x) / math.pi
        w = jnp.where(x >= 1.0, 0.0, w)
    elif name == "kaiser":
        beta = float(args[0]) if args else 12.0
        x = 2.0 * i / (n - 1) - 1.0
        import jax.scipy.special  # i0 lives here

        w = jax.scipy.special.i0(beta * jnp.sqrt(jnp.maximum(
            0.0, 1 - x * x))) / jax.scipy.special.i0(jnp.asarray(beta))
    elif name == "gaussian":
        std = float(args[0]) if args else 1.0
        x = i - (n - 1) / 2.0
        w = jnp.exp(-0.5 * (x / std) ** 2)
    elif name == "general_gaussian":
        p = float(args[0]) if args else 1.0
        sig = float(args[1]) if len(args) > 1 else 1.0
        x = i - (n - 1) / 2.0
        w = jnp.exp(-0.5 * jnp.abs(x / sig) ** (2 * p))
    elif name == "exponential":
        center = args[0] if args else None
        tau = float(args[1]) if len(args) > 1 else 1.0
        c = (n - 1) / 2.0 if center is None else float(center)
        w = jnp.exp(-jnp.abs(i - c) / tau)
    else:
        raise ValueError(
            f"unsupported window: {name!r} (taylor is the one reference "
            "window not implemented; the rest are listed in the docstring)")
    if fftbins:
        w = w[:-1]
    return Tensor(w.astype(dtype))


# needed by get_window('kaiser') at import sites that jit
import jax  # noqa: E402
