"""paddle_tpu.audio.features — Spectrogram / MelSpectrogram /
LogMelSpectrogram / MFCC layers.

Reference: python/paddle/audio/features/layers.py:§0. Each is an
``nn.Layer`` whose forward is pure jnp (stft → |·|^power → fbank → dct),
so a feature pipeline jits and fuses with the model that consumes it.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from .. import signal
from ..core.tensor import Tensor
from ..nn import Layer
from . import functional as F

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    """|STFT|^power of a waveform (…, T) → (…, n_fft//2+1, frames)."""

    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None,
                 window: Union[str, tuple] = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = F.get_window(window, self.win_length, fftbins=True, dtype=dtype)
        self.register_buffer("window", w)

    def forward(self, x):
        spec = signal.stft(x, self.n_fft, hop_length=self.hop_length,
                           win_length=self.win_length, window=self.window,
                           center=self.center, pad_mode=self.pad_mode,
                           onesided=True)
        v = jnp.abs(spec._value if isinstance(spec, Tensor) else spec)
        if self.power != 1.0:
            v = v ** self.power
        return Tensor(v)


class MelSpectrogram(Layer):
    """Spectrogram projected through a mel filterbank:
    (…, n_mels, frames)."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None,
                 window: Union[str, tuple] = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0,
                 f_max: Optional[float] = None, htk: bool = False,
                 norm: Union[str, float] = "slaney",
                 dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(
            n_fft=n_fft, hop_length=hop_length, win_length=win_length,
            window=window, power=power, center=center, pad_mode=pad_mode,
            dtype=dtype)
        fb = F.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype)
        self.register_buffer("fbank_matrix", fb)

    def forward(self, x):
        spec = self._spectrogram(x)
        mel = jnp.matmul(self.fbank_matrix._value, spec._value)
        return Tensor(mel)


class LogMelSpectrogram(Layer):
    """power_to_db of the mel spectrogram."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None,
                 window: Union[str, tuple] = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0,
                 f_max: Optional[float] = None, htk: bool = False,
                 norm: Union[str, float] = "slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 dtype: str = "float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length,
            win_length=win_length, window=window, power=power,
            center=center, pad_mode=pad_mode, n_mels=n_mels, f_min=f_min,
            f_max=f_max, htk=htk, norm=norm, dtype=dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return F.power_to_db(mel, ref_value=self.ref_value, amin=self.amin,
                             top_db=self.top_db)


class MFCC(Layer):
    """DCT of the log-mel spectrogram: (…, n_mfcc, frames)."""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None,
                 window: Union[str, tuple] = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0,
                 f_max: Optional[float] = None, htk: bool = False,
                 norm: Union[str, float] = "slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 dtype: str = "float32"):
        super().__init__()
        if n_mfcc > n_mels:
            raise ValueError("n_mfcc cannot be larger than n_mels")
        self._log_melspectrogram = LogMelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length,
            win_length=win_length, window=window, power=power,
            center=center, pad_mode=pad_mode, n_mels=n_mels, f_min=f_min,
            f_max=f_max, htk=htk, norm=norm, ref_value=ref_value,
            amin=amin, top_db=top_db, dtype=dtype)
        self.register_buffer("dct_matrix",
                             F.create_dct(n_mfcc, n_mels, dtype=dtype))

    def forward(self, x):
        logmel = self._log_melspectrogram(x)._value
        # (…, n_mels, frames) x (n_mels, n_mfcc) over the mel axis
        mfcc = jnp.einsum("...mf,mk->...kf", logmel,
                          self.dct_matrix._value)
        return Tensor(mfcc)
