"""``paddle_tpu.audio`` — audio feature extraction.

Parity with python/paddle/audio/ of the reference (SURVEY.md §2 L7 API
long tail): ``functional`` (mel scales, fbank matrices, dct, windows,
power_to_db) and ``features`` (Spectrogram / MelSpectrogram /
LogMelSpectrogram / MFCC layers). Everything is jnp on top of
paddle_tpu.signal's stft, so features jit and run on device — the
reference computes these with its own kernels on CPU/GPU.

The reference's ``audio.backends`` (soundfile/wave I/O) is host-side by
nature; a stdlib-``wave`` WAV loader is provided and anything beyond
16/32-bit PCM WAV raises with a pointer at the optional deps.
"""

from . import functional  # noqa: F401
from . import features  # noqa: F401
from . import backends  # noqa: F401

__all__ = ["functional", "features", "backends"]
