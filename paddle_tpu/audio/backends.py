"""paddle_tpu.audio.backends — host-side WAV I/O.

Reference: python/paddle/audio/backends/:§0 (wave_backend + optional
soundfile). Audio file I/O is inherently host-side; this backend covers
16/32-bit PCM WAV through the stdlib ``wave`` module (the reference's
no-dependency default backend does the same) and names the limitation
for everything else.
"""

from __future__ import annotations

import wave
from typing import Tuple

import numpy as np

from ..core.tensor import Tensor

__all__ = ["load", "save", "info", "list_available_backends",
           "get_current_backend", "set_backend"]


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name: str):
    if backend_name != "wave_backend":
        raise ValueError(
            f"backend {backend_name!r} unavailable: only the stdlib "
            "wave_backend is bundled (this environment has no soundfile)")


def info(filepath: str):
    """Metadata (sample_rate, num_channels, num_frames, bits_per_sample)."""
    with wave.open(filepath, "rb") as f:
        class _Info:
            sample_rate = f.getframerate()
            num_channels = f.getnchannels()
            num_frames = f.getnframes()
            bits_per_sample = f.getsampwidth() * 8
        return _Info()


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True
         ) -> Tuple[Tensor, int]:
    """Load a PCM WAV file → (waveform Tensor, sample_rate). Normalized
    float32 in [-1, 1] by default; (channels, time) when channels_first."""
    with wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    if width == 2:
        data = np.frombuffer(raw, dtype=np.int16)
        scale = 1 << 15
    elif width == 4:
        data = np.frombuffer(raw, dtype=np.int32)
        scale = 1 << 31
    elif width == 1:
        data = np.frombuffer(raw, dtype=np.uint8).astype(np.int16) - 128
        scale = 1 << 7
    else:
        raise ValueError(f"unsupported PCM sample width {width} bytes; "
                         "wave_backend reads 8/16/32-bit PCM WAV")
    data = data.reshape(-1, nch)
    if normalize:
        data = data.astype(np.float32) / scale
    if channels_first:
        data = data.T
    return Tensor(np.ascontiguousarray(data)), sr


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_16", bits_per_sample: int = 16):
    """Save a waveform Tensor to 16-bit PCM WAV."""
    if bits_per_sample != 16 or encoding != "PCM_16":
        raise ValueError("wave_backend writes PCM_16 only")
    x = np.asarray(src._value if isinstance(src, Tensor) else src)
    if x.ndim == 1:
        x = x[None, :]
    if not channels_first:
        x = x.T
    if np.issubdtype(x.dtype, np.floating):
        x = np.clip(x, -1.0, 1.0)
        x = (x * ((1 << 15) - 1)).astype(np.int16)
    with wave.open(filepath, "wb") as f:
        f.setnchannels(x.shape[0])
        f.setsampwidth(2)
        f.setframerate(sample_rate)
        f.writeframes(np.ascontiguousarray(x.T).tobytes())
