"""ERNIE/BERT-style bidirectional encoder family.

Workload #3's encoder side (SURVEY.md §2.2: fused_attention +
fused_feedforward are "used by ERNIE/GPT"): post-LN transformer encoder
built from the incubate FusedMultiHeadAttention (causal=False) and
FusedFeedForward blocks, with word+position+token-type embeddings, pooler,
and masked-LM / sequence-classification heads. Surface follows the
reference model zoo's ErnieModel/ErnieForSequenceClassification.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import apply
from ..incubate.nn.layer.fused_transformer import (
    FusedFeedForward, FusedMultiHeadAttention)
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.common_layers import LayerNorm, Linear
from ..nn.layer import Layer, LayerList


@dataclass
class ErnieConfig:
    vocab_size: int = 18000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_epsilon: float = 1e-12
    activation: str = "gelu"
    pad_token_id: int = 0


def ernie_tiny(**over) -> ErnieConfig:
    base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, intermediate_size=64,
                max_position_embeddings=64, type_vocab_size=2)
    base.update(over)
    return ErnieConfig(**base)


class ErnieEmbeddings(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        mk = lambda shape: self.create_parameter(
            shape, default_initializer=I.Normal(0.0, 0.02))
        self.word_embeddings = mk((config.vocab_size, config.hidden_size))
        self.position_embeddings = mk(
            (config.max_position_embeddings, config.hidden_size))
        self.token_type_embeddings = mk(
            (config.type_vocab_size, config.hidden_size))
        self.layer_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        pos = (position_ids._value if hasattr(position_ids, "_value")
               else position_ids)

        def fn(ids, tt, we, pe, te):
            s = ids.shape[-1]
            if pos is None:
                p = pe[None, :s]
            else:
                p = jnp.take(pe, pos.astype(jnp.int32), axis=0)
            return (jnp.take(we, ids.astype(jnp.int32), axis=0)
                    + p
                    + jnp.take(te, tt.astype(jnp.int32), axis=0))

        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros(tuple(input_ids.shape),
                                              jnp.int32))
        x = apply(fn, input_ids, token_type_ids, self.word_embeddings,
                  self.position_embeddings, self.token_type_embeddings,
                  op_name="ernie_embeddings")
        return self.layer_norm(x)


class ErnieEncoderLayer(Layer):
    """Post-LN encoder block over the fused attention/FFN ops."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.self_attn = FusedMultiHeadAttention(
            config.hidden_size, config.num_attention_heads,
            normalize_before=False, epsilon=config.layer_norm_epsilon)
        self.ffn = FusedFeedForward(
            config.hidden_size, config.intermediate_size,
            activation=config.activation, normalize_before=False,
            epsilon=config.layer_norm_epsilon)

    def forward(self, x, attn_mask=None, seg_ids=None):
        x = self.self_attn(x, attn_mask=attn_mask, causal=False,
                           seg_ids=seg_ids)
        return self.ffn(x)


class ErniePooler(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.dense = Linear(config.hidden_size, config.hidden_size)

    def forward(self, hidden):
        return F.tanh(self.dense(hidden[:, 0]))


def _attention_mask_from_ids(input_ids, pad_token_id: int):
    """(B, S) token ids -> additive (B, 1, 1, S) mask (-1e4 at pads)."""
    def fn(ids):
        pad = (ids == pad_token_id)
        return jnp.where(pad, -1e4, 0.0)[:, None, None, :].astype(jnp.float32)
    return apply(fn, input_ids, op_name="ernie_attn_mask")


def packed_position_ids(segment_ids):
    """(B, S) segment ids -> (B, S) positions restarting at 0 per segment
    (pads get 0). The packed-batch analogue of the reference's implicit
    arange positions."""
    def fn(seg):
        s = seg.shape[-1]
        idx = jnp.arange(s, dtype=jnp.int32)[None, :]
        is_start = jnp.concatenate(
            [jnp.ones_like(seg[:, :1], bool),
             seg[:, 1:] != seg[:, :-1]], axis=-1)
        start = jax.lax.cummax(jnp.where(is_start, idx, 0), axis=seg.ndim - 1)
        return jnp.where(seg < 0, 0, idx - start).astype(jnp.int32)
    return apply(fn, segment_ids, op_name="packed_position_ids")


class ErnieModel(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.embeddings = ErnieEmbeddings(config)
        self.encoder = LayerList([ErnieEncoderLayer(config)
                                  for _ in range(config.num_hidden_layers)])
        self.pooler = ErniePooler(config)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                segment_ids=None):
        """``segment_ids`` (B, S) turns on sequence-packed mode: each row
        holds several sequences back to back (negative ids = pad), tokens
        attend only within their own segment via the segment-masked flash
        kernel, and positions restart per segment. Mutually exclusive with
        ``attention_mask``."""
        if segment_ids is not None:
            if attention_mask is not None:
                raise ValueError(
                    "segment_ids and attention_mask are mutually exclusive")
            pos = packed_position_ids(segment_ids)
            x = self.embeddings(input_ids, token_type_ids, position_ids=pos)
            for layer in self.encoder:
                x = layer(x, seg_ids=segment_ids)
            return x, self.pooler(x)
        if attention_mask is None:
            attention_mask = _attention_mask_from_ids(
                input_ids, self.config.pad_token_id)
        x = self.embeddings(input_ids, token_type_ids)
        for layer in self.encoder:
            x = layer(x, attn_mask=attention_mask)
        return x, self.pooler(x)


class ErnieForSequenceClassification(Layer):
    def __init__(self, config: ErnieConfig, num_classes: int = 2,
                 dropout: Optional[float] = None):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.classifier = Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.ernie(input_ids, token_type_ids, attention_mask)
        return self.classifier(pooled)


class ErnieForMaskedLM(Layer):
    """MLM head tied to the word embedding."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.ernie = ErnieModel(config)
        self.transform = Linear(config.hidden_size, config.hidden_size)
        self.transform_ln = LayerNorm(config.hidden_size,
                                      epsilon=config.layer_norm_epsilon)
        self.bias = self.create_parameter((config.vocab_size,), is_bias=True)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                segment_ids=None):
        seq, _ = self.ernie(input_ids, token_type_ids, attention_mask,
                            segment_ids=segment_ids)
        h = self.transform_ln(F.gelu(self.transform(seq)))
        from ..core import math_ops as M
        return M.matmul(h, self.ernie.embeddings.word_embeddings,
                        transpose_y=True) + self.bias

    def compute_loss(self, input_ids, labels, token_type_ids=None,
                     segment_ids=None):
        """labels: -100 at unmasked positions (ignore_index). In packed
        mode pass ``segment_ids`` and set labels=-100 at pads."""
        logits = self(input_ids, token_type_ids, segment_ids=segment_ids)
        return F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]),
            labels.reshape([-1]), ignore_index=-100)
